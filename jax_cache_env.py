"""Shared JAX persistent-compilation-cache environment setup.

Mosaic kernel compiles on the remote axon backend run 2-5 minutes EACH
and the fused ResNet-50 train step alone carries ~18 of them, so every
process that might compile for the chip (the bench suite, the on-chip
experiment queue, the capture daemon) must agree on ONE cache so
compiles are paid once per kernel per git state, not once per process.
Measured on v5e (ONCHIP_QUEUE.log r4): first compile 8.6s, second
process 0.2s.

Call set_cache_env() BEFORE jax initialises (setdefault semantics: an
operator override via real env vars wins).
"""
import os

_REPO = os.path.dirname(os.path.abspath(__file__))


def set_cache_env(environ=None):
    """Set the cache env vars on `environ` (default os.environ)."""
    env = os.environ if environ is None else environ
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(_REPO, ".jax_cache"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    return env
