"""paddle_tpu: a TPU-native deep-learning framework.

Capability parity target: PaddlePaddle ~v1.7 (static "fluid" graphs +
imperative dygraph + distributed training); architecture: JAX/XLA/Pallas.
See SURVEY.md at the repo root for the reference layer map this package
rebuilds.

Top-level namespace mirrors the reference's `paddle.fluid` surface:

    import paddle_tpu as fluid
    x = fluid.data("x", [None, 784])
    y = fluid.layers.fc(x, 10)
    exe = fluid.Executor()
    exe.run(fluid.default_startup_program())
"""

from . import _jax_compat  # noqa: F401  — must run before any jax use
from . import flags
from .flags import set_flags, get_flags

from .core import (
    CPUPlace,
    TPUPlace,
    CUDAPlace,
    CUDAPinnedPlace,
    default_place,
    is_compiled_with_tpu,
    device_count,
)

from . import ops  # registers all op kernels
from .framework import (
    Program,
    Variable,
    Parameter,
    default_main_program,
    default_startup_program,
    program_guard,
    name_scope,
    data,
    Executor,
    CompiledProgram,
    BuildStrategy,
    ExecutionStrategy,
    Scope,
    global_scope,
    scope_guard,
    append_backward,
    gradients,
    ParamAttr,
    cpu_places,
    cuda_places,
    cuda_pinned_places,
    in_dygraph_mode,
    is_compiled_with_cuda,
    load_op_library,
    require_version,
    device_guard,
)

# top-level fluid module paths (richer than the framework internals:
# initializer adds init_on_cpu, unique_name adds switch)
from . import initializer
from . import unique_name
from . import backward

from . import analysis  # static Program verifier (FLAGS_static_check)
from . import layers
from . import nets
from . import debugger
from . import average
from . import install_check
from . import model_stat
from . import contrib
from . import (communicator, compiler, data_feeder, evaluator,  # noqa: F401
               executor, input, lod_tensor, log_helper, param_attr,
               parallel_executor)
from .parallel_executor import ParallelExecutor  # noqa: F401
from . import compat  # noqa: F401
from . import incubate  # noqa: F401
from .reader import batch  # noqa: F401
from . import dygraph_grad_clip  # noqa: F401
from .param_attr import WeightNormParamAttr  # noqa: F401
from . import sysconfig
from . import utils
from .lod import (LoDTensor, create_lod_tensor,
                  create_random_int_lodtensor)
from . import optimizer
from . import regularizer
from . import clip
from . import io
from . import reader
from . import dataset
from . import metrics
from . import profiler
from . import monitor
from . import resilience
from . import nn
from . import dygraph
from . import distributed
from . import amp
from . import jit
from . import models
from . import slim
from . import checkpoint
from . import inference
from . import transpiler
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig

from .reader import DataLoader
from .version import full_version as __version__

__all__ = [
    "flags", "set_flags", "get_flags",
    "CPUPlace", "TPUPlace", "CUDAPlace", "CUDAPinnedPlace",
    "default_place", "is_compiled_with_tpu", "device_count",
    "ops", "Program", "Variable", "Parameter",
    "default_main_program", "default_startup_program", "program_guard",
    "name_scope", "data", "Executor", "Scope", "global_scope",
    "scope_guard", "append_backward", "gradients", "ParamAttr",
    "initializer", "unique_name", "backward", "layers", "optimizer",
    "regularizer", "clip", "io", "reader", "dataset", "metrics",
    "profiler", "monitor", "nn", "dygraph", "distributed", "amp", "jit",
    "models",
    "contrib",
    "DataLoader",
]
