"""`paddle.compat` parity (python/paddle/compat.py) — py2/py3 string
shims that 1.x scripts import; on py3 they reduce to the obvious
conversions (the reference's own py3 branches)."""

import math

__all__ = ["long_type", "to_text", "to_bytes", "round",
           "floor_division", "get_exception_message"]

long_type = int


def _convert(obj, conv, inplace):
    if isinstance(obj, list):
        if inplace:
            obj[:] = [_convert(i, conv, False) for i in obj]
            return obj
        return [_convert(i, conv, False) for i in obj]
    if isinstance(obj, set):
        if inplace:
            vals = [_convert(i, conv, False) for i in obj]
            obj.clear()
            obj.update(vals)
            return obj
        return {_convert(i, conv, False) for i in obj}
    return conv(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    # reference semantics: only bytes decode; str passes through and
    # every other type (None, bool, float, ...) is returned UNCHANGED
    return _convert(
        obj, lambda o: o.decode(encoding)
        if isinstance(o, (bytes, bytearray)) else o, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    return _convert(
        obj, lambda o: o.encode(encoding) if isinstance(o, str) else o,
        inplace)


def round(x, d=0):
    """py2-style half-away-from-zero rounding (compat.py round)."""
    if x > 0.0:
        p = 10 ** d
        return float(math.floor((x * p) + math.copysign(0.5, x))) / p
    if x < 0.0:
        p = 10 ** d
        return float(math.ceil((x * p) + math.copysign(0.5, x))) / p
    return math.copysign(0.0, x)


def floor_division(x, y):
    return x // y


def get_exception_message(exc):
    return str(exc)
