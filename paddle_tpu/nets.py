"""Composite network builders — fluid.nets parity.

Parity: /root/reference/python/paddle/fluid/nets.py:28
(simple_img_conv_pool), :138 (img_conv_group), :251 (sequence_conv_pool),
:319 (glu), :360 (scaled_dot_product_attention). Each helper composes
this repo's layer builders; XLA fuses the pipeline (the reference's
motivation for grouping them no longer needs hand care on TPU).
"""

from . import layers


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride, pool_padding=0, pool_type="max",
                         global_pooling=False, conv_stride=1,
                         conv_padding=0, conv_dilation=1, conv_groups=1,
                         param_attr=None, bias_attr=None, act=None,
                         use_cudnn=True):
    """nets.py:28 — conv2d + pool2d."""
    conv_out = layers.conv2d(
        input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr,
        act=act)
    return layers.pool2d(
        conv_out, pool_size=pool_size, pool_type=pool_type,
        pool_stride=pool_stride, pool_padding=pool_padding,
        global_pooling=global_pooling)


def img_conv_group(input, conv_num_filter, pool_size, conv_padding=1,
                   conv_filter_size=3, conv_act=None, param_attr=None,
                   conv_with_batchnorm=False, conv_batchnorm_drop_rate=0.0,
                   pool_stride=1, pool_type="max", use_cudnn=True):
    """nets.py:138 — serial conv(+bn)(+dropout) blocks then one pool (the
    VGG block)."""
    tmp = input
    assert isinstance(conv_num_filter, (list, tuple))

    def expand(v):
        return list(v) if isinstance(v, (list, tuple)) \
            else [v] * len(conv_num_filter)

    conv_padding = expand(conv_padding)
    conv_filter_size = expand(conv_filter_size)
    param_attr = param_attr if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * len(conv_num_filter)
    conv_with_batchnorm = expand(conv_with_batchnorm)
    conv_batchnorm_drop_rate = expand(conv_batchnorm_drop_rate)

    for i, nf in enumerate(conv_num_filter):
        local_act = conv_act if not conv_with_batchnorm[i] else None
        tmp = layers.conv2d(
            tmp, num_filters=nf, filter_size=conv_filter_size[i],
            padding=conv_padding[i], param_attr=param_attr[i],
            act=local_act)
        if conv_with_batchnorm[i]:
            tmp = layers.batch_norm(tmp, act=conv_act)
            rate = conv_batchnorm_drop_rate[i]
            if abs(rate) > 1e-5:
                tmp = layers.dropout(tmp, dropout_prob=rate)
    return layers.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride)


def sequence_conv_pool(input, num_filters, filter_size, lengths=None,
                       param_attr=None, act="sigmoid", pool_type="max",
                       bias_attr=None):
    """nets.py:251 — sequence_conv + sequence_pool. Under the padded+
    lengths ragged design the sequence is [B, T, D] with a lengths
    vector (pass `lengths`; defaults to full length)."""
    conv_out = layers.sequence_conv(
        input, num_filters=num_filters, filter_size=filter_size,
        param_attr=param_attr, bias_attr=bias_attr, act=act,
        lengths=lengths)
    return layers.sequence_pool(conv_out, lengths, pool_type)


def glu(input, dim=-1):
    """nets.py:319 — gated linear unit: split -> sigmoid -> mul."""
    a, b = layers.split(input, num_or_sections=2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """nets.py:360 — multi-head scaled dot-product attention over
    [B, T, D] q/k/v; returns [B, T_q, D_v]."""
    if queries.shape[-1] != keys.shape[-1]:
        raise ValueError("queries and keys need matching hidden size")
    if num_heads < 1:
        raise ValueError("num_heads must be >= 1")
    d = queries.shape[-1]
    if d % num_heads != 0:
        raise ValueError("hidden size must divide num_heads")

    def split_heads(x):
        b = layers.reshape(x, [0, 0, num_heads, x.shape[-1] // num_heads])
        return layers.transpose(b, [0, 2, 1, 3])

    def combine_heads(x):
        t = layers.transpose(x, [0, 2, 1, 3])
        return layers.reshape(t, [0, 0, t.shape[2] * t.shape[3]])

    q = split_heads(queries)
    k = split_heads(keys)
    v = split_heads(values)
    scale = (d // num_heads) ** -0.5
    logits = layers.matmul(q, k, transpose_y=True, alpha=scale)
    weights = layers.softmax(logits)
    if dropout_rate:
        weights = layers.dropout(weights, dropout_prob=dropout_rate)
    return combine_heads(layers.matmul(weights, v))
