"""Fleet filesystem utilities — LocalFS + the HDFSClient interface.

Parity: /root/reference/python/paddle/fluid/incubate/fleet/utils/hdfs.py
(HDFSClient shelling out to `hadoop fs`) and the fleet checkpoint/model
save flows built on it. The portable contract here is the `FS` interface
with a fully working LocalFS (what localhost clusters and tests use);
HDFSClient keeps the reference's method surface and delegates to the
`hadoop` binary when one exists, raising a clear error otherwise (this
image ships no Hadoop).

split_files is the reference's deterministic file-to-trainer assignment
(hdfs.py:396), used by dataset sharding.
"""

import os
import shutil
import subprocess

__all__ = ["FS", "LocalFS", "HDFSClient", "split_files"]


class FS:
    """Interface: the subset of hdfs.py's HDFSClient the fleet flows use."""

    def is_exist(self, path):
        raise NotImplementedError

    def is_dir(self, path):
        raise NotImplementedError

    def is_file(self, path):
        raise NotImplementedError

    def ls(self, path):
        raise NotImplementedError

    def makedirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def rename(self, src, dst, overwrite=False):
        raise NotImplementedError

    def cat(self, path):
        raise NotImplementedError

    def upload(self, dest, local):
        raise NotImplementedError

    def download(self, src, local):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem implementation of the FS contract."""

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def ls(self, path):
        return sorted(os.listdir(path))

    def makedirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst, overwrite=False):
        if os.path.exists(dst):
            if not overwrite:
                raise FileExistsError(dst)
            self.delete(dst)
        os.replace(src, dst)

    def cat(self, path):
        with open(path, "r") as f:
            return f.read()

    def upload(self, dest, local):
        if os.path.isdir(local):
            shutil.copytree(local, dest, dirs_exist_ok=True)
        else:
            os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
            shutil.copy2(local, dest)

    def download(self, src, local):
        self.upload(local, src)


class HDFSClient(FS):
    """hdfs.py:45 surface — shells out to `hadoop fs` like the
    reference. Constructing it without a hadoop binary raises with a
    clear message (no Hadoop in this image; LocalFS is the tested
    path)."""

    def __init__(self, hadoop_home=None, configs=None):
        self._hadoop = (os.path.join(hadoop_home, "bin", "hadoop")
                        if hadoop_home else shutil.which("hadoop"))
        if not self._hadoop or not os.path.exists(self._hadoop):
            raise RuntimeError(
                "HDFSClient needs a hadoop binary (hadoop_home or PATH); "
                "none found in this environment — use LocalFS, or mount "
                "a Hadoop install")
        self._configs = [f"-D{k}={v}"
                         for k, v in (configs or {}).items()]

    def _run(self, *args):
        cmd = [self._hadoop, "fs"] + self._configs + list(args)
        r = subprocess.run(cmd, capture_output=True, text=True)
        return r.returncode, r.stdout, r.stderr

    def is_exist(self, path):
        return self._run("-test", "-e", path)[0] == 0

    def is_dir(self, path):
        return self._run("-test", "-d", path)[0] == 0

    def is_file(self, path):
        return self._run("-test", "-f", path)[0] == 0

    def ls(self, path):
        rc, out, err = self._run("-ls", path)
        if rc != 0:
            raise IOError(err)
        return [line.split()[-1] for line in out.splitlines()
                if line and not line.startswith("Found")]

    def makedirs(self, path):
        self._run("-mkdir", "-p", path)

    def delete(self, path):
        self._run("-rm", "-r", "-f", path)

    def rename(self, src, dst, overwrite=False):
        if overwrite:
            self.delete(dst)
        self._run("-mv", src, dst)

    def cat(self, path):
        rc, out, err = self._run("-cat", path)
        if rc != 0:
            raise IOError(err)
        return out

    def upload(self, dest, local):
        self._run("-put", "-f", local, dest)

    def download(self, src, local):
        self._run("-get", src, local)


def split_files(files, trainer_id, trainers):
    """hdfs.py:396 — deterministic round-robin assignment of input files
    to trainers (sorted first so every rank computes the same split)."""
    if trainer_id >= trainers or trainer_id < 0:
        raise ValueError(f"trainer_id {trainer_id} out of range "
                         f"[0, {trainers})")
    return sorted(files)[trainer_id::trainers]
