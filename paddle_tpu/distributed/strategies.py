"""Distributed training strategies: DGC and LocalSGD.

TPU-native implementations of two reference capabilities that were dead
knobs in round 1:

- **DGC** (deep gradient compression): /root/reference/python/paddle/fluid/
  optimizer.py:1041 DGCMomentumOptimizer + paddle/fluid/framework/details/
  sparse_all_reduce_op_handle.cc. Local momentum correction (u = m*u + g),
  error-feedback accumulation (v += u), per-parameter top-k selection on
  |v|, and an all-reduce of only the selected entries; selected slots are
  cleared from u and v. On TPU the "sparse all-reduce" is a psum of the
  top-k-masked dense tensor: ICI collectives are compiled, not hand-rolled
  NCCL, so the masked psum is the native expression of the same semantics
  (and XLA fuses mask+psum into the backward).
- **LocalSGD**: /root/reference/python/paddle/fluid/transpiler/
  collective.py:270 — every worker takes `local_sgd_steps` independent
  optimizer steps on its own replica, then replicas are averaged. Workers
  = slots of the "dp" mesh axis; each device owns its replica as the
  leading axis of a [ndev, ...] stacked param tree sharded over dp.

Both run as ONE jitted SPMD program over the mesh (shard_map over "dp"),
mirroring the repo-wide inversion of the reference's graph-rewriting
transpilers.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..nn.layers import _swap_params, buffer_dict
from ..nn.parameter import default_rng
from .mesh import default_mesh

__all__ = ["DGCTrainStep", "LocalSGDTrainStep", "dgc_topk_mask"]


def dgc_topk_mask(v, sparsity):
    """Top-k selection mask on |v|: keep the largest (1-sparsity) fraction.

    Default: exact kth value via lax.top_k (an efficient TPU sort).
    Under FLAGS_use_pallas_dgc_topk the threshold instead comes from the
    streaming Pallas histogram kernel (kernels/topk_threshold.py) — one
    data pass, no sort, conservatively keeping >= k elements (the DGC
    paper itself only estimates the threshold)."""
    from .. import flags

    if flags.flag("use_pallas_dgc_topk"):
        from ..kernels.topk_threshold import dgc_topk_mask_pallas

        return dgc_topk_mask_pallas(v, sparsity)
    flat = jnp.abs(v).reshape(-1)
    k = max(1, int(round(flat.shape[0] * (1.0 - sparsity))))
    kth = jax.lax.top_k(flat, k)[0][-1]
    return (jnp.abs(v) >= kth).astype(v.dtype)


class DGCTrainStep:
    """DGC momentum data-parallel train step.

    step = DGCTrainStep(model, loss_fn, mesh, lr=..., momentum=...,
                        sparsity=0.999, rampup_begin_step=0)
    loss = step(x, y)

    Before `rampup_begin_step` global steps the update is plain dense
    momentum DP (reference DGCMomentumOptimizer behavior: dgc kicks in
    after the rampup boundary, optimizer.py:1041).
    """

    def __init__(self, model, loss_fn, mesh=None, lr=0.01, momentum=0.9,
                 sparsity=0.999, rampup_begin_step=0):
        self._model = model
        self._mesh = mesh or default_mesh()
        self._lr = lr
        self._m = momentum
        self._sparsity = sparsity
        self._rampup = int(rampup_begin_step)
        self._state = None  # (u, v, velocity_dense, step_count)
        self._loss_fn = loss_fn

        def _local_grad(params, buffers, rng_key, *batch):
            from ..jit import (_get_buffer, _restore_buffers,
                               _swap_in_buffers)

            def loss_of(ps):
                with _swap_params(model, ps), \
                        default_rng.key_context(rng_key):
                    old = _swap_in_buffers(model, buffers)
                    try:
                        loss = loss_fn(model, *batch)
                        new_buffers = {p: _get_buffer(model, p)
                                       for p in buffers}
                    finally:
                        _restore_buffers(model, old)
                return loss, new_buffers
            return jax.value_and_grad(loss_of, has_aux=True)(params)

        def _step(params, buffers, u, v, vel, count, rng_key, *batch):
            (loss, new_buffers), grads = _local_grad(
                params, buffers, rng_key, *batch)
            loss = jax.lax.pmean(loss, "dp")
            new_buffers = jax.tree.map(
                lambda b: jax.lax.pmean(b, "dp") if jnp.issubdtype(
                    jnp.asarray(b).dtype, jnp.floating) else b,
                new_buffers)
            use_dgc = count >= self._rampup

            def dgc_branch(_):
                def per_param(g, u_, v_):
                    u_n = self._m * u_ + g          # momentum correction
                    v_n = v_ + u_n                  # error feedback accum
                    mask = dgc_topk_mask(v_n, self._sparsity)
                    send = v_n * mask
                    dense = jax.lax.pmean(send, "dp")
                    return dense, u_n * (1 - mask), v_n * (1 - mask)
                out = jax.tree.map(per_param, grads, u, v)
                dense = jax.tree.map(lambda t: t[0], out,
                                     is_leaf=lambda t: isinstance(t, tuple))
                u_n = jax.tree.map(lambda t: t[1], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
                v_n = jax.tree.map(lambda t: t[2], out,
                                   is_leaf=lambda t: isinstance(t, tuple))
                # selected velocity applied directly (u carries momentum)
                p_n = jax.tree.map(lambda p, d: p - self._lr * d,
                                   params, dense)
                return p_n, u_n, v_n, vel

            def dense_branch(_):
                g = jax.tree.map(lambda g_: jax.lax.pmean(g_, "dp"), grads)
                vel_n = jax.tree.map(lambda vl, g_: self._m * vl + g_,
                                     vel, g)
                p_n = jax.tree.map(lambda p, vl: p - self._lr * vl,
                                   params, vel_n)
                return p_n, u, v, vel_n

            params, u, v, vel = jax.lax.cond(use_dgc, dgc_branch,
                                             dense_branch, None)
            return params, new_buffers, u, v, vel, count + 1, loss

        rep = P()
        bspec = P("dp")

        def _sharded(params, buffers, u, v, vel, count, rng_key, *batch):
            return shard_map(
                _step, mesh=self._mesh,
                in_specs=(rep, rep, rep, rep, rep, rep, rep)
                + tuple(bspec for _ in batch),
                out_specs=(rep, rep, rep, rep, rep, rep, rep),
                check_vma=False,
            )(params, buffers, u, v, vel, count, rng_key, *batch)

        self._jit = jax.jit(_sharded, donate_argnums=(0, 1, 2, 3, 4))

    def __call__(self, *batch):
        from ..nn.layers import buffer_dict

        params = {n: p.value for n, p in self._model.named_parameters()
                  if p.trainable}
        buffers = buffer_dict(self._model)
        if self._state is None:
            zeros = jax.tree.map(jnp.zeros_like, params)
            self._state = (zeros,
                           jax.tree.map(jnp.zeros_like, params),
                           jax.tree.map(jnp.zeros_like, params),
                           jnp.zeros((), jnp.int32))
        u, v, vel, count = self._state
        batch = tuple(jnp.asarray(b) for b in batch)
        params, buffers, u, v, vel, count, loss = self._jit(
            params, buffers, u, v, vel, count, default_rng.next_key(),
            *batch)
        self._state = (u, v, vel, count)
        named = dict(self._model.named_parameters())
        for n, val in params.items():
            named[n].value = val
        for path, val in buffers.items():
            self._model._set_buffer_by_path(path, val)
        return loss


class LocalSGDTrainStep:
    """LocalSGD data-parallel train step (collective.py:270 parity).

    Each dp slot owns an independent replica (leading [ndev] axis sharded
    over "dp"); every `local_sgd_steps` global steps the replicas are
    averaged with a pmean. local_sgd_steps=1 is exactly synchronous DP
    for SGD-family optimizers.
    """

    def __init__(self, model, optimizer, loss_fn, mesh=None,
                 local_sgd_steps=1):
        self._model = model
        self._optimizer = optimizer
        self._mesh = mesh or default_mesh()
        self._n = int(np.prod([self._mesh.shape[a]
                               for a in ("dp",) if a in self._mesh.shape]))
        self._k = int(local_sgd_steps)
        self._state = None  # (params_stacked, opt_state_stacked, count)

        def _step(params, buffers, opt_state, count, rng_key, *batch):
            from ..jit import (_get_buffer, _restore_buffers,
                               _swap_in_buffers)

            # params: per-device block [1, ...] -> local replica
            local = jax.tree.map(lambda p: p[0], params)
            local_buf = jax.tree.map(lambda b: b[0], buffers)

            def loss_of(ps):
                with _swap_params(model, ps), \
                        default_rng.key_context(rng_key):
                    old = _swap_in_buffers(model, local_buf)
                    try:
                        loss = loss_fn(model, *batch)
                        new_buf = {p: _get_buffer(model, p)
                                   for p in local_buf}
                    finally:
                        _restore_buffers(model, old)
                return loss, new_buf

            (loss, new_buf), grads = jax.value_and_grad(
                loss_of, has_aux=True)(local)
            loss = jax.lax.pmean(loss, "dp")
            new_local, new_opt = optimizer.functional_update(
                grads, jax.tree.map(lambda s: s[0], opt_state), local)
            count = count + 1
            sync = (count % self._k) == 0

            def maybe_avg(p):
                if not jnp.issubdtype(jnp.asarray(p).dtype, jnp.floating):
                    return p
                return jax.lax.cond(
                    sync, lambda q: jax.lax.pmean(q, "dp"), lambda q: q, p)

            new_local = jax.tree.map(maybe_avg, new_local)
            new_buf = jax.tree.map(maybe_avg, new_buf)
            return (jax.tree.map(lambda p: p[None], new_local),
                    jax.tree.map(lambda b: b[None], new_buf),
                    jax.tree.map(lambda s: s[None], new_opt),
                    count, loss)

        rep = P()
        stacked = P("dp")
        bspec = P("dp")

        def _sharded(params, buffers, opt_state, count, rng_key, *batch):
            return shard_map(
                _step, mesh=self._mesh,
                in_specs=(stacked, stacked, stacked, rep, rep)
                + tuple(bspec for _ in batch),
                out_specs=(stacked, stacked, stacked, rep, rep),
                check_vma=False,
            )(params, buffers, opt_state, count, rng_key, *batch)

        self._jit = jax.jit(_sharded, donate_argnums=(0, 1, 2))

    def _stack(self, tree):
        sharding = NamedSharding(self._mesh, P("dp"))
        return jax.tree.map(
            lambda p: jax.device_put(
                jnp.broadcast_to(p[None], (self._n,) + p.shape), sharding),
            tree)

    def __call__(self, *batch):
        from ..nn.layers import buffer_dict

        if self._state is None:
            params = {n: p.value for n, p in
                      self._model.named_parameters() if p.trainable}
            opt_state = self._optimizer.init_state(params)
            self._state = (self._stack(params),
                           self._stack(buffer_dict(self._model)),
                           self._stack(opt_state),
                           jnp.zeros((), jnp.int32))
        params_st, buf_st, opt_st, count = self._state
        batch = tuple(jnp.asarray(b) for b in batch)
        params_st, buf_st, opt_st, count, loss = self._jit(
            params_st, buf_st, opt_st, count, default_rng.next_key(),
            *batch)
        self._state = (params_st, buf_st, opt_st, count)
        # reflect replica 0 into the model (replicas coincide after sync)
        named = dict(self._model.named_parameters())
        for n, val in jax.tree.map(lambda p: p[0],
                                   dict(params_st)).items():
            named[n].value = val
        for path, val in jax.tree.map(lambda b: b[0],
                                      dict(buf_st)).items():
            self._model._set_buffer_by_path(path, val)
        return loss
