"""Pipeline parallelism: differentiable GPipe schedule over a "pp" mesh axis.

TPU-native replacement for the reference's pipeline trainer
(/root/reference/paddle/fluid/framework/pipeline_trainer.cc,
device_worker.h:325 SectionWorker, driven by PipelineOptimizer
python/paddle/fluid/optimizer.py:3413): where the reference moves Scopes
through blocking queues between per-section threads, here the WHOLE
schedule is one compiled SPMD program. Per-stage weights are stacked on a
leading stage axis and sharded over "pp"; each schedule tick every device
runs its stage and ppermutes the activation to its ring neighbor (ICI
hop). The bubble is the standard (n_stages - 1) ticks.

Because the schedule is just scan + ppermute + masked updates, jax.grad
differentiates through it — backward pipelining falls out of the
transpose of ppermute, with jax.checkpoint bounding activation memory to
the stage boundaries.

Composition: batch may additionally be sharded over "dp" (specs below);
tensor parallelism composes by NamedSharding on the stacked weights'
trailing dims as usual.
"""


import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["gpipe", "stack_block_params", "build_gpt_pipeline",
           "pipeline_dryrun"]


def gpipe(stage_fn, mesh, num_microbatches, axis_name="pp",
          batch_axis="dp", remat=True):
    """Build fn(stacked_params, x) -> y running the GPipe schedule.

    stage_fn(stage_params, h) -> h': one pipeline stage; h' must have
    h's shape/dtype (transformer-block shape preservation).
    stacked_params: pytree whose leaves have a leading n_stages dim.
    x: [B, ...] activations; B must divide into num_microbatches.
    """
    n_stages = mesh.shape[axis_name]
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def body(params_loc, x_loc):
        my = jax.tree.map(lambda l: l[0], params_loc)     # this stage's slice
        i = jax.lax.axis_index(axis_name)
        m = num_microbatches
        mb = x_loc.shape[0] // m
        xs = x_loc.reshape(m, mb, *x_loc.shape[1:])
        out_buf = jnp.zeros_like(xs)
        h0 = jnp.zeros_like(xs[0])
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        is_first = i == 0
        is_last = i == n_stages - 1

        def tick(carry, t):
            h_recv, out_buf = carry
            x_t = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            h_in = jnp.where(is_first, x_t, h_recv)
            h_out = stage_fn(my, h_in)
            slot = t - (n_stages - 1)
            valid = (slot >= 0) & (slot < m) & is_last
            cl = jnp.clip(slot, 0, m - 1)
            cur = jax.lax.dynamic_index_in_dim(out_buf, cl, 0,
                                               keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(valid, h_out, cur), cl, 0)
            h_recv = jax.lax.ppermute(h_out, axis_name, perm)
            return (h_recv, out_buf), None

        ticks = jnp.arange(m + n_stages - 1)
        (_, out_buf), _ = jax.lax.scan(tick, (h0, out_buf), ticks)
        # only the last stage holds real outputs; psum of the masked
        # buffer replicates them across the pp axis
        out_buf = jnp.where(is_last, out_buf, 0.0)
        out_buf = jax.lax.psum(out_buf, axis_name)
        return out_buf.reshape(x_loc.shape)

    has_dp = batch_axis and batch_axis in mesh.shape
    x_spec = P(batch_axis) if has_dp else P()
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(axis_name), x_spec),
        out_specs=x_spec,
        check_vma=False)
    return fn


def stack_block_params(block_param_dicts):
    """[{name: arr}, ...] per block -> {name: arr[L, ...]} stacked."""
    names = block_param_dicts[0].keys()
    return {n: jnp.stack([d[n] for d in block_param_dicts])
            for n in names}


def build_gpt_pipeline(model, mesh, num_microbatches, axis_name="pp"):
    """Split a models.gpt.GPT into a pp-sharded pipelined middle.

    Returns (apply_fn, params) where params = {"emb": {...}, "stages":
    {name: [L, ...]}, "head": {...}} and apply_fn(params, input_ids,
    labels) -> scalar loss. Embedding/unembedding stay outside the
    pipeline (they are dp/tp-sharded as usual); the block stack runs
    through the GPipe schedule, scanning blocks-per-stage inside each
    stage.
    """
    from ..nn.layers import functional_call, param_dict

    if getattr(model.cfg, "dropout", 0.0):
        # functional_call would bake a single trace-time dropout mask into
        # the compiled scan — silently wrong training numerics
        raise ValueError(
            "build_gpt_pipeline requires dropout=0.0 (per-step RNG "
            "threading through the pipeline schedule is not supported)")

    n_stages = mesh.shape[axis_name]
    blocks = list(model.blocks)
    assert len(blocks) % n_stages == 0, (
        f"{len(blocks)} blocks not divisible into {n_stages} stages")
    per_stage = len(blocks) // n_stages

    block0 = blocks[0]
    stacked = stack_block_params([param_dict(b) for b in blocks])
    # [L, ...] -> [n_stages, per_stage, ...]
    stages = {n: v.reshape(n_stages, per_stage, *v.shape[1:])
              for n, v in stacked.items()}

    all_params = param_dict(model)
    emb = {n: v for n, v in all_params.items()
           if n.startswith(("wte.", "wpe."))}
    head = {n: v for n, v in all_params.items()
            if n.startswith("norm_f.")}

    def stage_fn(stage_params, h):
        # scan this stage's blocks (leaves [per_stage, ...])
        def one_block(h, blk_params):
            return functional_call(block0, blk_params, h), None

        h, _ = jax.lax.scan(one_block, h, stage_params)
        return h

    pipe = gpipe(stage_fn, mesh, num_microbatches, axis_name=axis_name)
    max_seq = model.cfg.max_seq_len

    def apply_fn(params, input_ids, labels):
        from ..nn import functional as F

        wte = params["emb"]["wte.weight"]
        wpe = params["emb"]["wpe.weight"]
        seq = input_ids.shape[1]
        if seq > max_seq:
            raise ValueError(
                f"sequence length {seq} exceeds max_seq_len {max_seq}")
        pos = jnp.arange(seq, dtype=jnp.int32)[None, :]
        h = jnp.take(wte, input_ids, axis=0) + jnp.take(wpe, pos, axis=0)
        h = pipe(params["stages"], h)
        h = F.layer_norm(h, weight=params["head"]["norm_f.weight"],
                         bias=params["head"]["norm_f.bias"])
        logits = jnp.einsum("bsh,vh->bsv", h, wte)
        logp = F.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return nll.mean()

    params = {"emb": emb, "stages": stages, "head": head}
    return apply_fn, params


def pipeline_dryrun(n_devices, devices=None, num_microbatches=4):
    """Driver hook: one pipelined fwd+bwd+sgd step on a pp x dp mesh."""
    import numpy as np

    from ..models.gpt import GPT, GPTConfig
    from .mesh import build_mesh

    pp = 2
    dp = n_devices // pp
    mesh = build_mesh(dp=dp, tp=1, pp=pp, sp=1, devices=devices)
    model = GPT(GPTConfig(vocab_size=256, hidden_size=32, num_layers=4,
                          num_heads=4, max_seq_len=16, dropout=0.0))
    apply_fn, params = build_gpt_pipeline(model, mesh, num_microbatches)

    r = np.random.default_rng(0)
    batch = 2 * dp * num_microbatches
    x = jnp.asarray(r.integers(0, 256, (batch, 16)), jnp.int32)
    y = jnp.asarray(r.integers(0, 256, (batch, 16)), jnp.int32)

    @jax.jit
    def step(params, x, y):
        loss, grads = jax.value_and_grad(apply_fn)(params, x, y)
        params = jax.tree.map(lambda p, g: p - 1e-3 * g, params, grads)
        return params, loss

    params, loss = step(params, x, y)
    loss.block_until_ready()
    assert jnp.isfinite(loss), "pipeline dryrun loss not finite"
    return float(loss)
