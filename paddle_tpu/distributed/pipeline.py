"""Pipeline parallelism: differentiable GPipe schedule over a "pp" mesh axis.

TPU-native replacement for the reference's pipeline trainer
(/root/reference/paddle/fluid/framework/pipeline_trainer.cc,
device_worker.h:325 SectionWorker, driven by PipelineOptimizer
python/paddle/fluid/optimizer.py:3413): where the reference moves Scopes
through blocking queues between per-section threads, here the WHOLE
schedule is one compiled SPMD program. Per-stage weights are stacked on a
leading stage axis and sharded over "pp"; each schedule tick every device
runs its stage and ppermutes the activation to its ring neighbor (ICI
hop). The bubble is the standard (n_stages - 1) ticks.

Because the schedule is just scan + ppermute + masked updates, jax.grad
differentiates through it — backward pipelining falls out of the
transpose of ppermute, with jax.checkpoint bounding activation memory to
the stage boundaries.

Composition: batch may additionally be sharded over "dp" (specs below);
tensor parallelism composes by NamedSharding on the stacked weights'
trailing dims as usual.
"""


import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["gpipe", "interleaved_gpipe", "bubble_fraction",
           "stack_block_params", "interleave_stack_params",
           "build_gpt_pipeline", "pipeline_dryrun"]


def gpipe(stage_fn, mesh, num_microbatches, axis_name="pp",
          batch_axis="dp", remat=True, needs_rng=False,
          param_specs=None):
    """Build fn(stacked_params, x[, rng_key]) -> y running the GPipe
    schedule.

    stage_fn(stage_params, h) -> h': one pipeline stage; h' must have
    h's shape/dtype (transformer-block shape preservation).  With
    needs_rng=True, stage_fn(stage_params, h, key) -> h' instead: each
    schedule tick derives key = fold_in(fold_in(base, tick), stage), so
    every (microbatch, stage) pair sees an independent stream — the
    per-tick threading dropout needs.  Under jax.grad/remat the same
    fold happens in the recompute, so forward and backward masks agree.
    stacked_params: pytree whose leaves have a leading n_stages dim.
    x: [B, ...] activations; B must divide into num_microbatches.
    """
    n_stages = mesh.shape[axis_name]
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    has_dp = batch_axis and batch_axis in mesh.shape

    def body(params_loc, x_loc, key):
        my = jax.tree.map(lambda l: l[0], params_loc)     # this stage's slice
        i = jax.lax.axis_index(axis_name)
        m = num_microbatches
        mb = x_loc.shape[0] // m
        xs = x_loc.reshape(m, mb, *x_loc.shape[1:])
        out_buf = jnp.zeros_like(xs)
        h0 = jnp.zeros_like(xs[0])
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        is_first = i == 0
        is_last = i == n_stages - 1

        def tick(carry, t):
            h_recv, out_buf = carry
            x_t = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, m - 1), 0, keepdims=False)
            h_in = jnp.where(is_first, x_t, h_recv)
            if needs_rng:
                tick_key = jax.random.fold_in(
                    jax.random.fold_in(key, t), i)
                if has_dp:
                    # each dp replica holds different data and must draw
                    # its own masks — replicated keys would correlate
                    # dropout noise across the batch shards
                    tick_key = jax.random.fold_in(
                        tick_key, jax.lax.axis_index(batch_axis))
                h_out = stage_fn(my, h_in, tick_key)
            else:
                h_out = stage_fn(my, h_in)
            slot = t - (n_stages - 1)
            valid = (slot >= 0) & (slot < m) & is_last
            cl = jnp.clip(slot, 0, m - 1)
            cur = jax.lax.dynamic_index_in_dim(out_buf, cl, 0,
                                               keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(valid, h_out, cur), cl, 0)
            h_recv = jax.lax.ppermute(h_out, axis_name, perm)
            return (h_recv, out_buf), None

        ticks = jnp.arange(m + n_stages - 1)
        (_, out_buf), _ = jax.lax.scan(tick, (h0, out_buf), ticks)
        # only the last stage holds real outputs; psum of the masked
        # buffer replicates them across the pp axis
        out_buf = jnp.where(is_last, out_buf, 0.0)
        out_buf = jax.lax.psum(out_buf, axis_name)
        return out_buf.reshape(x_loc.shape)

    x_spec = P(batch_axis) if has_dp else P()
    # param_specs: per-leaf PartitionSpecs for the stacked weights (all
    # leading with the pp axis); lets tensor parallelism ride the same
    # shard_map — each device then holds its (stage, tp) weight tile
    p_spec = P(axis_name) if param_specs is None else param_specs
    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(p_spec, x_spec, P()),
        out_specs=x_spec,
        check_vma=False)
    if needs_rng:
        return fn
    # keep the historical two-arg signature when no rng is threaded
    return lambda params, x: fn(params, x,
                                jax.random.PRNGKey(0))


def bubble_fraction(n_stages, num_microbatches, num_virtual=1):
    """Idle fraction of the schedule (per device, forward or its
    transpose): GPipe = (S-1)/(m+S-1); with V interleaved virtual
    chunks per device the fill shrinks V-fold to (S-1)/(mV+S-1)
    (Megatron-LM interleaved schedule, arXiv:2104.04473 §2.2)."""
    s, m, v = n_stages, num_microbatches, num_virtual
    return (s - 1) / (m * v + s - 1)


def interleaved_gpipe(stage_fn, mesh, num_microbatches, num_virtual,
                      axis_name="pp", batch_axis="dp", remat=True,
                      param_specs=None):
    """Interleaved virtual-stage pipeline (Megatron-LM 2104.04473 §2.2)
    as ONE SPMD program — the perf schedule the reference's async
    pipeline trainer (optimizer.py:3413, pipeline_trainer.cc) never
    had.

    Each device owns `num_virtual` (V) NON-contiguous chunks of the
    layer stack: chunk c lives on device c mod S, so a microbatch rides
    the ppermute ring V full laps.  Per tick every device computes one
    (microbatch, chunk) unit and ppermutes the activation to its ring
    neighbor — the SAME dataflow as gpipe, only the tick->unit indexing
    changes:

        tp = t - d; q, r = divmod(tp, S); v = q % V; w = q // V
        unit = (microbatch w*S + r, chunk v*S + d)

    which makes every dependency arrive exactly one tick earlier on the
    ring neighbor (incl. the lap boundary S-1 -> 0).  Total schedule:
    m*V + S - 1 chunk-ticks where a chunk-tick is 1/V of a gpipe stage
    -> wall m + (S-1)/V stage-times vs gpipe's m + S - 1: the fill
    bubble shrinks V-fold (`bubble_fraction`).  jax.grad transposes the
    whole schedule for the backward, so the backward bubble shrinks
    identically.

    stacked_params: leaves [S*V, ...] in INTERLEAVED device order (row
    d*V + v = chunk v*S + d) — see interleave_stack_params.  Requires
    num_microbatches % S == 0 (wave injection).
    """
    n_stages = mesh.shape[axis_name]
    v_chunks = int(num_virtual)
    m = num_microbatches
    if m % n_stages != 0:
        raise ValueError(
            f"interleaved schedule needs num_microbatches ({m}) "
            f"divisible by n_stages ({n_stages}) — wave injection")
    if remat:
        stage_fn = jax.checkpoint(stage_fn)
    has_dp = batch_axis and batch_axis in mesh.shape

    def body(params_loc, x_loc):
        # local leaves [V, ...]: this device's chunks, level-major
        my = params_loc
        for leaf in jax.tree.leaves(my):
            if leaf.shape[0] != v_chunks:
                # without this, dynamic_index_in_dim would CLAMP an
                # out-of-range level to row 0 and silently reuse chunk
                # 0's weights (e.g. gpipe-style [S, ...] stacks)
                raise ValueError(
                    f"interleaved params must have local leading dim "
                    f"num_virtual={v_chunks} (global S*V in interleaved "
                    f"order, see interleave_stack_params); got "
                    f"{leaf.shape[0]}")
        d = jax.lax.axis_index(axis_name)
        mb = x_loc.shape[0] // m
        xs = x_loc.reshape(m, mb, *x_loc.shape[1:])
        out_buf = jnp.zeros_like(xs)
        h0 = jnp.zeros_like(xs[0])
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        total_ticks = m * v_chunks + n_stages - 1

        def tick(carry, t):
            h_recv, out_buf = carry
            tp = t - d                         # device-local phase
            valid = (tp >= 0) & (tp < m * v_chunks)
            q = jnp.clip(tp, 0, m * v_chunks - 1) // n_stages
            r = jnp.clip(tp, 0, m * v_chunks - 1) % n_stages
            v = q % v_chunks                   # virtual chunk level
            w = q // v_chunks                  # microbatch wave
            j = w * n_stages + r               # microbatch index
            x_t = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(j, 0, m - 1), 0, keepdims=False)
            inject = (d == 0) & (v == 0)       # chunk 0 loads the data
            h_in = jnp.where(inject, x_t, h_recv)
            chunk_p = jax.tree.map(
                lambda l: jax.lax.dynamic_index_in_dim(
                    l, v, 0, keepdims=False), my)
            h_out = stage_fn(chunk_p, h_in)
            emit = valid & (d == n_stages - 1) & (v == v_chunks - 1)
            cl = jnp.clip(j, 0, m - 1)
            cur = jax.lax.dynamic_index_in_dim(out_buf, cl, 0,
                                               keepdims=False)
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf, jnp.where(emit, h_out, cur), cl, 0)
            h_recv = jax.lax.ppermute(h_out, axis_name, perm)
            return (h_recv, out_buf), None

        (_, out_buf), _ = jax.lax.scan(tick, (h0, out_buf),
                                       jnp.arange(total_ticks))
        out_buf = jnp.where(d == n_stages - 1, out_buf, 0.0)
        out_buf = jax.lax.psum(out_buf, axis_name)
        return out_buf.reshape(x_loc.shape)

    x_spec = P(batch_axis) if has_dp else P()
    p_spec = P(axis_name) if param_specs is None else param_specs
    return jax.shard_map(
        body, mesh=mesh, in_specs=(p_spec, x_spec), out_specs=x_spec,
        check_vma=False)


def interleave_stack_params(block_param_dicts, n_stages, num_virtual):
    """Blocks -> {name: [S*V, per_chunk, ...]} in interleaved device
    order: global row d*V + v holds chunk c = v*S + d, so sharding the
    leading dim over "pp" gives device d its V chunk levels
    contiguously (level-major)."""
    L = len(block_param_dicts)
    chunks = n_stages * num_virtual
    if L % chunks != 0:
        raise ValueError(
            f"{L} blocks not divisible into {chunks} chunks")
    per = L // chunks
    stacked = stack_block_params(block_param_dicts)
    out = {}
    for n, varr in stacked.items():
        byc = varr.reshape(chunks, per, *varr.shape[1:])
        rows = [byc[v * n_stages + d]
                for d in range(n_stages) for v in range(num_virtual)]
        out[n] = jnp.stack(rows)        # [S*V, per, ...]
    return out


def stack_block_params(block_param_dicts):
    """[{name: arr}, ...] per block -> {name: arr[L, ...]} stacked."""
    names = block_param_dicts[0].keys()
    return {n: jnp.stack([d[n] for d in block_param_dicts])
            for n in names}


def build_gpt_pipeline(model, mesh, num_microbatches, axis_name="pp",
                       interleave=1):
    """Split a models.gpt.GPT into a pp-sharded pipelined middle.

    Returns (apply_fn, params) where params = {"emb": {...}, "stages":
    {name: [L, ...]}, "head": {...}} and apply_fn(params, input_ids,
    labels) -> scalar loss. Embedding/unembedding stay outside the
    pipeline (they are dp/tp-sharded as usual); the block stack runs
    through the GPipe schedule, scanning blocks-per-stage inside each
    stage.

    interleave=V > 1 switches to the interleaved virtual-stage schedule
    (interleaved_gpipe): each device holds V non-contiguous chunks and
    the fill bubble shrinks V-fold.  Requires dropout == 0 (the per-tick
    rng threading is wired for the GPipe schedule only) and
    num_microbatches % n_stages == 0.
    """
    from ..nn.layers import functional_call, param_dict

    dropout_p = float(getattr(model.cfg, "dropout", 0.0) or 0.0)
    n_stages = mesh.shape[axis_name]
    blocks = list(model.blocks)
    block0 = blocks[0]

    def plain_stage_fn(stage_params, h):
        # scan this stage's blocks (leaves [per_stage, ...])
        def one_block(h, blk_params):
            return functional_call(block0, blk_params, h), None

        h, _ = jax.lax.scan(one_block, h, stage_params)
        return h

    if interleave > 1:
        if dropout_p:
            raise ValueError(
                "interleave > 1 requires dropout=0.0 (per-tick rng "
                "threading is GPipe-schedule only)")
        stages = interleave_stack_params(
            [param_dict(b) for b in blocks], n_stages, interleave)
        pipe = interleaved_gpipe(plain_stage_fn, mesh, num_microbatches,
                                 interleave, axis_name=axis_name)
    else:
        assert len(blocks) % n_stages == 0, (
            f"{len(blocks)} blocks not divisible into {n_stages} stages")
        per_stage = len(blocks) // n_stages
        stacked = stack_block_params([param_dict(b) for b in blocks])
        # [L, ...] -> [n_stages, per_stage, ...]
        stages = {n: v.reshape(n_stages, per_stage, *v.shape[1:])
                  for n, v in stacked.items()}

        if dropout_p:
            from ..nn.parameter import default_rng

            def stage_fn(stage_params, h, key):
                # scan this stage's blocks (leaves [per_stage, ...]);
                # each block folds its index so masks differ across
                # blocks, and key_context routes the per-(tick, stage,
                # block) stream into the blocks' Dropout layers
                def one_block(h, xs):
                    blk_params, idx = xs
                    blk_key = jax.random.fold_in(key, idx)
                    with default_rng.key_context(blk_key):
                        return functional_call(block0, blk_params, h), \
                            None

                per = jax.tree.leaves(stage_params)[0].shape[0]
                h, _ = jax.lax.scan(
                    one_block, h,
                    (stage_params, jnp.arange(per, dtype=jnp.int32)))
                return h
        else:
            stage_fn = plain_stage_fn

        pipe = gpipe(stage_fn, mesh, num_microbatches,
                     axis_name=axis_name, needs_rng=bool(dropout_p))

    all_params = param_dict(model)
    emb = {n: v for n, v in all_params.items()
           if n.startswith(("wte.", "wpe."))}
    head = {n: v for n, v in all_params.items()
            if n.startswith("norm_f.")}
    return _lm_apply_fn(model, pipe, dropout_p), \
        {"emb": emb, "stages": stages, "head": head}


def _lm_apply_fn(model, pipe, dropout_p):
    """Shared pre/post-pipeline LM wrapper: embedding lookup (+dropout),
    pipelined block stack, final layer norm, tied-head logits, fused CE
    (one wrapper so the dp x pp and dp x tp x pp builders cannot
    diverge)."""
    from ..nn import functional as F

    max_seq = model.cfg.max_seq_len

    def apply_fn(params, input_ids, labels, rng_key=None):
        wte = params["emb"]["wte.weight"]
        wpe = params["emb"]["wpe.weight"]
        seq = input_ids.shape[1]
        if seq > max_seq:
            raise ValueError(
                f"sequence length {seq} exceeds max_seq_len {max_seq}")
        pos = jnp.arange(seq, dtype=jnp.int32)[None, :]
        h = jnp.take(wte, input_ids, axis=0) + jnp.take(wpe, pos, axis=0)
        if dropout_p:
            if rng_key is None:
                raise ValueError(
                    "this pipeline was built with dropout>0: pass a "
                    "fresh rng_key to every apply_fn call (a fixed key "
                    "would reuse the same dropout masks each step)")
            # embedding dropout (model.drop) lives outside the pipeline;
            # fold a constant far above any tick index for its stream
            h = F.dropout(h, p=dropout_p,
                          rng_key=jax.random.fold_in(rng_key, 1 << 30))
            h = pipe(params["stages"], h, rng_key)
        else:
            h = pipe(params["stages"], h)
        h = F.layer_norm(h, weight=params["head"]["norm_f.weight"],
                         bias=params["head"]["norm_f.bias"])
        logits = jnp.einsum("bsh,vh->bsv", h, wte)
        lse = jax.nn.logsumexp(logits.astype(jnp.float32), axis=-1)
        lab = jnp.take_along_axis(logits, labels[..., None],
                                  axis=-1)[..., 0]
        return (lse - lab.astype(jnp.float32)).mean()

    return apply_fn


def build_gpt_pipeline_3d(model, mesh, num_microbatches, axis_pp="pp",
                          axis_tp="tp", batch_axis="dp"):
    """dp x tp x pp composed in ONE mesh: megatron tensor parallelism
    inside each pipeline stage.

    The stacked block weights shard over BOTH the pp axis (leading
    stage dim) and the tp axis (megatron column/row dims): q/k/v and
    fc1 split their output dim (attention heads divide across tp),
    out_proj and fc2 split their input dim with a psum(tp) completing
    the row-parallel matmul — two tp collectives per block, the
    standard megatron count.  The batch additionally shards over dp via
    the gpipe x_spec.  Math mirrors models.gpt.GPTBlock exactly (same
    SDPA kernel, gelu, layer_norm), so the pipelined+tp loss matches
    the single-device model.

    Requires dropout == 0 (the dp x pp builder handles dropout; see
    build_gpt_pipeline).  Returns (apply_fn, params) like
    build_gpt_pipeline.
    """
    from ..nn import functional as F
    from ..nn.layers import param_dict

    if float(getattr(model.cfg, "dropout", 0.0) or 0.0):
        raise ValueError("build_gpt_pipeline_3d requires dropout=0.0")

    n_stages = mesh.shape[axis_pp]
    tp = mesh.shape[axis_tp]
    heads = model.cfg.num_heads
    hidden = model.cfg.hidden_size
    assert heads % tp == 0, f"{heads} heads not divisible by tp={tp}"
    blocks = list(model.blocks)
    assert len(blocks) % n_stages == 0
    per_stage = len(blocks) // n_stages
    head_dim = hidden // heads

    stacked = stack_block_params([param_dict(b) for b in blocks])
    stages = {n: v.reshape(n_stages, per_stage, *v.shape[1:])
              for n, v in stacked.items()}

    # megatron sharding per stacked leaf [pp, per_stage, ...]:
    #   column parallel (split output dim): q/k/v, fc1 -> last dim tp
    #   row parallel (split input dim): out_proj, fc2 -> dim 2 tp,
    #     bias replicated (added once, after the psum)
    def leaf_spec(name):
        if name.endswith(".weight") and any(
                k in name for k in ("q_proj", "k_proj", "v_proj", "fc1")):
            return P(axis_pp, None, None, axis_tp)
        if name.endswith(".bias") and any(
                k in name for k in ("q_proj", "k_proj", "v_proj", "fc1")):
            return P(axis_pp, None, axis_tp)
        if name.endswith(".weight") and any(
                k in name for k in ("out_proj", "fc2")):
            return P(axis_pp, None, axis_tp, None)
        return P(axis_pp)           # norms + row-parallel biases

    param_specs = {n: leaf_spec(n) for n in stages}
    eps = blocks[0].norm1._epsilon

    def stage_fn(p, h):
        # p: this stage's local tile {name: [per_stage, ...local...]}
        def one_block(h, bp):
            x = h
            hn = F.layer_norm(x, [hidden], bp["norm1.weight"],
                              bp["norm1.bias"], eps)
            b, s, _ = hn.shape
            loc = heads // tp

            def proj(w, bias):
                return (hn @ w + bias).reshape(b, s, loc, head_dim)

            q = proj(bp["attn.q_proj.weight"], bp["attn.q_proj.bias"])
            k = proj(bp["attn.k_proj.weight"], bp["attn.k_proj.bias"])
            v = proj(bp["attn.v_proj.weight"], bp["attn.v_proj.bias"])
            q, k, v = (jnp.transpose(t, (0, 2, 1, 3)) for t in (q, k, v))
            o = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                               training=False)
            o = jnp.transpose(o, (0, 2, 1, 3)).reshape(b, s, -1)
            attn_out = jax.lax.psum(o @ bp["attn.out_proj.weight"],
                                    axis_tp) + bp["attn.out_proj.bias"]
            x = x + attn_out
            hn = F.layer_norm(x, [hidden], bp["norm2.weight"],
                              bp["norm2.bias"], eps)
            ff = F.gelu(hn @ bp["fc1.weight"] + bp["fc1.bias"])
            ff = jax.lax.psum(ff @ bp["fc2.weight"],
                              axis_tp) + bp["fc2.bias"]
            return x + ff, None

        h, _ = jax.lax.scan(one_block, h, p)
        return h

    pipe = gpipe(stage_fn, mesh, num_microbatches, axis_name=axis_pp,
                 batch_axis=batch_axis, param_specs=param_specs)
    all_params = param_dict(model)
    emb = {n: v for n, v in all_params.items()
           if n.startswith(("wte.", "wpe."))}
    head = {n: v for n, v in all_params.items()
            if n.startswith("norm_f.")}
    return _lm_apply_fn(model, pipe, 0.0), \
        {"emb": emb, "stages": stages, "head": head}


def pipeline_dryrun(n_devices, devices=None, num_microbatches=4, pp=2,
                    dropout=0.0):
    """Driver hook: one pipelined fwd+bwd+sgd step on a pp x dp mesh
    (pp is configurable so deeper pipelines get exercised; dropout>0
    threads per-tick PRNG keys through the schedule)."""
    import numpy as np

    from ..models.gpt import GPT, GPTConfig
    from .mesh import build_mesh

    dp = n_devices // pp
    mesh = build_mesh(dp=dp, tp=1, pp=pp, sp=1, devices=devices)
    model = GPT(GPTConfig(vocab_size=256, hidden_size=32, num_layers=pp * 2,
                          num_heads=4, max_seq_len=16, dropout=dropout))
    apply_fn, params = build_gpt_pipeline(model, mesh, num_microbatches)

    r = np.random.default_rng(0)
    batch = max(2 * dp, 1) * num_microbatches
    x = jnp.asarray(r.integers(0, 256, (batch, 16)), jnp.int32)
    y = jnp.asarray(r.integers(0, 256, (batch, 16)), jnp.int32)

    @jax.jit
    def step(params, x, y, key):
        def loss_fn(params):
            if dropout:
                return apply_fn(params, x, y, rng_key=key)
            return apply_fn(params, x, y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        params = jax.tree.map(lambda p, g: p - 1e-3 * g, params, grads)
        return params, loss

    params, loss = step(params, x, y, jax.random.PRNGKey(0))
    loss.block_until_ready()
    assert jnp.isfinite(loss), "pipeline dryrun loss not finite"
    return float(loss)
