"""Fleet — unified distributed-training facade.

Parity: /root/reference/python/paddle/fluid/incubate/fleet/ —
fleet.init (base/fleet_base.py:184), fleet.distributed_optimizer (:238),
role makers (base/role_maker.py), DistributedStrategy
(collective/__init__.py:134).
"""

import os

from .env import ParallelEnv, init_parallel_env

__all__ = ["init", "distributed_optimizer", "DistributedStrategy",
           "PaddleCloudRoleMaker", "UserDefinedRoleMaker", "worker_index",
           "worker_num", "is_first_worker", "get_strategy",
           "make_train_step", "save_persistables",
           "save_inference_model"]


class DistributedStrategy:
    """Parity: incubate/fleet/collective/__init__.py:134 — knobs for the
    sharded step."""

    def __init__(self):
        self.nccl_comm_num = 1            # kept for API parity (unused)
        self.use_local_sgd = False
        self.local_sgd_steps = 1
        self.use_dgc = False
        self.dgc_sparsity = 0.999
        self.recompute = False
        self.recompute_checkpoints = []
        self.amp = False
        self.amp_loss_scale = 2.0 ** 15
        # mesh degrees
        self.dp_degree = None  # default: all devices
        self.tp_degree = 1
        self.pp_degree = 1
        self.sp_degree = 1


class PaddleCloudRoleMaker:
    """Parity: role_maker.py PaddleCloudRoleMaker — ranks from env vars."""

    def __init__(self, is_collective=True):
        self._env = ParallelEnv()
        self._is_collective = is_collective

    def worker_index(self):
        return self._env.local_rank

    def worker_num(self):
        return max(self._env.nranks, 1)

    def is_first_worker(self):
        return self.worker_index() == 0


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, current_id=0, workers=1, **kw):
        super().__init__()
        self._env._local_rank = current_id
        self._env._nranks = workers


_role_maker = None
_strategy = None


def init(role_maker=None):
    global _role_maker
    _role_maker = role_maker or PaddleCloudRoleMaker()
    init_parallel_env()
    return _role_maker


def worker_index():
    return _role_maker.worker_index() if _role_maker else 0


def worker_num():
    return _role_maker.worker_num() if _role_maker else 1


def is_first_worker():
    return worker_index() == 0 if _role_maker else True


def distributed_optimizer(optimizer, strategy=None):
    """Wrap a dygraph optimizer for collective training (fleet_base.py:238).

    Returns the optimizer augmented with the strategy. The strategy's knobs
    change behavior through `make_train_step` (or DataParallelTrainStep,
    which consults the stored strategy): use_dgc -> DGCTrainStep,
    use_local_sgd -> LocalSGDTrainStep, recompute -> jax.checkpoint around
    the loss, amp -> bf16 auto_cast, mesh degrees -> build_mesh."""
    global _strategy
    _strategy = strategy or DistributedStrategy()
    optimizer._fleet_strategy = _strategy
    return optimizer


def get_strategy():
    return _strategy


def make_train_step(model, optimizer, loss_fn, mesh=None, strategy=None):
    """Build the train step the strategy asks for (CollectiveOptimizer
    .minimize parity, incubate/fleet/collective/__init__.py:182 — but as a
    step factory instead of a program transpile).

    Consumes every DistributedStrategy knob:
      use_dgc          -> DGC sparse-allreduce momentum step
      use_local_sgd    -> per-replica steps + periodic averaging
      recompute        -> jax.checkpoint around the loss (activation remat)
      amp              -> bf16 auto_cast around the loss
      dp/tp/pp/sp degrees -> mesh construction when no mesh is passed
    """
    import jax as _jax

    from .data_parallel import DataParallelTrainStep
    from .mesh import build_mesh, default_mesh
    from .strategies import DGCTrainStep, LocalSGDTrainStep

    strategy = (strategy or getattr(optimizer, "_fleet_strategy", None)
                or DistributedStrategy())
    if mesh is None:
        if strategy.dp_degree or strategy.tp_degree > 1 \
                or strategy.sp_degree > 1 or strategy.pp_degree > 1:
            mesh = build_mesh(dp=strategy.dp_degree or 1,
                              tp=strategy.tp_degree,
                              pp=strategy.pp_degree,
                              sp=strategy.sp_degree)
        else:
            mesh = default_mesh()

    wrapped_loss = loss_fn
    if strategy.amp:
        from ..amp import auto_cast

        def wrapped_loss(m, *batch, _inner=wrapped_loss):
            with auto_cast(enable=True):
                return _inner(m, *batch)
    if strategy.recompute:
        def wrapped_loss(m, *batch, _inner=wrapped_loss):
            return _jax.checkpoint(
                lambda *b: _inner(m, *b))(*batch)

    if strategy.use_dgc:
        hp = getattr(optimizer, "_hyperparams", None)
        if hp is None or "learning_rate" in hp and callable(
                hp["learning_rate"]):
            raise ValueError(
                "use_dgc needs an optimizer with recorded scalar "
                "hyperparameters (paddle_tpu.dygraph SGD/Momentum); got "
                f"{type(optimizer).__name__} without _hyperparams")
        return DGCTrainStep(model, wrapped_loss, mesh,
                            lr=float(hp["learning_rate"]),
                            momentum=float(hp.get("momentum", 0.9)),
                            sparsity=strategy.dgc_sparsity,
                            rampup_begin_step=getattr(
                                strategy, "dgc_rampup_begin_step", 0))
    if strategy.use_local_sgd:
        return LocalSGDTrainStep(model, optimizer, wrapped_loss, mesh,
                                 local_sgd_steps=strategy.local_sgd_steps)
    return DataParallelTrainStep(model, optimizer, wrapped_loss, mesh)


def save_persistables(executor, dirname, main_program=None):
    """Fleet save facade (fleet_base.py save_persistables): rank 0 writes,
    other ranks no-op — checkpoint state is replicated under pjit DP, so
    one copy is the whole model (the reference pulls pserver slices;
    the PS-table analogue here rides paddle_tpu.checkpoint)."""
    if not is_first_worker():
        return None
    from .. import io

    return io.save_persistables(executor, dirname,
                                main_program=main_program)


def save_inference_model(executor, dirname, feeded_var_names,
                         target_vars, main_program=None):
    """Fleet export facade (fleet_base.py save_inference_model): rank 0
    writes the pruned serving program + params."""
    if not is_first_worker():
        return None
    from .. import io

    return io.save_inference_model(dirname, feeded_var_names,
                                   target_vars, executor,
                                   main_program=main_program)


class Fleet:
    """Base-class parity (reference fleet_base.py Fleet ABC): the
    module-level functions (init/worker_index/distributed_optimizer/...)
    are the one implementation; this class offers them as methods for
    scripts subclassing or type-checking against Fleet."""

    def init(self, role_maker=None):
        return init(role_maker)

    def worker_index(self):
        return worker_index()

    def worker_num(self):
        return worker_num()

    def is_first_worker(self):
        return is_first_worker()

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)
