"""Fleet — unified distributed-training facade.

Parity: /root/reference/python/paddle/fluid/incubate/fleet/ —
fleet.init (base/fleet_base.py:184), fleet.distributed_optimizer (:238),
role makers (base/role_maker.py), DistributedStrategy
(collective/__init__.py:134).
"""

import os

from .env import ParallelEnv, init_parallel_env

__all__ = ["init", "distributed_optimizer", "DistributedStrategy",
           "PaddleCloudRoleMaker", "UserDefinedRoleMaker", "worker_index",
           "worker_num", "is_first_worker"]


class DistributedStrategy:
    """Parity: incubate/fleet/collective/__init__.py:134 — knobs for the
    sharded step."""

    def __init__(self):
        self.nccl_comm_num = 1            # kept for API parity (unused)
        self.use_local_sgd = False
        self.local_sgd_steps = 1
        self.use_dgc = False
        self.dgc_sparsity = 0.999
        self.recompute = False
        self.recompute_checkpoints = []
        self.amp = False
        self.amp_loss_scale = 2.0 ** 15
        # mesh degrees
        self.dp_degree = None  # default: all devices
        self.tp_degree = 1
        self.pp_degree = 1
        self.sp_degree = 1


class PaddleCloudRoleMaker:
    """Parity: role_maker.py PaddleCloudRoleMaker — ranks from env vars."""

    def __init__(self, is_collective=True):
        self._env = ParallelEnv()
        self._is_collective = is_collective

    def worker_index(self):
        return self._env.local_rank

    def worker_num(self):
        return max(self._env.nranks, 1)

    def is_first_worker(self):
        return self.worker_index() == 0


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, current_id=0, workers=1, **kw):
        super().__init__()
        self._env._local_rank = current_id
        self._env._nranks = workers


_role_maker = None
_strategy = None


def init(role_maker=None):
    global _role_maker
    _role_maker = role_maker or PaddleCloudRoleMaker()
    init_parallel_env()
    return _role_maker


def worker_index():
    return _role_maker.worker_index() if _role_maker else 0


def worker_num():
    return _role_maker.worker_num() if _role_maker else 1


def is_first_worker():
    return worker_index() == 0 if _role_maker else True


def distributed_optimizer(optimizer, strategy=None):
    """Wrap a dygraph optimizer for collective training (fleet_base.py:238).

    Returns the optimizer augmented with the strategy; actual gradient
    synchronization happens in DataParallelTrainStep / ShardedTrainStep
    which consult the strategy's mesh degrees."""
    global _strategy
    _strategy = strategy or DistributedStrategy()
    optimizer._fleet_strategy = _strategy
    return optimizer


def get_strategy():
    return _strategy
