"""Ring attention: exact attention over sequence-sharded q/k/v.

The long-context capability the reference lacks entirely (SURVEY.md §2.3:
"Sequence/context parallelism ... NO"; its long-sequence story is LoD
ragged batching + recompute). Here each device of the "sp" mesh axis
holds a [B, H, S/n, D] shard; k/v shards rotate around the ring via
jax.lax.ppermute (compiled to ICI neighbor exchanges) while the local
q shard accumulates online-softmax partial results — so attention over
the FULL sequence is computed without any device ever holding more than
1/n of it, and the per-step block compute overlaps the next shard's
transfer (XLA schedules the ppermute DMA against the einsums).

Math: same numerically-stable streaming softmax as the flash kernel
(kernels/flash_attention.py) — carry running max m, running sum l and an
unnormalised accumulator; each incoming block contributes via
exp-rescaling. Causal masking uses global positions derived from the
ring step, so fully-future blocks contribute exp(-inf)=0 and vanish.

grads: everything is jnp + ppermute (which has a transpose rule), so
jax.grad differentiates straight through the ring; the per-block compute
is wrapped in jax.checkpoint to keep backward memory at O(S/n).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30

__all__ = ["ring_attention", "ring_attention_sharded"]


def _block_attn(q, k, v, scale, causal, q_start, k_start):
    """One q-shard x kv-shard block. Returns (unnormalised out, m, l).

    q: [B,H,Sq,D], k/v: [B,H,Sk,D]; q_start/k_start are the global
    offsets of the shards (traced scalars — the kv offset changes per
    ring step).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_start + jnp.arange(q.shape[2])[:, None]
        k_pos = k_start + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    m = s.max(axis=-1)                                    # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def ring_attention(q, k, v, axis_name="sp", causal=False, sm_scale=None):
    """Exact attention with q/k/v sequence-sharded over `axis_name`.

    Must be called inside shard_map (or pmap) over a mesh with that axis;
    q, k, v are the local [B, H, S_local, D] shards. Returns the local
    output shard, same shape/dtype as q.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_loc = q.shape[2]
    q_start = idx * s_loc

    block = jax.checkpoint(
        functools.partial(_block_attn, scale=sm_scale, causal=causal))

    perm = [(i, (i + 1) % n) for i in range(n)]

    def combine(acc, o_i, m_i, l_i):
        m_acc, l_acc, o_acc = acc
        m_new = jnp.maximum(m_acc, m_i)
        # all-masked blocks have m_i = -inf -> beta = 0 -> no contribution
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_i - m_new)
        l_new = l_acc * alpha + l_i * beta
        o_new = o_acc * alpha[..., None] + o_i * beta[..., None]
        return m_new, l_new, o_new

    def step(carry, _):
        k_cur, v_cur, kv_idx, acc = carry
        # rotate kv shards one hop around the ring (ICI neighbor DMA),
        # then fold in the newly-arrived block
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        kv_idx = (kv_idx - 1) % n
        o_i, m_i, l_i = block(q, k_cur, v_cur,
                              q_start=q_start, k_start=kv_idx * s_loc)
        acc = combine(acc, o_i, m_i, l_i)
        return (k_cur, v_cur, kv_idx, acc), None

    # local block first, then n-1 rotate+combine steps (no wasted final hop)
    acc0 = combine(
        (jnp.full(q.shape[:3], NEG_INF, jnp.float32),
         jnp.zeros(q.shape[:3], jnp.float32),
         jnp.zeros(q.shape, jnp.float32)),
        *block(q, k, v, q_start=q_start, k_start=idx * s_loc))
    carry0 = (k, v, idx, acc0)
    (_, _, _, (m, l, o)), _ = jax.lax.scan(step, carry0, None, length=n - 1)

    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (o / l_safe[..., None]).astype(q.dtype)


# small bounded cache: each entry pins its Mesh + compiled executables
# for the process lifetime, so cap it rather than let re-meshing
# workloads accumulate closures
@functools.lru_cache(maxsize=8)
def _sharded_ring_fn(mesh, axis_name, causal, sm_scale):
    spec = P(None, None, axis_name, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name,
                          causal=causal, sm_scale=sm_scale),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return jax.jit(fn)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False,
                           sm_scale=None):
    """Global-array entry point: q/k/v are [B, H, S, D] jax Arrays; the
    seq dim is (re)sharded over `axis_name` and the ring runs under jit.
    The jitted fn is cached per (mesh, axis, causal, scale) so repeated
    calls hit the compile cache."""
    return _sharded_ring_fn(mesh, axis_name, bool(causal),
                            sm_scale)(q, k, v)
