"""Ring attention: exact attention over sequence-sharded q/k/v.

The long-context capability the reference lacks entirely (SURVEY.md §2.3:
"Sequence/context parallelism ... NO"; its long-sequence story is LoD
ragged batching + recompute). Here each device of the "sp" mesh axis
holds a [B, H, S/n, D] shard; k/v shards rotate around the ring via
jax.lax.ppermute (compiled to ICI neighbor exchanges) while the local
q shard accumulates online-softmax partial results — so attention over
the FULL sequence is computed without any device ever holding more than
1/n of it, and the per-step block compute overlaps the next shard's
transfer (XLA schedules the ppermute DMA against the einsums).

Math: same numerically-stable streaming softmax as the flash kernel
(kernels/flash_attention.py) — carry running max m, running sum l and an
unnormalised accumulator; each incoming block contributes via
exp-rescaling. Causal masking uses global positions derived from the
ring step, so fully-future blocks contribute exp(-inf)=0 and vanish.

grads: everything is jnp + ppermute (which has a transpose rule), so
jax.grad differentiates straight through the ring; the per-block compute
is wrapped in jax.checkpoint to keep backward memory at O(S/n).
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30

__all__ = ["ring_attention", "ring_attention_sharded"]


def _block_attn(q, k, v, scale, causal, q_start, k_start):
    """One q-shard x kv-shard block. Returns (unnormalised out, m, l).

    q: [B,H,Sq,D], k/v: [B,H,Sk,D]; q_start/k_start are the global
    offsets of the shards (traced scalars — the kv offset changes per
    ring step).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = q_start + jnp.arange(q.shape[2])[:, None]
        k_pos = k_start + jnp.arange(k.shape[2])[None, :]
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    m = s.max(axis=-1)                                    # [B,H,Sq]
    p = jnp.exp(s - m[..., None])
    l = p.sum(axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def _use_flash_blocks(impl, s_loc):
    if impl not in ("auto", "flash", "xla"):
        raise ValueError(
            f"ring_attention impl must be 'auto', 'flash' or 'xla'; "
            f"got {impl!r}")
    if impl == "xla":
        return False
    if impl == "flash":
        return True
    # auto: the Pallas kernel path needs the TPU backend (interpret mode
    # on CPU is correctness-only) and a lane-aligned local shard
    from ..kernels.backend import is_tpu_backend

    return is_tpu_backend() and s_loc % 128 == 0


def _flash_ring_block(q, k, v, scale, rel):
    """One q-shard x kv-shard block through the Pallas flash kernel,
    returning (normalized out f32, lse f32).

    rel classifies the kv shard against the q shard on the causal ring:
    0 = past (full attention), 1 = diagonal (causal triangle), 2 =
    future (contributes nothing: lse = -inf weights it out of the
    combine).  No offset mask is ever needed — the three cases are
    exactly the kernel's causal=False / causal=True / skip."""
    from ..kernels.flash_attention import flash_attention_with_lse

    def past(_):
        o, lse = flash_attention_with_lse(q, k, v, causal=False,
                                          sm_scale=scale)
        return o.astype(jnp.float32), lse

    def diag(_):
        o, lse = flash_attention_with_lse(q, k, v, causal=True,
                                          sm_scale=scale)
        return o.astype(jnp.float32), lse

    def future(_):
        return (jnp.zeros(q.shape, jnp.float32),
                jnp.full(q.shape[:3], NEG_INF, jnp.float32))

    return jax.lax.switch(rel, [past, diag, future], None)


def _combine_lse(acc, o_i, lse_i):
    """Merge (normalized out, lse) partials: softmax-weighted average.
    An empty partial (lse = -inf) gets weight exp(-inf) = 0."""
    o_acc, lse_acc = acc
    lse_new = jnp.logaddexp(lse_acc, lse_i)
    safe = jnp.where(lse_new <= NEG_INF, 0.0, lse_new)
    a = jnp.exp(lse_acc - safe)[..., None]
    b = jnp.exp(lse_i - safe)[..., None]
    return o_acc * a + o_i * b, lse_new


def _ring_attention_flash(q, k, v, axis_name, causal, sm_scale):
    """Ring loop with the Pallas flash kernel computing each block —
    the per-block [S/n, S/n] score tile never touches HBM, and the
    (out, lse) partials merge exactly (same identity the flash kernel
    uses across k tiles, applied across ring hops)."""
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def rel_of(kv_idx):
        if not causal:
            return jnp.int32(0)                    # every shard: full
        return jnp.where(kv_idx == idx, 1,
                         jnp.where(kv_idx < idx, 0, 2)).astype(jnp.int32)

    acc = _combine_lse(
        (jnp.zeros(q.shape, jnp.float32),
         jnp.full(q.shape[:3], NEG_INF, jnp.float32)),
        *_flash_ring_block(q, k, v, sm_scale, rel_of(idx)))

    def step(carry, _):
        k_cur, v_cur, kv_idx, acc = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        kv_idx = (kv_idx - 1) % n
        o_i, lse_i = _flash_ring_block(q, k_cur, v_cur, sm_scale,
                                       rel_of(kv_idx))
        return (k_cur, v_cur, kv_idx, _combine_lse(acc, o_i, lse_i)), None

    (_, _, _, (o, _)), _ = jax.lax.scan(
        step, (k, v, idx, acc), None, length=n - 1)
    return o.astype(q.dtype)


def ring_attention(q, k, v, axis_name="sp", causal=False, sm_scale=None,
                   impl="auto"):
    """Exact attention with q/k/v sequence-sharded over `axis_name`.

    Must be called inside shard_map (or pmap) over a mesh with that axis;
    q, k, v are the local [B, H, S_local, D] shards. Returns the local
    output shard, same shape/dtype as q.

    impl: "auto" (Pallas flash blocks on TPU, XLA composition
    elsewhere), "flash", or "xla".
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if _use_flash_blocks(impl, q.shape[2]):
        return _ring_attention_flash(q, k, v, axis_name, causal, sm_scale)
    n = jax.lax.axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_loc = q.shape[2]
    q_start = idx * s_loc

    block = jax.checkpoint(
        functools.partial(_block_attn, scale=sm_scale, causal=causal))

    perm = [(i, (i + 1) % n) for i in range(n)]

    def combine(acc, o_i, m_i, l_i):
        m_acc, l_acc, o_acc = acc
        m_new = jnp.maximum(m_acc, m_i)
        # all-masked blocks have m_i = -inf -> beta = 0 -> no contribution
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_i - m_new)
        l_new = l_acc * alpha + l_i * beta
        o_new = o_acc * alpha[..., None] + o_i * beta[..., None]
        return m_new, l_new, o_new

    def step(carry, _):
        k_cur, v_cur, kv_idx, acc = carry
        # rotate kv shards one hop around the ring (ICI neighbor DMA),
        # then fold in the newly-arrived block
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        kv_idx = (kv_idx - 1) % n
        o_i, m_i, l_i = block(q, k_cur, v_cur,
                              q_start=q_start, k_start=kv_idx * s_loc)
        acc = combine(acc, o_i, m_i, l_i)
        return (k_cur, v_cur, kv_idx, acc), None

    # local block first, then n-1 rotate+combine steps (no wasted final hop)
    acc0 = combine(
        (jnp.full(q.shape[:3], NEG_INF, jnp.float32),
         jnp.zeros(q.shape[:3], jnp.float32),
         jnp.zeros(q.shape, jnp.float32)),
        *block(q, k, v, q_start=q_start, k_start=idx * s_loc))
    carry0 = (k, v, idx, acc0)
    (_, _, _, (m, l, o)), _ = jax.lax.scan(step, carry0, None, length=n - 1)

    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (o / l_safe[..., None]).astype(q.dtype)


# small bounded cache: each entry pins its Mesh + compiled executables
# for the process lifetime, so cap it rather than let re-meshing
# workloads accumulate closures
@functools.lru_cache(maxsize=8)
def _sharded_ring_fn(mesh, axis_name, causal, sm_scale, impl):
    spec = P(None, None, axis_name, None)
    fn = jax.shard_map(
        functools.partial(ring_attention, axis_name=axis_name,
                          causal=causal, sm_scale=sm_scale, impl=impl),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return jax.jit(fn)


def ring_attention_sharded(q, k, v, mesh, axis_name="sp", causal=False,
                           sm_scale=None, impl="auto"):
    """Global-array entry point: q/k/v are [B, H, S, D] jax Arrays; the
    seq dim is (re)sharded over `axis_name` and the ring runs under jit.
    The jitted fn is cached per (mesh, axis, causal, scale, impl) so
    repeated calls hit the compile cache."""
    return _sharded_ring_fn(mesh, axis_name, bool(causal),
                            sm_scale, impl)(q, k, v)
