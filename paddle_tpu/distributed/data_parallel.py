"""Data parallelism.

Replaces three reference mechanisms with ONE sharded train step:
- ParallelExecutor local DP (/root/reference/paddle/fluid/framework/
  parallel_executor.cc:443 + multi_devices_graph_pass.cc:446 allreduce
  insertion),
- Fleet collective "NCCL2" mode (python/paddle/fluid/transpiler/
  collective.py:178 GradAllReduce),
- dygraph DataParallel (python/paddle/fluid/dygraph/parallel.py:223
  scale_loss/apply_collective_grads).

Mechanism: params replicated, batch sharded over the "dp" mesh axis, grads
pmean'd inside shard_map — XLA fuses the gradient all-reduce with backward
compute (the hand-written fused_all_reduce_op_handle / coalescing logic of
the reference is the compiler's job here).
"""

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

from ..nn.layers import _swap_params, buffer_dict, param_dict
from ..nn.parameter import default_rng
from .mesh import default_mesh

__all__ = ["DataParallel", "DataParallelTrainStep", "scale_loss"]


def scale_loss(loss, nranks=None):
    """Parity: dygraph/parallel.py:290 — kept for API compatibility; the
    sharded step's pmean makes explicit loss scaling unnecessary."""
    return loss


class DataParallelTrainStep:
    """Jitted DP train step over a mesh's "dp" axis.

        step = DataParallelTrainStep(model, optimizer, loss_fn, mesh)
        loss = step(x, y)   # x,y batched over all devices

    Batch arrays are global; they get sharded over dp. Params/opt state are
    replicated. Gradient sync = pmean on the dp axis.
    """

    def __init__(self, model, optimizer, loss_fn, mesh=None):
        self._model = model
        self._optimizer = optimizer
        self._mesh = mesh or default_mesh()
        mesh_axes = self._mesh.axis_names

        def _step(params, buffers, opt_state, rng_key, *batch):
            def loss_of(ps):
                with _swap_params(model, ps), default_rng.key_context(rng_key):
                    from ..jit import _get_buffer, _restore_buffers, _swap_in_buffers

                    old = _swap_in_buffers(model, buffers)
                    try:
                        loss = loss_fn(model, *batch)
                        new_buffers = {p: _get_buffer(model, p)
                                       for p in buffers}
                    finally:
                        _restore_buffers(model, old)
                return loss, new_buffers

            (loss, new_buffers), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params)
            grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
            loss = jax.lax.pmean(loss, "dp")
            new_buffers = jax.tree.map(lambda b: jax.lax.pmean(b, "dp"),
                                       new_buffers)
            new_params, new_opt_state = optimizer.functional_update(
                grads, opt_state, params)
            return new_params, new_buffers, new_opt_state, loss

        replicated = P()
        batch_spec = P("dp")

        def _sharded(params, buffers, opt_state, rng_key, *batch):
            return shard_map(
                _step,
                mesh=self._mesh,
                in_specs=(replicated, replicated, replicated, replicated)
                + tuple(batch_spec for _ in batch),
                out_specs=(replicated, replicated, replicated, replicated),
                check_vma=False,
            )(params, buffers, opt_state, rng_key, *batch)

        self._jit_step = jax.jit(_sharded, donate_argnums=(0, 1, 2))
        self._opt_state = None

    def __call__(self, *batch):
        params = {n: p.value for n, p in self._model.named_parameters()
                  if p.trainable}
        buffers = buffer_dict(self._model)
        if self._opt_state is None:
            self._opt_state = self._optimizer.init_state(params)
        batch = tuple(jnp.asarray(b) for b in batch)
        new_params, new_buffers, self._opt_state, loss = self._jit_step(
            params, buffers, self._opt_state, default_rng.next_key(), *batch)
        named = dict(self._model.named_parameters())
        for n, v in new_params.items():
            named[n].value = v
        for path, v in new_buffers.items():
            self._model._set_buffer_by_path(path, v)
        return loss


class DataParallel:
    """Alias for THE dygraph DataParallel implementation
    (paddle_tpu.dygraph.parallel.DataParallel — reference
    parallel.py:223): one semantics for both import paths.  Lazy so
    this module never imports the dygraph package at import time."""

    def __new__(cls, layer, strategy=None):
        from ..dygraph.parallel import DataParallel as _Impl

        return _Impl(layer, strategy)
