"""Device mesh management.

TPU-native replacement for the reference's communicator registry: NCCL
rings keyed by ring_id (/root/reference/paddle/fluid/platform/
collective_helper.h:62, c_comm_init ops) become named mesh axes on a
jax.sharding.Mesh — "dp"/"tp"/"pp"/"sp"/"ep" axes replace ring ids, and
XLA compiles the collectives onto ICI links; no comm-init ops exist.
"""

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_GLOBAL_MESH = None

# canonical axis order
AXES = ("pp", "dp", "sp", "tp", "ep")


def build_mesh(dp=1, tp=1, pp=1, sp=1, ep=1, devices=None):
    """Create a Mesh with the requested parallelism degrees.

    Axis semantics (scaling-book conventions):
      dp — data parallel (gradient psum)
      tp — tensor parallel (megatron-style sharded matmuls)
      pp — pipeline stages
      sp — sequence/context parallel (ring attention)
      ep — expert parallel (MoE all_to_all dispatch)
    """
    devices = devices if devices is not None else jax.devices()
    need = dp * tp * pp * sp * ep
    if need > len(devices):
        raise ValueError(
            f"mesh needs {need} devices, only {len(devices)} available")
    devs = np.array(devices[:need]).reshape(pp, dp, sp, tp, ep)
    return Mesh(devs, AXES)


def set_global_mesh(mesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh
    return mesh


def get_global_mesh():
    return _GLOBAL_MESH


def default_mesh():
    """All local devices on the dp axis."""
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        _GLOBAL_MESH = build_mesh(dp=len(jax.devices()))
    return _GLOBAL_MESH


def replicated(mesh):
    return NamedSharding(mesh, P())


def data_sharding(mesh, batch_axes=("dp",)):
    """Shard leading (batch) dim over the given mesh axes."""
    return NamedSharding(mesh, P(batch_axes))
