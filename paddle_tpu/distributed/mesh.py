"""Device mesh management.

TPU-native replacement for the reference's communicator registry: NCCL
rings keyed by ring_id (/root/reference/paddle/fluid/platform/
collective_helper.h:62, c_comm_init ops) become named mesh axes on a
jax.sharding.Mesh — "dp"/"tp"/"pp"/"sp"/"ep" axes replace ring ids, and
XLA compiles the collectives onto ICI links; no comm-init ops exist.
"""

import math

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_GLOBAL_MESH = None

# canonical axis order
AXES = ("pp", "dp", "sp", "tp", "ep")


def build_mesh(dp=1, tp=1, pp=1, sp=1, ep=1, devices=None):
    """Create a Mesh with the requested parallelism degrees.

    Axis semantics (scaling-book conventions):
      dp — data parallel (gradient psum)
      tp — tensor parallel (megatron-style sharded matmuls)
      pp — pipeline stages
      sp — sequence/context parallel (ring attention)
      ep — expert parallel (MoE all_to_all dispatch)
    """
    devices = devices if devices is not None else jax.devices()
    need = dp * tp * pp * sp * ep
    if need > len(devices):
        raise ValueError(
            f"mesh needs {need} devices, only {len(devices)} available")
    devs = np.array(devices[:need]).reshape(pp, dp, sp, tp, ep)
    return Mesh(devs, AXES)


def build_rule_mesh(axes, devices=None):
    """Mesh whose axis names/order follow a partition-rule
    ``MeshSpec``-style ``{axis: size}`` dict (e.g. ``{"dp": 2,
    "mp": 2}``) — the analyzer's axis names become jax mesh axes
    VERBATIM, so a rule spec ``[None, "mp"]`` lowers to
    ``PartitionSpec(None, "mp")`` on this mesh with no renaming
    table.  Size-1 axes are kept (they cost nothing and preserve the
    rule set's axis vocabulary).  ``devices`` pins an explicit device
    list (the elastic contract of ``with_data_parallel(places=...)``);
    otherwise the first ``prod(sizes)`` global devices are taken."""
    axes = {str(k): int(v) for k, v in dict(
        axes.axes if hasattr(axes, "axes") else axes).items()}
    if not axes:
        axes = {"dp": 1}
    devices = devices if devices is not None else jax.devices()
    need = math.prod(axes.values())
    if need > len(devices):
        raise ValueError(
            f"mesh {axes} needs {need} devices, only "
            f"{len(devices)} available")
    devs = np.array(devices[:need]).reshape(tuple(axes.values()))
    return Mesh(devs, tuple(axes))


def mesh_key(mesh):
    """Device-IDENTITY cache key of a mesh: (axis names, shape, sorted
    device ids).  Two meshes with the same key compile to the same
    executable; an elastic retarget onto a same-sized DIFFERENT device
    set changes the key and forces a retrace."""
    return (tuple(mesh.axis_names), mesh.shape_tuple,
            tuple(sorted(int(d.id) for d in mesh.devices.flat)))


class MeshLayout:
    """One mesh's derived placement facts, computed once and shared by
    every feed path (ISSUE 16): the executor's compiled-step cache key,
    the fleet timestamp-feed sharding, and the skew probe's per-shard
    process map all read the same object instead of memoizing
    separately.

    Fields:
      mesh         — the jax Mesh
      key          — :func:`mesh_key` device-identity tuple
      data_axis    — the batch-sharding axis name (None if absent)
      data_sharding— NamedSharding splitting dim 0 over data_axis
      local_rows   — device rows this process contributes
      shard_procs  — process_index per mesh device, flat order
      data_rows    — data-axis rows this process contributes: on a 1-D
                     dp mesh identical to local_rows, on a {dp,mp} mesh
                     the number of DISTINCT dp coordinates among the
                     local devices (the fleet timestamp feed is one row
                     per dp SHARD, not per device)
      data_procs   — process_index per data-axis shard (first device of
                     each dp slice), the skew table's rank->host map
      fingerprint  — the rule-set fingerprint this layout was keyed
                     with (None for plain dp layouts)
    """

    __slots__ = ("mesh", "key", "data_axis", "data_sharding",
                 "local_rows", "shard_procs", "data_rows", "data_procs",
                 "fingerprint")

    def __init__(self, mesh, data_axis="dp", fingerprint=None):
        self.mesh = mesh
        self.key = mesh_key(mesh)
        self.data_axis = (data_axis if data_axis in mesh.axis_names
                          else None)
        self.fingerprint = fingerprint
        devs = list(mesh.devices.flat)
        try:
            me = jax.process_index()
        except Exception:
            me = 0
        self.shard_procs = [int(getattr(d, "process_index", 0))
                            for d in devs]
        self.local_rows = (sum(1 for p in self.shard_procs if p == me)
                           or len(devs))
        if self.data_axis is not None:
            ax = list(mesh.axis_names).index(self.data_axis)
            ndata = int(mesh.shape[self.data_axis])
            procs = [None] * ndata
            mine = set()
            for idx, d in np.ndenumerate(mesh.devices):
                i = idx[ax]
                if procs[i] is None:
                    procs[i] = int(getattr(d, "process_index", 0))
                if int(getattr(d, "process_index", 0)) == me:
                    mine.add(i)
            self.data_procs = [p if p is not None else 0 for p in procs]
            self.data_rows = len(mine) or ndata
        else:
            self.data_procs = list(self.shard_procs)
            self.data_rows = self.local_rows
        try:
            self.data_sharding = NamedSharding(
                mesh, P(self.data_axis) if self.data_axis else P())
        except Exception:
            self.data_sharding = None


_LAYOUT_CACHE = {}   # (id(mesh), data_axis, fingerprint) -> MeshLayout


def mesh_layout(mesh, data_axis="dp", fingerprint=None):
    """The shared mesh-layout cache (ISSUE 16 satellite): one
    :class:`MeshLayout` per (mesh device identity, rule fingerprint),
    id-recycle-proof (the entry holds the mesh; a recycled id() with a
    different mesh object misses).  Bounded like the fleet's old
    private cache: 8 entries, cleared wholesale."""
    k = (id(mesh), data_axis, fingerprint)
    ent = _LAYOUT_CACHE.get(k)
    if ent is not None and ent.mesh is mesh:
        return ent
    layout = MeshLayout(mesh, data_axis=data_axis,
                        fingerprint=fingerprint)
    if len(_LAYOUT_CACHE) >= 8:
        _LAYOUT_CACHE.clear()
    _LAYOUT_CACHE[k] = layout
    return layout


def set_global_mesh(mesh):
    global _GLOBAL_MESH
    _GLOBAL_MESH = mesh
    return mesh


def get_global_mesh():
    return _GLOBAL_MESH


def default_mesh():
    """All local devices on the dp axis."""
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        _GLOBAL_MESH = build_mesh(dp=len(jax.devices()))
    return _GLOBAL_MESH


def replicated(mesh):
    return NamedSharding(mesh, P())


def data_sharding(mesh, batch_axes=("dp",)):
    """Shard leading (batch) dim over the given mesh axes."""
    return NamedSharding(mesh, P(batch_axes))
