"""Mixture-of-Experts with expert parallelism over a mesh axis.

The reference has no MoE (SURVEY §2.3: expert parallel — NO); this module
is capability the TPU rebuild adds, designed mesh-first the way the
scaling-book prescribes: experts are a sharded leading dimension, tokens
are dispatched to expert shards with one-hot einsums (GShard/Switch
style, all static shapes for the MXU), and the `ep` mesh axis turns the
dispatch/combine einsums into XLA all_to_all collectives over ICI —
no hand-written communication.

Forms:
- `top_k_gating`: softmax router with top-k expert choice, capacity
  clipping, and the Switch load-balance auxiliary loss.
- `moe_ffn`: dense (single-device or auto-sharded under jit) MoE FFN.
- `sharded_moe_ffn`: the same computation with explicit sharding
  constraints so pjit lowers dispatch/combine to all_to_all over "ep".
"""

import math

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P


def init_moe_params(key, num_experts, d_model, d_hidden, dtype=jnp.float32):
    """Router + per-expert FFN weights: wg [D,E], w1 [E,D,H], w2 [E,H,D]."""
    k1, k2, k3 = jax.random.split(key, 3)
    s1 = 1.0 / math.sqrt(d_model)
    s2 = 1.0 / math.sqrt(d_hidden)
    return {
        "wg": (jax.random.normal(k1, (d_model, num_experts)) * s1
               ).astype(dtype),
        "w1": (jax.random.normal(k2, (num_experts, d_model, d_hidden))
               * s1).astype(dtype),
        "w2": (jax.random.normal(k3, (num_experts, d_hidden, d_model))
               * s2).astype(dtype),
    }


def top_k_gating(x, wg, k=2, capacity_factor=1.25, min_capacity=4):
    """Route tokens to top-k experts.

    x: [N, D] tokens. Returns (dispatch [N, E, C] bool-ish float,
    combine [N, E, C], aux_loss) with C = ceil(k*N/E * capacity_factor).
    """
    n, _ = x.shape
    e = wg.shape[1]
    cap = max(int(min_capacity),
              int(math.ceil(k * n / e * capacity_factor)))
    logits = x.astype(jnp.float32) @ wg.astype(jnp.float32)    # [N, E]
    probs = jax.nn.softmax(logits, axis=-1)

    dispatch = jnp.zeros((n, e, cap), jnp.float32)
    combine = jnp.zeros((n, e, cap), jnp.float32)
    masked = probs
    # Switch load-balance loss on the FULL router distribution
    me = probs.mean(axis=0)                                    # [E]
    total_mask = jnp.zeros((n, e), jnp.float32)
    counts = jnp.zeros((e,), jnp.float32)  # slots taken by earlier passes

    for _ in range(k):
        idx = jnp.argmax(masked, axis=1)                       # [N]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)     # [N, E]
        # position inside the expert's capacity, offset past the slots
        # already taken by previous choice passes (GShard position
        # bookkeeping; without the offset 2nd-choice tokens double-book)
        pos = ((jnp.cumsum(onehot, axis=0) - 1.0)
               + counts[None, :]) * onehot                     # [N, E]
        keep = (pos < cap) & (onehot > 0)
        pos_c = jax.nn.one_hot(pos.sum(axis=1).astype(jnp.int32), cap,
                               dtype=jnp.float32)              # [N, C]
        slot = keep.astype(jnp.float32)[:, :, None] * pos_c[:, None, :]
        gate = (probs * onehot).sum(axis=1, keepdims=True)     # [N, 1]
        dispatch = dispatch + slot
        combine = combine + slot * gate[:, :, None]
        total_mask = total_mask + onehot
        counts = counts + keep.astype(jnp.float32).sum(axis=0)
        masked = masked * (1.0 - onehot)                       # next choice

    ce = total_mask.mean(axis=0) / k                           # frac routed
    aux_loss = e * jnp.sum(me * ce)
    return dispatch, combine, aux_loss


def moe_ffn(params, x, k=2, capacity_factor=1.25, activation=jax.nn.gelu):
    """MoE feed-forward over tokens x: [..., D] -> [..., D], plus the
    load-balance aux loss. Static-shape einsum dispatch (MXU-friendly)."""
    lead = x.shape[:-1]
    d = x.shape[-1]
    toks = x.reshape(-1, d)
    dispatch, combine, aux = top_k_gating(
        toks, params["wg"], k=k, capacity_factor=capacity_factor)
    xin = jnp.einsum("nd,nec->ecd", toks.astype(jnp.float32), dispatch)
    h = activation(jnp.einsum("ecd,edh->ech", xin,
                              params["w1"].astype(jnp.float32)))
    out = jnp.einsum("ech,ehd->ecd", h, params["w2"].astype(jnp.float32))
    y = jnp.einsum("ecd,nec->nd", out, combine)
    return y.reshape(*lead, d).astype(x.dtype), aux


def shard_moe_params(params, mesh, axis="ep"):
    """Place expert-major weights over the mesh's expert axis; the router
    is replicated."""
    put = lambda v, spec: jax.device_put(v, NamedSharding(mesh, spec))
    return {
        "wg": put(params["wg"], P()),
        "w1": put(params["w1"], P(axis, None, None)),
        "w2": put(params["w2"], P(axis, None, None)),
    }


def sharded_moe_ffn(params, x, mesh, axis="ep", k=2, capacity_factor=1.25,
                    activation=jax.nn.gelu):
    """Expert-parallel MoE forward: expert weights sharded over `axis`,
    dispatch/combine einsums constrained so XLA lowers them to
    all_to_all over that axis (tokens replicated or batch-sharded by the
    caller's outer pjit)."""
    cst = jax.lax.with_sharding_constraint
    lead = x.shape[:-1]
    d = x.shape[-1]
    toks = x.reshape(-1, d)
    dispatch, combine, aux = top_k_gating(
        toks, params["wg"], k=k, capacity_factor=capacity_factor)
    xin = jnp.einsum("nd,nec->ecd", toks.astype(jnp.float32), dispatch)
    xin = cst(xin, NamedSharding(mesh, P(axis, None, None)))
    h = activation(jnp.einsum("ecd,edh->ech", xin,
                              params["w1"].astype(jnp.float32)))
    out = jnp.einsum("ech,ehd->ecd", h, params["w2"].astype(jnp.float32))
    out = cst(out, NamedSharding(mesh, P(axis, None, None)))
    y = jnp.einsum("ecd,nec->nd", out, combine)
    return y.reshape(*lead, d).astype(x.dtype), aux


def moe_ffn_shardmap(params, x, axis="ep", k=2, capacity_factor=1.25,
                     activation=jax.nn.gelu):
    """Expert-parallel MoE for use INSIDE a `jax.shard_map` body.

    `sharded_moe_ffn` above is the pjit-style path (sharding
    constraints, XLA inserts the all_to_alls); this is its shard_map
    twin for composition with the pipeline schedules in
    distributed/pipeline.py, whose gpipe/interleaved_gpipe bodies are
    per-device code where sharding constraints don't exist — the GShard
    dispatch/combine all_to_alls over `axis` are written explicitly
    (the role NCCL all-to-all plays in MoE ports of the reference's
    collective ops, operators/collective/).

    params' expert-major leaves are the LOCAL slices ([E_loc, ...]
    with E_loc = E / axis_size); the router `wg` is replicated [D, E].
    x is this device's token shard.  Tokens are gated locally, slots
    exchange expert-major over `axis`, local experts run, and the
    reverse exchange returns each token's expert outputs for the
    combine.  With enough capacity (no drops) the result is
    numerically the dense moe_ffn of the same tokens.
    """
    ep = jax.lax.axis_size(axis)
    lead = x.shape[:-1]
    d = x.shape[-1]
    toks = x.reshape(-1, d)
    e_loc = params["w1"].shape[0]
    assert params["wg"].shape[-1] == ep * e_loc, (
        f"moe_ffn_shardmap: router wg routes over "
        f"{params['wg'].shape[-1]} experts but w1 holds {e_loc} local "
        f"experts x {ep} '{axis}' shards = {ep * e_loc}.  Expert-major "
        f"leaves (w1/w2) must be the LOCAL [E/ep, ...] slices of the "
        f"global expert dim — pass params already sharded over '{axis}' "
        f"(e.g. via moe_rules), not the replicated full-expert arrays.")
    dispatch, combine, aux = top_k_gating(
        toks, params["wg"], k=k, capacity_factor=capacity_factor)
    cap = dispatch.shape[-1]
    xin = jnp.einsum("nd,nec->ecd", toks.astype(jnp.float32), dispatch)
    # [E, C, D] -> [ep, E_loc, C, D] -> exchange: leading dim becomes
    # the SOURCE peer whose tokens fill those slots
    xin = xin.reshape(ep, e_loc, cap, d)
    xin = jax.lax.all_to_all(xin, axis, split_axis=0, concat_axis=0)
    h = activation(jnp.einsum("secd,edh->sech", xin,
                              params["w1"].astype(jnp.float32)))
    out = jnp.einsum("sech,ehd->secd", h,
                     params["w2"].astype(jnp.float32))
    # reverse exchange: slots travel back to their token owners
    out = jax.lax.all_to_all(out, axis, split_axis=0, concat_axis=0)
    y = jnp.einsum("ecd,nec->nd", out.reshape(ep * e_loc, cap, d),
                   combine)
    return y.reshape(*lead, d).astype(x.dtype), aux
