"""Distributed training.

TPU-native mapping of the reference's distributed stack (SURVEY.md §2.3):
NCCL rings -> mesh axes + XLA ICI collectives; gRPC PS runtime -> host
sharded-embedding service (ps module); launch.py -> launch module;
transpilers/ParallelExecutor -> sharded train steps.
"""

from . import collective
from . import mesh
from . import fleet
from .collective import (
    all_reduce, all_gather, reduce_scatter, broadcast, ppermute, all_to_all,
    psum, pmean, pmax, pmin,
)
from .mesh import (build_mesh, build_rule_mesh, default_mesh,
                   get_global_mesh, mesh_key, mesh_layout,
                   set_global_mesh)
from .env import ParallelEnv, init_parallel_env, get_rank, get_world_size
from .data_parallel import DataParallel, DataParallelTrainStep, scale_loss
from .sharded import (
    PartitionRules, gpt_rules, bert_rules, mlp_rules, fsdp_rules,
    shard_params, shard_batch, shard_train_state,
    make_sharded_train_step,
)
from .ring_attention import ring_attention, ring_attention_sharded
from .pipeline import (gpipe, build_gpt_pipeline,
                       build_gpt_pipeline_3d)
from .federated import FLClient, FLServer, run_fl_round
from .moe import (
    init_moe_params, moe_ffn, moe_ffn_shardmap, shard_moe_params,
    sharded_moe_ffn, top_k_gating,
)
from .ps import (
    SparseEmbedding, Communicator, PSServer, PSClient, HeartBeatMonitor,
)

__all__ = [
    "collective", "mesh", "fleet",
    "all_reduce", "all_gather", "reduce_scatter", "broadcast", "ppermute",
    "all_to_all", "psum", "pmean", "pmax", "pmin",
    "build_mesh", "build_rule_mesh", "default_mesh", "get_global_mesh",
    "mesh_key", "mesh_layout", "set_global_mesh",
    "ParallelEnv", "init_parallel_env", "get_rank", "get_world_size",
    "DataParallel", "DataParallelTrainStep", "scale_loss",
    "PartitionRules", "gpt_rules", "bert_rules", "mlp_rules",
    "shard_params", "shard_batch", "shard_train_state",
    "make_sharded_train_step", "fsdp_rules",
    "ring_attention", "ring_attention_sharded",
    "gpipe", "build_gpt_pipeline", "build_gpt_pipeline_3d",
    "SparseEmbedding", "Communicator", "PSServer", "PSClient",
    "HeartBeatMonitor",
    "FLServer", "FLClient", "run_fl_round",
    "init_moe_params", "moe_ffn", "moe_ffn_shardmap", "sharded_moe_ffn",
    "shard_moe_params",
    "top_k_gating",
]
