"""Collective communication API.

Parity: /root/reference/paddle/fluid/operators/collective/ (c_allreduce_sum
c_allreduce_op.h:105, c_allgather, c_reducescatter, c_broadcast) and
python/paddle/fluid/layers/collective.py:20-172.

Two modes, mirroring the reference's graph-op vs eager duality:
- inside shard_map/pjit: thin jax.lax wrappers keyed by mesh AXIS NAME
  (the ring_id analogue);
- eagerly on a mesh: the `eager_*` forms shard_map the collective for you.

There is no gen_comm_id/comm_init — mesh axes are pre-wired by XLA
(c_gen_nccl_id_op.cc's RPC rendezvous maps to jax.distributed.initialize,
see paddle_tpu.distributed.env).
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P
from jax import shard_map

__all__ = [
    "all_reduce", "all_gather", "reduce_scatter", "broadcast", "ppermute",
    "all_to_all", "psum", "pmean", "pmax", "pmin",
    "eager_all_reduce", "eager_all_gather", "eager_broadcast",
    "eager_reduce_scatter",
]

# --- in-spmd collectives (usable inside shard_map'ed functions) -----------

def all_reduce(x, axis_name="dp", op="sum"):
    """c_allreduce_{sum,max,min,prod} parity."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    if op == "prod":
        return jnp.exp(lax.psum(jnp.log(x), axis_name))
    raise ValueError(f"unknown reduce op {op}")


psum = partial(all_reduce, op="sum")
pmean = partial(all_reduce, op="mean")
pmax = partial(all_reduce, op="max")
pmin = partial(all_reduce, op="min")


def all_gather(x, axis_name="dp", axis=0, tiled=True):
    """c_allgather parity."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name="dp", scatter_axis=0):
    """c_reducescatter parity."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_axis,
                            tiled=True)


def broadcast(x, axis_name="dp", root=0):
    """c_broadcast parity: every shard gets root's value."""
    idx = lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name)


def ppermute(x, axis_name, perm):
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis):
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=True)


# --- eager collectives over a mesh ----------------------------------------

def _eager(fn, mesh, in_spec, out_spec):
    return shard_map(fn, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
                     check_vma=False)


def eager_all_reduce(x, mesh=None, axis_name="dp", op="sum"):
    from .mesh import default_mesh

    mesh = mesh or default_mesh()
    spec = P(axis_name)
    return _eager(lambda s: all_reduce(s, axis_name, op), mesh, (spec,),
                  spec)(x)


def eager_all_gather(x, mesh=None, axis_name="dp"):
    from .mesh import default_mesh

    mesh = mesh or default_mesh()
    return _eager(lambda s: all_gather(s, axis_name), mesh, (P(axis_name),),
                  P())(x)


def eager_reduce_scatter(x, mesh=None, axis_name="dp"):
    from .mesh import default_mesh

    mesh = mesh or default_mesh()
    return _eager(lambda s: reduce_scatter(s, axis_name), mesh,
                  (P(axis_name),), P(axis_name))(x)


def eager_broadcast(x, mesh=None, axis_name="dp", root=0):
    from .mesh import default_mesh

    mesh = mesh or default_mesh()
    return _eager(lambda s: broadcast(s, axis_name, root), mesh,
                  (P(axis_name),), P(axis_name))(x)
