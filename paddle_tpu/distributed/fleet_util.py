"""Fleet metric aggregation utilities.

Parity: /root/reference/python/paddle/fluid/incubate/fleet/utils/
fleet_util.py (global AUC / accuracy via allreduce across workers):
each worker holds local accumulator state; the global metric is computed
from the SUM of the accumulators, not the mean of local metrics. On TPU
the allreduce is an XLA psum over a mesh axis (shard_map) — or a plain
host-side sum when the caller already gathered per-worker states.
"""

import numpy as np

__all__ = ["sum_accumulators", "global_auc", "global_accuracy",
           "global_metric_over_mesh"]


def sum_accumulators(states):
    """Elementwise-sum a list of per-worker accumulator arrays (the
    host-side form of the reference's allreduce)."""
    out = None
    for s in states:
        a = np.asarray(s, np.float64)
        out = a if out is None else out + a
    return out


def global_auc(stat_pos_list, stat_neg_list, num_thresholds=None):
    """Global AUC from per-worker positive/negative histogram stats
    (fleet_util.get_global_auc): sum the histograms, then integrate one
    ROC curve — NOT the mean of local AUCs."""
    pos = sum_accumulators(stat_pos_list)
    neg = sum_accumulators(stat_neg_list)
    # integrate from the highest threshold bucket down
    tp = np.cumsum(pos[::-1])
    fp = np.cumsum(neg[::-1])
    tot_p = tp[-1]
    tot_n = fp[-1]
    if tot_p == 0 or tot_n == 0:
        return 0.5
    tpr = np.concatenate([[0.0], tp / tot_p])
    fpr = np.concatenate([[0.0], fp / tot_n])
    return float(np.trapezoid(tpr, fpr))


def global_accuracy(correct_list, total_list):
    """Global accuracy = sum(correct) / sum(total) across workers."""
    c = float(sum_accumulators(correct_list))
    t = float(sum_accumulators(total_list))
    return c / max(t, 1.0)


def global_metric_over_mesh(mesh, axis, local_state):
    """psum `local_state` (an array or pytree of arrays) over a mesh
    axis with shard_map — the in-graph form of the reference's
    allreduce-based metric aggregation."""
    import jax
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    def agg(x):
        return jax.tree.map(lambda v: jax.lax.psum(v, axis), x)

    spec = jax.tree.map(lambda _: P(), local_state)
    return jax.jit(shard_map(
        agg, mesh=mesh, in_specs=(spec,), out_specs=spec,
        check_vma=False))(local_state)
