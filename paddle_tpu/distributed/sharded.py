"""Tensor-parallel / fully-sharded training via partition rules.

This is the capability the reference lacks (SURVEY.md §2.3: "Tensor
parallel ... NO") built the TPU way: instead of rewriting the graph with
collective ops (transpiler/collective.py in the reference does this for
DP), we attach `jax.sharding.NamedSharding`s to the *arrays* of the train
state according to regex partition rules, and `jax.jit` propagates the
shardings through the whole train step — XLA inserts all-gathers /
reduce-scatters / psums on ICI where the math demands them.

Megatron-style rules for a transformer block (weights are [in, out]):
  qkv / fc1 weights  -> shard OUT dim over "tp"  (column parallel)
  out_proj / fc2     -> shard IN  dim over "tp"  (row parallel)
  embeddings         -> shard vocab dim over "tp"
  layernorm, biases of row-parallel layers -> replicated

Optimizer moments inherit param shardings for free: FunctionalOptimizer
.init builds them with zeros_like(param), which preserves sharding — so
Adam/LAMB state is automatically sharded like the weights (ZeRO-style for
the tp-sharded slices).

ZeRO staging under XLA (make_sharded_train_step(zero1=True) is stage 1):
stage 2 (sharded GRADIENTS) has no separate array to annotate here —
within the one compiled step XLA materializes each grad only between
its producer and the update that consumes it and frees it immediately,
so grad residency is already transient; the partitioner turns the
dp-psum feeding a dp-sharded update into reduce-scatter where
profitable.  Stage 3 (sharded PARAMS) is spelled differently in this
framework: shard the params themselves via PartitionRules (fsdp-style
specs) and XLA inserts the all-gathers per layer — no separate "zero3"
flag is needed, the rules ARE the mechanism.
"""

import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "PartitionRules", "gpt_rules", "bert_rules", "mlp_rules",
    "fsdp_rules", "shard_params", "shard_train_state", "shard_batch",
    "make_sharded_train_step",
]


class PartitionRules:
    """Ordered (regex, PartitionSpec) table; first match wins.

    The analogue of the reference's per-op placement decisions in
    multi_devices_graph_pass.cc — but declarative and per-parameter.
    """

    def __init__(self, rules, default=P()):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.default = default

    def spec(self, name, value=None):
        for pat, spec in self.rules:
            if pat.search(name):
                return spec
        return self.default

    def __add__(self, other):
        out = PartitionRules([], default=self.default)
        out.rules = self.rules + other.rules
        return out


def gpt_rules():
    """Megatron TP sharding for models/gpt.py / models/bert.py naming.

    No trailing `.*` catch-all: unmatched names already fall through to
    PartitionRules' replicated default, and keeping the table specific
    is what lets `gpt_rules() + fsdp_rules()` compose (a catch-all here
    would shadow fsdp's `.*` -> P("dp") under first-match-wins)."""
    col = P(None, "tp")   # [in, out] -> out sharded
    row = P("tp", None)   # [in, out] -> in sharded
    return PartitionRules([
        (r"(q_proj|k_proj|v_proj|fc1|linear1)\.weight$", col),
        (r"(q_proj|k_proj|v_proj|fc1|linear1)\.bias$", P("tp")),
        (r"(out_proj|fc2|linear2)\.weight$", row),
        (r"(wte|wpe|word_emb|pos_emb|embedding)\.weight$", P("tp", None)),
        # MoE expert-major weights shard over the expert-parallel axis;
        # the router stays replicated (it must match BEFORE any
        # composed catch-all, hence an explicit rule despite equalling
        # the default)
        (r"moe\.(w1|w2)$", P("ep", None, None)),
        (r"moe\.wg$", P()),
    ])


def bert_rules():
    return gpt_rules()


def mlp_rules():
    # no `.*` catch-all for the same composability reason as gpt_rules
    return PartitionRules([
        (r"\.weight$", P(None, "tp")),
    ])


def fsdp_rules():
    """ZeRO-3/FSDP-style rules: every parameter's dim 0 shards over dp
    (params, grads, AND moments all divide by the dp degree; XLA
    all-gathers each layer's weights where the forward/backward needs
    them and reduce-scatters grads into the sharded update).  Biases
    and other small dims that don't divide are clamped to replicated by
    _named.  Compose with gpt_rules as `gpt_rules() + fsdp_rules()` —
    specific rules FIRST, this catch-all LAST, since
    PartitionRules.spec returns the FIRST matching rule (the reverse
    order would have the `.*` -> P("dp") rule shadow every gpt rule).
    That composition gives tp+fsdp on DIFFERENT params; for tp+fsdp on
    the SAME param use explicit per-name rules."""
    return PartitionRules([
        (r".*", P("dp")),
    ])


def _named(mesh, spec, value):
    # drop axes that exceed rank; clamp spec to array rank
    rank = np.ndim(value)
    parts = list(spec) + [None] * max(0, rank - len(spec))
    parts = parts[:rank]
    # un-shard dims not divisible by the axis size (e.g. tiny test models)
    def axsize(a):
        if a is None:
            return 1
        names = (a,) if isinstance(a, str) else a
        return int(np.prod([mesh.shape[n] for n in names]))
    shape = np.shape(value)
    parts = [a if shape[i] % axsize(a) == 0 else None
             for i, a in enumerate(parts)]
    while parts and parts[-1] is None:
        parts.pop()
    return NamedSharding(mesh, P(*parts))


def shard_params(params, mesh, rules):
    """device_put a {name: array} dict per the partition rules."""
    return {
        n: jax.device_put(v, _named(mesh, rules.spec(n, v), v))
        for n, v in params.items()
    }


def shard_batch(mesh, *arrays, spec=None):
    """Shard batch arrays: leading dim over dp, second (seq) over sp."""
    out = []
    for a in arrays:
        s = spec
        if s is None:
            s = P("dp", "sp") if np.ndim(a) >= 2 else P("dp")
        out.append(jax.device_put(a, _named(mesh, s, a)))
    return tuple(out) if len(out) > 1 else out[0]


def _zero1_spec(spec, shape, mesh):
    """Add dp-sharding of dim 0 to an optimizer-moment spec (ZeRO-1).

    The param itself stays replicated over dp (plain data parallelism);
    only the OPTIMIZER STATE shards, cutting its memory by the dp
    degree — the ZeRO-1 trade (arXiv:1910.02054 §5.1) expressed the
    pjit way: annotate the moment arrays and let XLA partition the
    update computation over dp and all-gather the new params.  A dim-0
    axis of SIZE 1 (e.g. "tp" on a pure-DP mesh — which gpt_rules puts
    on the vocab embedding, the largest param) counts as free, or the
    headline memory saving would silently not materialize exactly
    where it matters; indivisible dims are left for _named to clamp."""
    dp = mesh.shape.get("dp", 1)
    if dp <= 1 or not shape:
        return spec

    def axsize(a):
        names = (a,) if isinstance(a, str) else (a or ())
        return int(np.prod([mesh.shape[n] for n in names]))

    parts = list(spec) + [None] * (len(shape) - len(spec))
    if parts and axsize(parts[0]) == 1 and shape[0] % dp == 0:
        parts[0] = "dp"
        return P(*parts)
    return spec


def shard_train_state(state, mesh, rules, zero1=False):
    """Shard a models.train.TrainState: params + matching opt moments per
    rules, buffers/step/rng replicated.  zero1=True additionally shards
    the optimizer moments' dim 0 over dp (see _zero1_spec)."""
    from ..models.train import TrainState

    params = shard_params(state.params, mesh, rules)

    def shard_opt(leaf_path, leaf):
        # opt_state is a pytree whose dict keys mirror param names
        for n, p in params.items():
            if ("/" + n + "/" in leaf_path or leaf_path.endswith("/" + n)) \
                    and np.shape(leaf) == np.shape(p):
                spec = rules.spec(n)
                if zero1:
                    spec = _zero1_spec(spec, np.shape(leaf), mesh)
                return jax.device_put(leaf, _named(mesh, spec, leaf))
        return jax.device_put(leaf, NamedSharding(mesh, P()))

    opt_state = _tree_map_with_path(shard_opt, state.opt_state)
    rep = NamedSharding(mesh, P())
    return TrainState(
        params=params,
        opt_state=opt_state,
        buffers=jax.device_put(state.buffers, rep),
        step=jax.device_put(state.step, rep),
        rng=jax.device_put(state.rng, rep),
    )


def _tree_map_with_path(fn, tree, path=""):
    if isinstance(tree, dict):
        return {k: _tree_map_with_path(fn, v, path + "/" + str(k))
                for k, v in tree.items()}
    if isinstance(tree, (list, tuple)):
        t = [_tree_map_with_path(fn, v, path + f"/{i}")
             for i, v in enumerate(tree)]
        return type(tree)(t)
    return fn(path, tree)


def make_sharded_train_step(model, optimizer, mesh, rules=None,
                            loss_fn=None, rng_seed=0, zero1=False,
                            accum_steps=1):
    """Build (step, sharded_state). step(state, *batch) -> (state, loss).

    The step function is models.train.make_train_step's jitted step —
    sharding is carried entirely by the arrays; XLA compiles the TP/DP/SP
    collectives from the NamedShardings. Batch arrays should be placed
    with shard_batch (dp×sp).

    accum_steps=k > 1 scans grad accumulation over k microbatches
    inside the step (see models.train.make_train_step — batch leading
    dims must divide by k); composes with zero1 and the rules.
    zero1=True shards the optimizer moments over dp (ZeRO-1): params
    stay replicated, state memory divides by the dp degree, and XLA
    partitions the update + all-gathers the fresh params — the
    stage-1 memory optimisation the reference's DP never had.  The
    output state's shardings are pinned to the input's: without the
    constraint XLA's sharding inference returns dp-SHARDED params
    after step 1, breaking the replicated-params contract and forcing
    a recompile of the donated-state step on call 2.
    """
    from ..models.train import init_train_state, make_train_step

    rules = rules or gpt_rules()
    state = init_train_state(model, optimizer, rng_seed=rng_seed)
    state = shard_train_state(state, mesh, rules, zero1=zero1)
    if not zero1:
        step = make_train_step(model, optimizer, loss_fn=loss_fn, jit=True,
                               accum_steps=accum_steps)
        return step, state

    inner = make_train_step(model, optimizer, loss_fn=loss_fn, jit=False,
                            accum_steps=accum_steps)
    state_sh = jax.tree.map(lambda a: a.sharding, state)

    def step(st, *batch):
        st2, loss = inner(st, *batch)
        st2 = jax.tree.map(jax.lax.with_sharding_constraint, st2, state_sh)
        return st2, loss

    return jax.jit(step, donate_argnums=(0,)), state
