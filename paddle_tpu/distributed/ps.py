"""Parameter-server-style sparse embedding subsystem.

TPU-native reshape of the reference's PS stack
(/root/reference/paddle/fluid/operators/distributed/: RPCClient/RPCServer,
Communicator modes, parameter_{send,recv,prefetch}.cc; plus the pslib
DownpourWorker pull→compute→push loop, framework/device_worker.h:203):

- Giant embedding tables are anti-XLA (dynamic shapes, sparse updates),
  so they live HOST-side in native C++ shards (csrc/ps_shard.cpp via
  paddle_tpu.native) with the optimizer folded into push. The device
  program only ever sees the dense [batch, dim] slice — the same split
  Downpour uses (pull_sparse → dense ops → push_sparse).
- `Communicator` reproduces the reference's send modes
  (operators/distributed/communicator.h:176): SYNC pushes inline,
  ASYNC/HALF_ASYNC batch pushes on a background thread, GEO accumulates
  locally and ships deltas every k steps.
- `PSServer`/`PSClient` are the control-plane service (listen_and_serv
  parity) as a length-prefixed TCP protocol for multi-host; in-process
  tables skip the network entirely.
"""

import queue
import socket
import socketserver
import struct
import threading
import time

import numpy as np

__all__ = ["SparseEmbedding", "Communicator", "PSServer", "PSClient",
           "HeartBeatMonitor"]


def _scramble(ids):
    # same splitmix-style mix as the native shard so routing spreads
    # sequential feature ids uniformly
    x = ids.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    return (x >> np.uint64(32)).astype(np.int64)


class _PyShard:
    """Pure-python fallback with the NativeShard interface."""

    def __init__(self, dim, init_range=0.05, seed=0, optimizer="adagrad",
                 lr=0.05, adagrad_eps=1e-6):
        self.dim = dim
        self.init_range = init_range
        self.seed = seed
        self.opt = optimizer
        self.lr = lr
        self.eps = adagrad_eps
        self.rows = {}
        self.accs = {}

    def _row(self, i):
        r = self.rows.get(i)
        if r is None:
            rng = np.random.default_rng(self.seed ^ (i & 0x7FFFFFFF))
            r = rng.uniform(-self.init_range, self.init_range,
                            self.dim).astype(np.float32)
            self.rows[i] = r
            if self.opt == "adagrad":
                self.accs[i] = np.zeros(self.dim, np.float32)
        return r

    def set_lr(self, lr):
        self.lr = lr

    def pull(self, ids):
        return np.stack([self._row(int(i)) for i in ids]) if len(ids) \
            else np.zeros((0, self.dim), np.float32)

    def push(self, ids, grads):
        for i, g in zip(ids, np.asarray(grads, np.float32)):
            i = int(i)
            r = self._row(i)
            if self.opt == "adagrad":
                acc = self.accs[i]
                acc += g * g
                r -= self.lr * g / (np.sqrt(acc) + self.eps)
            else:
                r -= self.lr * g

    def assign(self, ids, vals):
        for i, v in zip(ids, np.asarray(vals, np.float32)):
            self._row(int(i))[:] = v

    def __len__(self):
        return len(self.rows)

    def export(self):
        ids = np.fromiter(self.rows.keys(), dtype=np.int64,
                          count=len(self.rows))
        vals = (np.stack([self.rows[int(i)] for i in ids])
                if len(ids) else np.zeros((0, self.dim), np.float32))
        return ids, vals

    @property
    def row_width(self):
        return 2 * self.dim if self.opt == "adagrad" else self.dim

    def export_full(self):
        ids, vals = self.export()
        if self.opt != "adagrad":
            return ids, vals
        accs = (np.stack([self.accs[int(i)] for i in ids])
                if len(ids) else np.zeros((0, self.dim), np.float32))
        return ids, np.concatenate([vals, accs], axis=1)

    def assign_full(self, ids, vals):
        vals = np.asarray(vals, np.float32)
        for i, v in zip(ids, vals):
            i = int(i)
            self._row(i)[:] = v[:self.dim]
            if self.opt == "adagrad" and vals.shape[1] == 2 * self.dim:
                self.accs[i][:] = v[self.dim:]


def _make_shard(dim, **kw):
    from .. import native

    if native.available():
        return native.NativeShard(dim, **kw)
    return _PyShard(dim, **kw)


class SparseEmbedding:
    """N-way sharded host-resident embedding table.

    Parity surface: distributed_lookup_table_op + parameter_prefetch.cc
    (slice ids by shard, fetch, re-gather in input order).
    """

    def __init__(self, dim, num_shards=4, optimizer="adagrad", lr=0.05,
                 init_range=0.05, seed=0, clients=None):
        self.dim = dim
        if clients is not None:          # remote mode: one client per shard
            self.shards = clients
        else:
            self.shards = [
                _make_shard(dim, init_range=init_range, seed=seed + i,
                            optimizer=optimizer, lr=lr)
                for i in range(num_shards)
            ]
        self.n = len(self.shards)

    def _route(self, ids):
        flat = np.ascontiguousarray(ids, dtype=np.int64).ravel()
        shard_of = _scramble(flat) % self.n
        return flat, shard_of

    def pull(self, ids):
        """ids: int array any shape -> [*shape, dim] float32."""
        ids = np.asarray(ids)
        flat, shard_of = self._route(ids)
        out = np.empty((flat.size, self.dim), np.float32)
        for s in range(self.n):
            m = shard_of == s
            if m.any():
                out[m] = self.shards[s].pull(flat[m])
        return out.reshape(*ids.shape, self.dim)

    def push(self, ids, grads):
        ids = np.asarray(ids)
        flat, shard_of = self._route(ids)
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            flat.size, self.dim)
        for s in range(self.n):
            m = shard_of == s
            if m.any():
                self.shards[s].push(flat[m], grads[m])

    def set_lr(self, lr):
        for s in self.shards:
            s.set_lr(lr)

    def __len__(self):
        return sum(len(s) for s in self.shards)

    def state_dict(self):
        """Full rows INCLUDING optimizer accumulators (adagrad), so a
        resumed run continues the uninterrupted trajectory — pserver
        table snapshots carry optimizer state too."""
        ids, vals = [], []
        full = all(hasattr(s, "export_full") for s in self.shards)
        for s in self.shards:
            i, v = (s.export_full() if full else s.export())
            ids.append(i)
            vals.append(v)
        width = vals[0].shape[1] if vals and len(vals[0]) else self.dim
        return {"ids": np.concatenate(ids) if ids else np.zeros(0, np.int64),
                "values": np.concatenate(vals) if vals
                else np.zeros((0, width), np.float32)}

    def load_state_dict(self, state):
        ids = np.asarray(state["ids"], np.int64)
        vals = np.asarray(state["values"], np.float32)
        flat, shard_of = self._route(ids)
        for s in range(self.n):
            m = shard_of == s
            if not m.any():
                continue
            shard = self.shards[s]
            if (vals.shape[1] > self.dim
                    and getattr(shard, "row_width", self.dim)
                    == vals.shape[1]):
                shard.assign_full(flat[m], vals[m])
            else:
                shard.assign(flat[m], vals[m][:, :self.dim])


class Communicator:
    """Batched gradient push with the reference's mode taxonomy
    (communicator.h:176 AsyncCommunicator/HalfAsync/Sync/GeoSgd).

    sync: push() forwards immediately.
    async/half_async: pushes queue to a background thread; half_async's
      barrier() drains the queue (the reference's batch-barrier).
    geo: local delta accumulation, shipped every `geo_steps` steps
      (GeoSgdCommunicator delta-sync).
    """

    def __init__(self, table, mode="async", geo_steps=10, max_merge=20):
        assert mode in ("sync", "async", "half_async", "geo")
        self.table = table
        self.mode = mode
        self.geo_steps = geo_steps
        self.max_merge = max_merge
        self._q = queue.Queue()
        self._stop = threading.Event()
        self._thread = None
        self._error = None
        self._geo_acc = {}
        self._step = 0
        if mode in ("async", "half_async"):
            self._thread = threading.Thread(target=self._worker, daemon=True)
            self._thread.start()

    def _worker(self):
        while not self._stop.is_set() or not self._q.empty():
            try:
                ids, grads = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            # merge a burst of pending pushes into one table update
            batch = [(ids, grads)]
            for _ in range(self.max_merge):
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
            try:
                all_ids = np.concatenate([b[0] for b in batch])
                all_grads = np.concatenate([b[1] for b in batch])
                self.table.push(all_ids, all_grads)
            except Exception as e:  # surface at the next push/barrier;
                self._error = e     # task_done must still run or join()
                self._stop.set()    # deadlocks
            finally:
                for _ in batch:
                    self._q.task_done()

    def _check_error(self):
        if self._error is not None:
            e, self._error = self._error, None
            raise RuntimeError("communicator background push failed") from e

    def push(self, ids, grads):
        self._check_error()
        ids = np.ascontiguousarray(np.asarray(ids).ravel(), np.int64)
        grads = np.ascontiguousarray(grads, np.float32).reshape(
            ids.size, self.table.dim)
        if self.mode == "sync":
            self.table.push(ids, grads)
        elif self.mode in ("async", "half_async"):
            self._q.put((ids, grads))
        else:  # geo: accumulate deltas locally
            for i, g in zip(ids, grads):
                i = int(i)
                if i in self._geo_acc:
                    self._geo_acc[i] = self._geo_acc[i] + g
                else:
                    self._geo_acc[i] = g.copy()
            self._step += 1
            if self._step % self.geo_steps == 0:
                self._flush_geo()

    def _flush_geo(self):
        if not self._geo_acc:
            return
        ids = np.fromiter(self._geo_acc.keys(), np.int64,
                          len(self._geo_acc))
        grads = np.stack([self._geo_acc[int(i)] for i in ids])
        self.table.push(ids, grads)
        self._geo_acc.clear()

    def barrier(self):
        """Drain pending pushes (half-async batch barrier)."""
        if self.mode == "geo":
            self._flush_geo()
        elif self._thread is not None:
            self._q.join()
        self._check_error()

    def stop(self):
        if self._thread is not None:
            self._q.join()
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None


# --------------------------------------------------------------------------
# TCP control plane (listen_and_serv parity)
# --------------------------------------------------------------------------

# -- wire codec ------------------------------------------------------------
# Fixed binary format, parity with the reference's proto wire schema
# (operators/distributed/send_recv.proto.in VariableMessage: name, type,
# dims, serialized tensor bytes).  A tagged value tree — scalars, strings,
# ndarrays (dtype + dims + raw buffer), lists, dicts — with NO embedded
# code paths: decoding can only ever produce data, unlike pickle, so a
# peer that reaches the port cannot gain execution.

_WIRE_MAGIC = b"PT"
_WIRE_VERSION = 1
(_T_NONE, _T_BOOL, _T_INT, _T_FLOAT, _T_STR, _T_BYTES, _T_NDARRAY,
 _T_LIST, _T_TUPLE, _T_DICT) = range(10)

_WIRE_DTYPES = {"bool", "int8", "int16", "int32", "int64", "uint8",
                "uint16", "uint32", "uint64", "float16", "float32",
                "float64"}


def _enc(obj, out):
    if obj is None:
        out.append(struct.pack("<B", _T_NONE))
    elif isinstance(obj, bool) or isinstance(obj, np.bool_):
        out.append(struct.pack("<BB", _T_BOOL, bool(obj)))
    elif isinstance(obj, (int, np.integer)):
        out.append(struct.pack("<Bq", _T_INT, int(obj)))
    elif isinstance(obj, (float, np.floating)):
        out.append(struct.pack("<Bd", _T_FLOAT, float(obj)))
    elif isinstance(obj, str):
        b = obj.encode()
        out.append(struct.pack("<BI", _T_STR, len(b)))
        out.append(b)
    elif isinstance(obj, (bytes, bytearray)):
        out.append(struct.pack("<BI", _T_BYTES, len(obj)))
        out.append(bytes(obj))
    elif isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        dt = str(a.dtype)
        if dt not in _WIRE_DTYPES:
            raise TypeError(f"dtype {dt} not wire-encodable")
        dtb = dt.encode()
        out.append(struct.pack("<BB", _T_NDARRAY, len(dtb)))
        out.append(dtb)
        out.append(struct.pack("<B", a.ndim))
        out.append(struct.pack(f"<{a.ndim}q", *a.shape))
        out.append(struct.pack("<Q", a.nbytes))
        out.append(a.tobytes())
    elif isinstance(obj, (list, tuple)):
        tag = _T_TUPLE if isinstance(obj, tuple) else _T_LIST
        out.append(struct.pack("<BI", tag, len(obj)))
        for v in obj:
            _enc(v, out)
    elif isinstance(obj, dict):
        out.append(struct.pack("<BI", _T_DICT, len(obj)))
        for k, v in obj.items():
            if not isinstance(k, str):
                raise TypeError("wire dict keys must be str")
            _enc(k, out)
            _enc(v, out)
    else:
        raise TypeError(f"{type(obj).__name__} not wire-encodable")


def _dec(buf, off):
    (tag,) = struct.unpack_from("<B", buf, off)
    off += 1
    if tag == _T_NONE:
        return None, off
    if tag == _T_BOOL:
        (v,) = struct.unpack_from("<B", buf, off)
        return bool(v), off + 1
    if tag == _T_INT:
        (v,) = struct.unpack_from("<q", buf, off)
        return v, off + 8
    if tag == _T_FLOAT:
        (v,) = struct.unpack_from("<d", buf, off)
        return v, off + 8
    if tag in (_T_STR, _T_BYTES):
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        raw = bytes(buf[off:off + n])
        if len(raw) != n:
            raise ValueError("truncated wire string")
        return (raw.decode() if tag == _T_STR else raw), off + n
    if tag == _T_NDARRAY:
        (dtl,) = struct.unpack_from("<B", buf, off)
        off += 1
        dt = bytes(buf[off:off + dtl]).decode("ascii")
        off += dtl
        if dt not in _WIRE_DTYPES:
            raise ValueError(f"wire format forbids dtype {dt!r}")
        (ndim,) = struct.unpack_from("<B", buf, off)
        off += 1
        shape = struct.unpack_from(f"<{ndim}q", buf, off)
        off += 8 * ndim
        (nbytes,) = struct.unpack_from("<Q", buf, off)
        off += 8
        expect = int(np.prod(shape, dtype=np.int64)) * np.dtype(dt).itemsize
        if nbytes != expect or off + nbytes > len(buf):
            raise ValueError("wire ndarray length mismatch")
        a = np.frombuffer(bytes(buf[off:off + nbytes]), dtype=dt)
        return a.reshape(shape), off + nbytes
    if tag in (_T_LIST, _T_TUPLE):
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        items = []
        for _ in range(n):
            v, off = _dec(buf, off)
            items.append(v)
        return (tuple(items) if tag == _T_TUPLE else items), off
    if tag == _T_DICT:
        (n,) = struct.unpack_from("<I", buf, off)
        off += 4
        d = {}
        for _ in range(n):
            k, off = _dec(buf, off)
            v, off = _dec(buf, off)
            d[k] = v
        return d, off
    raise ValueError(f"unknown wire tag {tag}")


def wire_dumps(obj):
    out = [_WIRE_MAGIC, struct.pack("<B", _WIRE_VERSION)]
    _enc(obj, out)
    return b"".join(out)


def wire_loads(data):
    if len(data) < 3 or data[:2] != _WIRE_MAGIC:
        raise ValueError("bad wire magic (not a paddle_tpu PS frame)")
    if data[2] != _WIRE_VERSION:
        raise ValueError(f"unsupported wire version {data[2]}")
    obj, off = _dec(data, 3)
    if off != len(data):
        raise ValueError("trailing bytes in wire frame")
    return obj


def _send_msg(sock, obj):
    data = wire_dumps(obj)
    sock.sendall(struct.pack("<Q", len(data)) + data)


def _recv_msg(sock, max_frame=1 << 34):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    if n > max_frame:
        raise ValueError(f"wire frame of {n} bytes exceeds limit")
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return wire_loads(bytes(buf))


class PSServer:
    """One embedding shard behind a TCP endpoint.  The wire format is
    the fixed binary codec above (send_recv.proto.in parity) — pure
    data, no deserialization code paths."""

    def __init__(self, dim, port=0, host="127.0.0.1",
                 heartbeat_timeout=60.0, **shard_kw):
        self.shard = _make_shard(dim, **shard_kw)
        self.monitor = HeartBeatMonitor(timeout=heartbeat_timeout)
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = _recv_msg(self.request)
                    except (ConnectionError, EOFError):
                        return
                    op = msg["op"]
                    if op == "pull":
                        _send_msg(self.request,
                                  outer.shard.pull(msg["ids"]))
                    elif op == "push":
                        outer.shard.push(msg["ids"], msg["grads"])
                        _send_msg(self.request, b"ok")
                    elif op == "assign":
                        outer.shard.assign(msg["ids"], msg["vals"])
                        _send_msg(self.request, b"ok")
                    elif op == "export":
                        _send_msg(self.request, outer.shard.export())
                    elif op == "export_full":
                        _send_msg(self.request, outer.shard.export_full())
                    elif op == "assign_full":
                        outer.shard.assign_full(msg["ids"], msg["vals"])
                        _send_msg(self.request, b"ok")
                    elif op == "row_width":
                        _send_msg(self.request, outer.shard.row_width)
                    elif op == "set_lr":
                        outer.shard.set_lr(msg["lr"])
                        _send_msg(self.request, b"ok")
                    elif op == "heartbeat":
                        outer.monitor.beat(msg["worker"])
                        _send_msg(self.request, b"ok")
                    elif op == "dead_workers":
                        _send_msg(self.request, outer.monitor.dead_workers())
                    elif op == "size":
                        _send_msg(self.request, len(outer.shard))
                    elif op == "shutdown":
                        _send_msg(self.request, b"ok")
                        threading.Thread(
                            target=outer.server.shutdown).start()
                        return
                    else:
                        _send_msg(self.request,
                                  {"error": f"unknown op {op}"})

        class Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.server = Srv((host, port), Handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


class PSClient:
    """Shard-interface proxy over one PSServer connection."""

    def __init__(self, host, port, dim):
        self.dim = dim
        self._sock = socket.create_connection((host, port))
        self._lock = threading.Lock()

    def _call(self, **msg):
        with self._lock:
            _send_msg(self._sock, msg)
            return _recv_msg(self._sock)

    def pull(self, ids):
        return self._call(op="pull", ids=np.asarray(ids, np.int64))

    def push(self, ids, grads):
        self._call(op="push", ids=np.asarray(ids, np.int64),
                   grads=np.asarray(grads, np.float32))

    def assign(self, ids, vals):
        self._call(op="assign", ids=np.asarray(ids, np.int64),
                   vals=np.asarray(vals, np.float32))

    def export(self):
        return self._call(op="export")

    def export_full(self):
        return self._call(op="export_full")

    def assign_full(self, ids, vals):
        self._call(op="assign_full", ids=np.asarray(ids, np.int64),
                   vals=np.asarray(vals, np.float32))

    @property
    def row_width(self):
        return int(self._call(op="row_width"))

    def set_lr(self, lr):
        self._call(op="set_lr", lr=float(lr))

    def heartbeat(self, worker_id):
        self._call(op="heartbeat", worker=worker_id)

    def __len__(self):
        return int(self._call(op="size"))

    def shutdown_server(self):
        self._call(op="shutdown")

    def close(self):
        self._sock.close()


class HeartBeatMonitor:
    """Worker-liveness watchdog (heart_beat_monitor.h:70 parity): workers
    ping; stale workers are reported dead after `timeout` seconds."""

    def __init__(self, timeout=60.0):
        self.timeout = timeout
        self._beats = {}
        self._lock = threading.Lock()

    def beat(self, worker_id):
        with self._lock:
            self._beats[worker_id] = time.time()

    def dead_workers(self, now=None):
        now = now if now is not None else time.time()
        with self._lock:
            return [w for w, t in self._beats.items()
                    if now - t > self.timeout]


class ShardedPSClient:
    """Route pulls/pushes across N PSServer endpoints by `id % N` — the
    trainer-side counterpart of the reference's table sharding across
    pservers (transpiler/distribute_transpiler.py slice_vars /
    communicator send routing).  Connections are lazy so the client can
    be constructed before the servers finish binding."""

    def __init__(self, endpoints, dim):
        self.endpoints = list(endpoints)
        self.dim = dim
        self._clients = [None] * len(self.endpoints)

    def _client(self, shard):
        if self._clients[shard] is None:
            host, port = self.endpoints[shard].rsplit(":", 1)
            self._clients[shard] = PSClient(host, int(port), self.dim)
        return self._clients[shard]

    def pull(self, ids):
        ids = np.asarray(ids, np.int64)
        flat = ids.reshape(-1)
        n = len(self.endpoints)
        out = np.zeros((flat.size, self.dim), np.float32)
        for s in range(n):
            m = (flat % n) == s
            if m.any():
                out[m] = self._client(s).pull(flat[m])
        return out.reshape(ids.shape + (self.dim,))

    def push(self, ids, grads):
        ids = np.asarray(ids, np.int64)
        flat = ids.reshape(-1)
        g = np.asarray(grads, np.float32).reshape(flat.size, self.dim)
        n = len(self.endpoints)
        for s in range(n):
            m = (flat % n) == s
            if m.any():
                self._client(s).push(flat[m], g[m])

    def close(self):
        for c in self._clients:
            if c is not None:
                try:
                    c._sock.close()
                except OSError:
                    pass
