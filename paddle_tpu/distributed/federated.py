"""Federated learning: FedAvg rounds over the PS control plane.

Parity: /root/reference/paddle/fluid/operators/distributed_ops/
fl_listen_and_serv_op.cc — the reference's federated server is a
listen_and_serv variant that collects client-trained parameters each
round and averages them. Here the server is a small TCP service (same
trusted-transport model as distributed/ps.py) holding the global dense
model; clients run local train steps on private data, push
sample-weighted parameter updates, and block on the next global round.

TPU-native stance: the per-client local training step is the same jitted
train step used everywhere else; federation is purely a host-side
control-plane concern (weight exchange between processes/hosts over
DCN), so no graph surgery is involved — matching SURVEY §7's "host-side
service" boundary for PS-style training.
"""

import socket
import socketserver
import threading

import numpy as np

from .ps import _recv_msg, _send_msg


def _tree_avg(updates):
    """Sample-weighted average of [(params_dict, n_samples), ...]."""
    total = float(sum(n for _, n in updates))
    keys = updates[0][0].keys()
    out = {}
    for k in keys:
        acc = None
        for params, n in updates:
            term = np.asarray(params[k], np.float32) * (n / total)
            acc = term if acc is None else acc + term
        out[k] = acc
    return out


class FLServer:
    """FedAvg coordinator: one round = every registered client pushes a
    (params, n_samples) update; the server averages and bumps the model
    version (fl_listen_and_serv's aggregate step)."""

    def __init__(self, init_params, num_clients, port=0, host="127.0.0.1"):
        self.params = {k: np.asarray(v, np.float32)
                       for k, v in init_params.items()}
        self.num_clients = int(num_clients)
        self.version = 0
        self._pending = []
        self._cond = threading.Condition()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = _recv_msg(self.request)
                    except (ConnectionError, EOFError):
                        return
                    op = msg["op"]
                    if op == "get_model":
                        with outer._cond:
                            _send_msg(self.request,
                                      {"version": outer.version,
                                       "params": outer.params})
                    elif op == "push_update":
                        with outer._cond:
                            outer._pending.append(
                                (msg["params"], msg["num_samples"]))
                            if len(outer._pending) >= outer.num_clients:
                                outer.params = _tree_avg(outer._pending)
                                outer._pending = []
                                outer.version += 1
                                outer._cond.notify_all()
                        _send_msg(self.request, b"ok")
                    elif op == "wait_version":
                        want = msg["version"]
                        with outer._cond:
                            ok = outer._cond.wait_for(
                                lambda: outer.version >= want,
                                timeout=msg.get("timeout", 120.0))
                            _send_msg(self.request,
                                      {"version": outer.version,
                                       "params": outer.params,
                                       "timed_out": not ok})
                    elif op == "shutdown":
                        _send_msg(self.request, b"ok")
                        threading.Thread(
                            target=outer.server.shutdown).start()
                        return
                    else:
                        _send_msg(self.request,
                                  {"error": f"unknown op {op}"})

        class Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.server = Srv((host, port), Handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self.server.shutdown()
        self.server.server_close()


class FLClient:
    """Client-side proxy: pull the global model, push a local update,
    block for the next aggregated round."""

    def __init__(self, host, port):
        self._sock = socket.create_connection((host, port))
        self._lock = threading.Lock()

    def _call(self, **msg):
        with self._lock:
            _send_msg(self._sock, msg)
            return _recv_msg(self._sock)

    def get_model(self):
        r = self._call(op="get_model")
        return r["version"], r["params"]

    def push_update(self, params, num_samples):
        self._call(op="push_update",
                   params={k: np.asarray(v, np.float32)
                           for k, v in params.items()},
                   num_samples=int(num_samples))

    def wait_version(self, version, timeout=120.0):
        r = self._call(op="wait_version", version=version, timeout=timeout)
        if r.get("timed_out"):
            raise TimeoutError(
                f"wait_version({version}) timed out after {timeout}s; "
                f"server is still at version {r['version']}")
        return r["version"], r["params"]

    def shutdown_server(self):
        self._call(op="shutdown")

    def close(self):
        self._sock.close()


def run_fl_round(client, local_train_fn, num_samples):
    """One client-side FedAvg round: pull -> local train -> push -> wait.

    local_train_fn(params) -> new_params runs the client's private
    optimization (typically several jitted train steps).
    Returns (new_version, new_global_params).
    """
    version, params = client.get_model()
    new_params = local_train_fn(params)
    client.push_update(new_params, num_samples)
    return client.wait_version(version + 1)
