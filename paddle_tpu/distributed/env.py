"""Multi-host environment + rendezvous.

Parity: the reference's process-level bootstrap — launch.py env vars
(PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT,
/root/reference/python/paddle/distributed/launch.py:175) and ParallelEnv
(python/paddle/fluid/dygraph/parallel.py:54).  The nccl-id RPC rendezvous
(operators/collective/c_gen_nccl_id_op.cc:36) maps to
jax.distributed.initialize over DCN.
"""

import os

import jax

__all__ = ["ParallelEnv", "init_parallel_env", "get_rank", "get_world_size"]

_initialized = False


class ParallelEnv:
    """Parity: dygraph/parallel.py:54."""

    def __init__(self):
        self._nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self._local_rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._endpoints = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def dev_id(self):
        return self._local_rank

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._endpoints.split(",") if self._endpoints else []


def _maybe_enable_cpu_collectives():
    """Multi-process collectives on the CPU backend need the gloo
    transport switched on BEFORE the backend initialises (without it
    XLA:CPU fails every cross-process psum with "Multiprocess
    computations aren't implemented on the CPU backend").  Only the
    declared-platform config is consulted — calling
    jax.default_backend() here would itself initialise the backend and
    make the flag a no-op."""
    platforms = (getattr(jax.config, "jax_platforms", None)
                 or os.environ.get("JAX_PLATFORMS", ""))
    if not platforms.split(",")[0].strip().lower() == "cpu":
        return
    try:
        jax.config.update("jax_cpu_enable_gloo_collectives", True)
    except Exception:  # pragma: no cover — jax without the gloo option
        pass


def init_parallel_env():
    """Multi-host init. On a single host this is a no-op (the mesh covers
    local devices); with PADDLE_TRAINER_ENDPOINTS set it performs the DCN
    rendezvous via jax.distributed.initialize (replacing gen_nccl_id's RPC
    broadcast)."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    env = ParallelEnv()
    if env.nranks > 1 and env.trainer_endpoints:
        coordinator = env.trainer_endpoints[0]
        _maybe_enable_cpu_collectives()
        kwargs = {}
        # bounded rendezvous (reference launch.py aborts the pack when a
        # worker dies; an unbounded initialize would hang instead)
        timeout = os.environ.get("PADDLE_RENDEZVOUS_TIMEOUT")
        if timeout:
            kwargs["initialization_timeout"] = int(timeout)
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=env.nranks,
            process_id=env.local_rank,
            **kwargs,
        )
    _initialized = True
    return env


def get_rank():
    return getattr(jax, "process_index", lambda: 0)()


def get_world_size():
    return getattr(jax, "process_count", lambda: 1)()
