"""Multi-process launcher: ``python -m paddle_tpu.distributed.launch``.

Parity: /root/reference/python/paddle/distributed/launch.py — start_procs
(:175) spawns one worker per device with the trainer env contract
(PADDLE_TRAINER_ID, PADDLE_CURRENT_ENDPOINT, PADDLE_TRAINER_ENDPOINTS,
PADDLE_TRAINERS_NUM) and a log dir; failures of any worker terminate the
pack.

TPU shape: the reference launches one process per GPU; a TPU pod runs one
process per HOST (each owning its local chips), so ``--nproc_per_node``
defaults to 1 and ``--cluster_node_ips`` enumerates hosts. Worker 0's
endpoint doubles as the jax.distributed coordinator
(env.init_parallel_env). For tests, multiple workers on localhost with
JAX pinned to CPU exercise the same contract.
"""

import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["launch", "start_procs", "find_free_ports"]


def find_free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="spawn distributed training workers "
                    "(launch.py:175 parity)")
    p.add_argument("--cluster_node_ips", default="127.0.0.1",
                   help="comma-separated host ips")
    p.add_argument("--node_ip", default="127.0.0.1",
                   help="this node's ip")
    p.add_argument("--started_port", type=int, default=None)
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="workers per node (1 per TPU host)")
    p.add_argument("--log_dir", default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def start_procs(node_ips, node_ip, nproc_per_node, training_script,
                script_args=(), started_port=None, log_dir=None,
                env_extra=None):
    """Spawn nproc_per_node workers for THIS node; returns (procs, logs).

    The endpoint list covers every node so each worker sees the global
    cluster (PADDLE_TRAINER_ENDPOINTS), while PADDLE_TRAINER_ID counts
    globally across nodes — the reference's contract."""
    node_ips = list(node_ips)
    if started_port is None:
        if len(node_ips) > 1:
            # every node must compute the SAME global endpoint list, so
            # multi-node runs need a deterministic port (reference default
            # 6170, launch.py); random free ports are single-node only
            started_port = 6170
            ports = [started_port + i for i in range(nproc_per_node)]
        else:
            ports = find_free_ports(nproc_per_node)
    else:
        ports = [started_port + i for i in range(nproc_per_node)]
    endpoints = [f"{ip}:{port}" for ip in node_ips for port in ports]
    node_idx = node_ips.index(node_ip)
    nranks = len(node_ips) * nproc_per_node

    if log_dir:
        os.makedirs(log_dir, exist_ok=True)
    procs, logs = [], []
    for local_i in range(nproc_per_node):
        rank = node_idx * nproc_per_node + local_i
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_TRAINERS_NUM": str(nranks),
            "FLAGS_selected_devices": str(local_i),
        })
        env.update(env_extra or {})
        log_f = None
        if log_dir:
            log_f = open(os.path.join(log_dir, f"workerlog.{rank}"), "w")
            logs.append(log_f)
        procs.append(subprocess.Popen(
            [sys.executable, "-u", training_script, *script_args],
            env=env, stdout=log_f, stderr=subprocess.STDOUT if log_f
            else None))
    return procs, logs


def _wait(procs, logs):
    """Wait for all workers; on any failure terminate the rest (launch.py
    watch loop parity)."""
    rc = 0
    try:
        alive = set(range(len(procs)))
        while alive:
            for i in list(alive):
                r = procs[i].poll()
                if r is None:
                    continue
                alive.discard(i)
                if r != 0:
                    rc = r
                    for j in alive:
                        procs[j].send_signal(signal.SIGTERM)
                    for j in alive:
                        try:
                            procs[j].wait(timeout=10)
                        except subprocess.TimeoutExpired:
                            procs[j].kill()
                    alive = set()
                    break
            time.sleep(0.1)
    finally:
        for f in logs:
            f.close()
    return rc


def launch(argv=None):
    args = _parse_args(argv)
    node_ips = args.cluster_node_ips.split(",")
    procs, logs = start_procs(
        node_ips, args.node_ip, args.nproc_per_node,
        args.training_script, args.training_script_args,
        started_port=args.started_port, log_dir=args.log_dir)
    return _wait(procs, logs)


if __name__ == "__main__":
    sys.exit(launch())
