"""Anomaly guard — on-device all-finite check with a recovery policy.

Parity role: the reference's FLAGS_check_nan_inf checks every op
output on the host (operator.cc:1032) — useful for debugging, ruinous
for throughput.  The guard instead fuses ONE cheap reduction into the
compiled train step (isfinite over each section's loss and gradients,
AND-ed to a single scalar riding back with the fetches) and lets a
policy decide what an anomalous step means:

- ``raise``     — stop the run with AnomalyError (CI / debugging).
- ``skip_step`` — commit nothing: the compiled step selects the OLD
  state when the flag is down (the select is on-device, so a skipped
  step costs no extra sync beyond the flag read), counts it, and
  training continues with the next batch.  This is exactly the
  dynamic-loss-scaling skip of the AMP path, generalized to any
  program.
- ``rollback``  — restore the newest complete checkpoint through a
  CheckpointManager and signal the training loop (RollbackPerformed)
  to rewind its data cursor and replay the consumed batches.

AMP integration: the static-graph AMP decorator scales the loss before
backward, so the guard's gradient check sees SCALED grads — overflow
detection at the same point update_loss_scaling samples; with bf16
(no scaling) the check degenerates to a plain finiteness test.
"""

import threading

import jax
import jax.numpy as jnp

__all__ = ["AnomalyGuard", "AnomalyError", "RollbackPerformed",
           "enable_anomaly_guard", "disable_anomaly_guard",
           "anomaly_guard", "active_guard", "all_finite"]

POLICIES = ("raise", "skip_step", "rollback")


class AnomalyError(FloatingPointError):
    """A guarded step produced non-finite loss/gradients under the
    `raise` policy (or a policy escalated after repeated anomalies)."""


class RollbackPerformed(RuntimeError):
    """The guard restored checkpoint `step` into the scope; the
    training loop must rewind its data cursor to that step and replay.
    Executor.train_from_dataset handles this itself; bare Executor.run
    loops catch it and reset their batch index to `step`."""

    def __init__(self, step):
        super().__init__(
            f"anomaly guard rolled state back to checkpoint step {step}; "
            f"replay data from there")
        self.step = step


def all_finite(tree):
    """Single-scalar finiteness over a pytree of float leaves (the
    same reduction amp's loss-scaler uses).  Non-float leaves — int
    counters, rng keys — are finite by construction and skipped, but
    dtype-LESS Python floats (an eagerly accumulated loss) are
    promoted and checked: float('nan') must not slip through."""
    checks = []
    for x in jax.tree.leaves(tree):
        a = x if hasattr(x, "dtype") else jnp.asarray(x)
        if jnp.issubdtype(a.dtype, jnp.floating):
            checks.append(jnp.all(jnp.isfinite(a)))
    if not checks:
        return jnp.asarray(True)
    return jnp.stack(checks).all()


class AnomalyGuard:
    """Active guard configuration.

    policy:          one of POLICIES.
    manager:         CheckpointManager (required for ``rollback``).
    max_consecutive: escalate to AnomalyError after this many
                     anomalous steps IN A ROW — a persistent numeric
                     bug must not skip/rollback forever (the
                     reference's loss scaler has the same escape:
                     scale bottoms out at 1.0 and the run dies).
    max_rollbacks:   total rollbacks before escalating.
    """

    def __init__(self, policy="raise", manager=None, max_consecutive=10,
                 max_rollbacks=3):
        if policy not in POLICIES:
            raise ValueError(
                f"unknown anomaly policy {policy!r}; pick from {POLICIES}")
        if policy == "rollback" and manager is None:
            raise ValueError(
                "rollback policy needs a CheckpointManager (manager=...)")
        self.policy = policy
        self.manager = manager
        self.max_consecutive = max_consecutive
        self.max_rollbacks = max_rollbacks
        self._lock = threading.Lock()
        self.consecutive = 0
        self.rollbacks = 0
        # True exactly when the most recent guarded step was skipped —
        # the signal train_from_dataset's sparse-push path reads so a
        # skipped step's NaN gradient rows never reach the tables
        self.last_skipped = False

    # -- bookkeeping called by the executor ---------------------------
    def note_ok(self):
        with self._lock:
            self.consecutive = 0
            self.last_skipped = False

    def note_anomaly(self):
        """Count one anomalous step; returns True when the policy
        should still apply, raises AnomalyError when escalation is
        due."""
        with self._lock:
            self.consecutive += 1
            escalate = self.consecutive > self.max_consecutive
        if escalate:
            # dump OUTSIDE the guard lock: the recorder snapshots the
            # monitor registry, and holding two subsystem locks across
            # each other is how deadlocks are born
            _flight_dump(
                f"anomaly_guard:max_consecutive={self.max_consecutive}")
            raise AnomalyError(
                f"{self.consecutive} consecutive anomalous steps "
                f"exceed max_consecutive={self.max_consecutive}; "
                f"escalating past policy {self.policy!r}")
        return True

    def note_rollback(self):
        with self._lock:
            self.rollbacks += 1
            escalate = self.rollbacks > self.max_rollbacks
        if escalate:
            _flight_dump(
                f"anomaly_guard:max_rollbacks={self.max_rollbacks}")
            raise AnomalyError(
                f"{self.rollbacks} rollbacks exceed max_rollbacks="
                f"{self.max_rollbacks}; the anomaly is not transient")


def _flight_dump(reason):
    """Escalations are normally CAUGHT by driver code (CI harnesses,
    retry loops), so the excepthook may never see them: write the
    post-mortem at the escalation point.  Never raises — diagnostics
    must not mask the AnomalyError being thrown."""
    try:
        from ..monitor import flight_recorder

        flight_recorder.note_event("anomaly_escalation", severe=True,
                                   reason=reason)
        flight_recorder.dump(reason)
    except Exception:
        pass


_active = None


def enable_anomaly_guard(policy="raise", manager=None, **kw):
    """Install a process-wide guard; compiled train steps built while
    a guard is active carry the fused finite check (the executor's
    compiled-fn cache keys on this, so toggling is safe)."""
    global _active
    _active = AnomalyGuard(policy=policy, manager=manager, **kw)
    return _active


def disable_anomaly_guard():
    global _active
    _active = None


def active_guard():
    return _active


class anomaly_guard:
    """Context-manager form, restoring the previous guard on exit."""

    def __init__(self, policy="raise", manager=None, **kw):
        self._guard = AnomalyGuard(policy=policy, manager=manager, **kw)

    def __enter__(self):
        global _active
        self._prev = _active
        _active = self._guard
        return self._guard

    def __exit__(self, *exc):
        global _active
        _active = self._prev
        return False
