"""Error taxonomy — transient vs fatal classification for retry.

Parity role: the reference's trainer restart semantics distinguish
worker deaths the fleet recovers from (pserver timeout, barrier lost,
preempted trainer — fleet re-launches the worker) from programming
errors that must fail the job (shape mismatch, missing var).  Here the
same split drives the retry/backoff layer: only errors classified
TRANSIENT are retried; everything else fails fast with the original
traceback.

Classification is TABLE-driven (not a type check buried in a retry
loop) so new failure shapes are one row, and the table itself is
inspectable/testable.  Two axes:

- exception TYPE: connection/timeout OS errors are transient;
  Python programming errors (TypeError, KeyError, ...) are fatal no
  matter what their message says.
- MESSAGE pattern: jaxlib surfaces XLA/PJRT status codes as
  `XlaRuntimeError` with the gRPC code name in the message
  (RESOURCE_EXHAUSTED, UNAVAILABLE, ...), so the code word — not the
  exception type — carries the taxonomy.
"""

import re

__all__ = ["TRANSIENT", "FATAL", "DEADLINE", "PREEMPTION", "classify",
           "is_transient", "is_oom", "is_deadline", "is_preemption",
           "is_failover",
           "DeadlineExceeded", "InjectedTransientError", "InjectedCrash",
           "TAXONOMY"]

TRANSIENT = "transient"
FATAL = "fatal"
# a request/dispatch ran out of TIME BUDGET (shed in a serving queue,
# stalled past the hang watchdog's threshold).  Distinct from TRANSIENT
# on purpose: retrying is exactly wrong — the budget is already spent,
# so the only honest outcome is a fast classified failure the caller
# can act on (shed load, re-issue with a fresh budget).
DEADLINE = "deadline"
# a PEER (or this rank's own slice) went away: the platform preempted a
# worker, the jax.distributed coordination service lost a heartbeat, a
# collective's transport hit a dead socket.  Retry-worthy BY DEFAULT
# (is_transient covers it — a blip and a death look identical from one
# throw), but a distinct category so the elastic coordinator and the
# retry path agree on what "a rank died" looks like: while an
# ElasticCoordinator is active, retry fails fast on PREEMPTION and
# hands recovery to the topology-change path instead of blind-redialing
# a dead peer through the whole backoff schedule (ISSUE 11).
PREEMPTION = "preemption"


class DeadlineExceeded(RuntimeError):
    """A request exceeded its time budget — shed from the serving
    queue before dispatch, or expired while a dispatch was in flight.
    Classified DEADLINE by TYPE (never retried: the budget is gone);
    `elapsed_s`/`budget_s` carry the forensics when known."""

    def __init__(self, msg, elapsed_s=None, budget_s=None):
        super().__init__(msg)
        self.elapsed_s = elapsed_s
        self.budget_s = budget_s


class InjectedTransientError(RuntimeError):
    """Synthetic device/runtime error raised by the fault-injection
    harness; classified transient by TYPE so retry tests exercise the
    real classification path."""


class InjectedCrash(BaseException):
    """Synthetic SIGKILL stand-in raised at a crash point.  Derives
    from BaseException so no `except Exception` cleanup handler can
    swallow it — like the real signal, nothing downstream of the crash
    point runs (the _COMPLETE marker is never written)."""


# message patterns for XLA/PJRT/distributed-runtime status codes and
# preemption-shaped infrastructure failures.  Order matters: first
# match wins, and fatal codes are listed before the broad transient
# net so e.g. "INVALID_ARGUMENT: ... was ABORTED" stays fatal.
_MESSAGE_RULES = (
    # -- preemption-shaped, TIGHTLY-ANCHORED dead-peer transport/
    # control-plane shapes (ISSUE 11): these precede even the fatal
    # status codes because a dead peer's gloo collective surfaces as
    # "FAILED_PRECONDITION: ... Gloo all-reduce failed: ... Connection
    # reset by peer" (observed on the CPU backend) and the specific
    # shape must win over the generic code.  ONLY phrases that cannot
    # plausibly appear in a programming error's text belong up here —
    # a bare word like "heartbeat" does not (an "INVALID_ARGUMENT:
    # heartbeat_interval must be positive" must stay fatal), so the
    # broader shapes rank BELOW the fatal codes.
    (re.compile(r"socket closed|connection reset|broken pipe",
                re.IGNORECASE), PREEMPTION),
    (re.compile(r"coordination service", re.IGNORECASE), PREEMPTION),
    (re.compile(r"barrier.{0,40}(time.?out|timed.?out)|"
                r"(time.?out|timed.?out).{0,40}barrier",
                re.IGNORECASE), PREEMPTION),
    # -- fatal status codes: the program itself is wrong --------------
    (re.compile(r"\bINVALID_ARGUMENT\b"), FATAL),
    (re.compile(r"\bFAILED_PRECONDITION\b"), FATAL),
    (re.compile(r"\bUNIMPLEMENTED\b"), FATAL),
    (re.compile(r"\bOUT_OF_RANGE\b"), FATAL),
    (re.compile(r"\bPERMISSION_DENIED\b"), FATAL),
    (re.compile(r"\bUNAUTHENTICATED\b"), FATAL),
    # -- preemption-shaped, broader: the platform took a worker/device
    # back, or the control plane says a peer is gone.  One category
    # (PREEMPTION) for every "a rank died" shape so the retry path and
    # the elastic coordinator classify them identically instead of
    # falling through to a blind TRANSIENT retry — but AFTER the fatal
    # codes, so a status-coded programming error whose text merely
    # mentions one of these words stays fatal.  Still BEFORE the
    # transient codes: "UNAVAILABLE: ... missing heartbeats" is a rank
    # death, not a generic blip.
    (re.compile(r"preempt", re.IGNORECASE), PREEMPTION),
    (re.compile(r"slice.*restart|restart.*slice", re.IGNORECASE),
     PREEMPTION),
    (re.compile(r"heartbeat", re.IGNORECASE), PREEMPTION),
    (re.compile(r"(peer|worker|task|process)"
                r".{0,40}(disconnect|unreachable|shut ?down|terminated|"
                r"exited|closed)", re.IGNORECASE), PREEMPTION),
    (re.compile(r"device.*(lost|halted|reset)", re.IGNORECASE),
     PREEMPTION),
    # -- transient status codes: infrastructure, not the program ------
    (re.compile(r"\bRESOURCE_EXHAUSTED\b"), TRANSIENT),
    (re.compile(r"\bUNAVAILABLE\b"), TRANSIENT),
    (re.compile(r"\bDEADLINE_EXCEEDED\b"), TRANSIENT),
    (re.compile(r"\bABORTED\b"), TRANSIENT),
    (re.compile(r"\bCANCELLED\b"), TRANSIENT),
)

# exception TYPES classified without looking at the message.  Python
# programming errors fail fast even if their text happens to contain a
# transient-looking word (an error note quoting a log line, say).
_FATAL_TYPES = (
    TypeError, KeyError, AttributeError, IndexError, NotImplementedError,
    AssertionError, NameError, ImportError, SyntaxError,
)
_TRANSIENT_TYPES = (
    InjectedTransientError, TimeoutError,
)
# connection-level OS errors are how a dead peer manifests locally
# (gloo/PJRT surface SIGKILL'd ranks as resets and broken pipes), so
# they classify PREEMPTION by TYPE — is_transient still covers them,
# but the elastic coordinator sees them as a rank death.  A bare
# TimeoutError stays TRANSIENT: a slow socket is not a dead one.
_PREEMPTION_TYPES = (ConnectionError, BrokenPipeError)

# -- dump triggers (ISSUE 6): failure shapes that warrant a flight-
# recorder post-mortem BEFORE the error propagates.  Orthogonal to the
# transient/fatal axis — a RESOURCE_EXHAUSTED is *retried* (transient)
# AND *explained* (the executor writes the peak-HBM table + live-bytes
# timeline via flight_recorder.dump_oom when one finally surfaces).
_OOM_PATTERN = re.compile(
    r"\bRESOURCE_EXHAUSTED\b|\bout of memory\b|\ballocation fail",
    re.IGNORECASE)

# deadline/timeout-shaped failure text (ISSUE 8): a shed or stalled
# request must classify distinctly from generic transients — is_deadline
# walks the cause/context chain like is_oom, so a RetriesExhausted (or a
# serving-layer wrapper) around a watchdog stall still reads as one.
# Like OOM, deadline-shaped death is a flight-recorder dump trigger:
# the serving watchdog dumps the in-flight batch's metadata before
# escalating.
_DEADLINE_PATTERN = re.compile(
    r"\bDEADLINE_EXCEEDED\b|deadline exceeded|timed out\b"
    r"|watchdog stall", re.IGNORECASE)

# deadline-shaped exception TYPES for classify(): checked FIRST — a
# DeadlineExceeded whose message quotes a transient-looking log line
# must still fail fast.  (TimeoutError stays in _TRANSIENT_TYPES for
# classify — a bare socket timeout is retry-worthy — but is_deadline
# still recognizes it on the orthogonal axis.)
_DEADLINE_TYPES = (DeadlineExceeded,)

# the full inspectable table (used by the README and tests)
TAXONOMY = {
    "fatal_types": tuple(t.__name__ for t in _FATAL_TYPES),
    "transient_types": tuple(t.__name__ for t in _TRANSIENT_TYPES),
    "preemption_types": tuple(t.__name__ for t in _PREEMPTION_TYPES),
    "deadline_types": tuple(t.__name__ for t in _DEADLINE_TYPES),
    "message_rules": tuple((p.pattern, cls) for p, cls in _MESSAGE_RULES),
    "dump_triggers": {"oom": _OOM_PATTERN.pattern,
                      "deadline": _DEADLINE_PATTERN.pattern},
    # the fleet router's failover rule (ISSUE 19): which classes route
    # a per-replica failure onto a DIFFERENT replica
    "failover_classes": (TRANSIENT, PREEMPTION),
}


def classify(exc):
    """TRANSIENT, FATAL, DEADLINE or PREEMPTION for one exception
    instance.

    Precedence: deadline types > preemption types > transient types >
    fatal types > message rules > FATAL.  (An InjectedTransientError is
    a RuntimeError subclass; the type check must see it before any
    message rule fires.  A raw XLA "DEADLINE_EXCEEDED" status message
    on a non-DeadlineExceeded type stays TRANSIENT — a collective
    rendezvous timeout is infrastructure and retry-worthy; only the
    runtime's own budget-expiry type means the budget is spent.)
    """
    if isinstance(exc, _DEADLINE_TYPES):
        return DEADLINE
    if isinstance(exc, _PREEMPTION_TYPES):
        return PREEMPTION
    if isinstance(exc, _TRANSIENT_TYPES):
        return TRANSIENT
    if isinstance(exc, _FATAL_TYPES):
        return FATAL
    msg = str(exc)
    for pattern, cls in _MESSAGE_RULES:
        if pattern.search(msg):
            return cls
    return FATAL


def is_transient(exc):
    """Retry-worthy: TRANSIENT or PREEMPTION.  A single throw cannot
    distinguish a network blip from a dead peer, so without an elastic
    coordinator the preemption shapes keep their historical
    retry-and-pray behavior; retry.py itself fails fast on PREEMPTION
    while a coordinator is active (it owns the recovery)."""
    return classify(exc) in (TRANSIENT, PREEMPTION)


def is_oom(exc):
    """True when `exc` is a memory-exhaustion failure — a MemoryError,
    or an XLA/PJRT RESOURCE_EXHAUSTED / out-of-memory message anywhere
    in the exception or its cause/context chain (a RetriesExhausted
    wrapping an OOM still reads as one).  The executor treats OOM as a
    DUMP TRIGGER: the flight recorder writes the peak-HBM post-mortem
    before the error propagates."""
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if isinstance(exc, MemoryError):
            return True
        if _OOM_PATTERN.search(str(exc)):
            return True
        exc = exc.__cause__ or exc.__context__
    return False


def is_preemption(exc):
    """True when `exc` is a rank-death / preemption-shaped failure —
    classified PREEMPTION anywhere in its cause/context chain (a
    RetriesExhausted wrapping a dead-peer connection reset still reads
    as one, like is_oom/is_deadline).  This is the single definition of
    "a rank died" the retry path and the elastic coordinator share:
    what retry refuses to blind-redial while a coordinator is active is
    exactly what the coordinator turns into a topology change."""
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if classify(exc) == PREEMPTION:
            return True
        exc = exc.__cause__ or exc.__context__
    return False


def is_failover(exc):
    """True when a per-REPLICA failure should be retried on a
    DIFFERENT replica (the fleet router's failover rule, ISSUE 19) —
    distinct from plain retry: the same-replica budget is irrelevant
    because the router moves the request sideways instead of waiting
    out a backoff schedule against a dead socket.

    Failover-worthy: the transient and preemption shapes — a replica
    connection reset / RemoteDisconnected (its process was SIGKILL'd
    mid-request), an overload 503, a generic infrastructure blip.
    NOT failover-worthy: deadline shapes (the budget is spent — moving
    replicas cannot un-spend it) and fatal shapes (a bad request fails
    identically everywhere; re-running it N more times only multiplies
    the damage).  Walks the cause/context chain like is_oom/is_deadline
    so a router-side wrapper around the transport error still routes
    correctly — with deadline links checked first at every hop, since
    an expired budget must win over whatever transient noise the
    expiry surfaced alongside."""
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        cls = classify(exc)
        if cls == DEADLINE:
            return False
        if cls in (TRANSIENT, PREEMPTION):
            return True
        exc = exc.__cause__ or exc.__context__
    return False


def is_deadline(exc):
    """True when `exc` is a deadline/timeout-shaped failure — a
    DeadlineExceeded or TimeoutError, or a DEADLINE_EXCEEDED /
    "deadline exceeded" / watchdog-stall message anywhere in the
    exception or its cause/context chain (a RetriesExhausted wrapping a
    stalled dispatch still reads as one).  Orthogonal to classify():
    the serving layer uses it to count shed/stalled requests distinctly
    from generic transients and to trigger the watchdog's
    flight-recorder dump."""
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        if isinstance(exc, _DEADLINE_TYPES + (TimeoutError,)):
            return True
        if _DEADLINE_PATTERN.search(str(exc)):
            return True
        exc = exc.__cause__ or exc.__context__
    return False
