"""Retry with jittered exponential backoff for transient failures.

Parity role: the reference's fleet keeps training alive across worker
hiccups by re-launching (fleet_util / trainer restart); on TPU the
equivalent granularity is the single dispatched step — an XLA
RESOURCE_EXHAUSTED or a preemption-shaped runtime error is retried in
place after a backoff, while programming errors (see taxonomy.py) fail
fast on the first throw.

Determinism: the jitter source and the sleep function are both
injectable, so tests (and the fault-injection harness) observe the
exact delay sequence without wall-clock waits.
"""

import random
import time

from .taxonomy import classify, PREEMPTION, TRANSIENT

__all__ = ["RetryPolicy", "call_with_retry", "RetriesExhausted"]

# retryable categories: a single throw cannot tell a network blip from
# a dead peer, so preemption-shaped failures keep their historical
# retry behavior — UNLESS an elastic coordinator is active (below)
_RETRYABLE = (TRANSIENT, PREEMPTION)


class RetriesExhausted(RuntimeError):
    """All retry attempts failed; `last_error` holds the final throw
    (also chained as __cause__) and `attempts` the total call count."""

    def __init__(self, attempts, last_error):
        super().__init__(
            f"transient failure persisted through {attempts} attempts: "
            f"{type(last_error).__name__}: {last_error}")
        self.attempts = attempts
        self.last_error = last_error


class RetryPolicy:
    """max_retries retries (max_retries+1 total attempts) with
    delay_n = min(max_delay, base_delay * multiplier**n), each scaled
    by a uniform jitter in [1-jitter, 1+jitter] — the decorrelation
    that keeps a gang of preempted workers from re-dialing the
    coordinator in lockstep.

    `sleep` and `rng` are injectable for deterministic tests; `seed`
    builds a private PRNG so two policies never share jitter streams.
    """

    def __init__(self, max_retries=5, base_delay=0.5, max_delay=30.0,
                 multiplier=2.0, jitter=0.25, sleep=time.sleep, seed=None):
        if max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.max_retries = max_retries
        self.base_delay = base_delay
        self.max_delay = max_delay
        self.multiplier = multiplier
        self.jitter = jitter
        self.sleep = sleep
        self.rng = random.Random(seed)

    def delay(self, attempt):
        """Backoff before retry number `attempt` (0-based), jittered."""
        d = min(self.max_delay,
                self.base_delay * (self.multiplier ** attempt))
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * self.rng.random() - 1.0)
        return d


def _mon():
    from .. import monitor

    return monitor


def _fr():
    from ..monitor import flight_recorder

    return flight_recorder


def _elastic_active():
    """Lazy, cycle-free probe for an installed ElasticCoordinator —
    the signal that rank-death recovery belongs to the topology-change
    path, not the backoff loop."""
    from . import elastic

    return elastic.active_coordinator() is not None


def call_with_retry(fn, policy=None, classify_fn=classify,
                    on_retry=None):
    """Run `fn()`; on a TRANSIENT (or preemption-shaped) throw, back
    off and retry up to policy.max_retries times.  Fatal errors
    propagate immediately with their original traceback.  Exhausted
    retries raise RetriesExhausted chaining the last error.

    PREEMPTION-category failures (dead peer, lost heartbeat, barrier
    timeout — taxonomy.is_preemption) are retried like transients
    ONLY while no elastic coordinator is active: with one installed,
    the throw propagates immediately so the coordinator can turn the
    rank death into a topology change instead of the retry loop
    blind-redialing a dead peer through the whole backoff schedule.

    Recovery telemetry: each retry bumps `resilience.retries` and sets
    the `resilience.last_backoff_s` gauge; a give-up bumps
    `resilience.retry_giveup` (all monitor-gated)."""
    policy = policy or RetryPolicy()
    mon = _mon()
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:
            cat = classify_fn(e)
            if cat not in _RETRYABLE:
                raise
            if cat == PREEMPTION and _elastic_active():
                if mon.is_enabled():
                    mon.counter("resilience.retry_deferred_to_elastic") \
                        .add(1)
                raise
            if attempt >= policy.max_retries:
                if mon.is_enabled():
                    mon.counter("resilience.retry_giveup").add(1)
                fr = _fr()
                fr.note_event("retry_giveup", severe=True,
                              attempts=attempt + 1,
                              error=f"{type(e).__name__}: {e}"[:200])
                # the caller usually catches RetriesExhausted and shuts
                # down cleanly — this taxonomy path dumps NOW so the
                # post-mortem records what the device was doing
                fr.dump("retries_exhausted")
                raise RetriesExhausted(attempt + 1, e) from e
            d = policy.delay(attempt)
            if mon.is_enabled():
                mon.counter("resilience.retries").add(1)
                mon.gauge("resilience.last_backoff_s").set(d)
            _fr().note_event("retry", attempt=attempt,
                             backoff_s=round(d, 4),
                             error=f"{type(e).__name__}: {e}"[:200])
            if on_retry is not None:
                on_retry(attempt, d, e)
            # the backoff is pure badput: charge it to the goodput
            # ledger's recovery bucket (innermost-span-wins, so a
            # backoff during a compile retry still reads as recovery)
            gled = _mon().goodput.active()
            if gled is not None and gled.push("recovery"):
                try:
                    policy.sleep(d)
                finally:
                    gled.pop()
            else:
                policy.sleep(d)
            attempt += 1
