"""paddle_tpu.resilience — fault-tolerant training runtime.

Four pillars (ISSUE 4 tentpole):

1. **Anomaly guard** (`guard.py`) — a cheap on-device all-finite
   reduction fused into the compiled train step; policy ``raise`` /
   ``skip_step`` / ``rollback`` (restore newest complete checkpoint +
   replay the data cursor).  Wired through Executor.run and the AMP
   loss-scale path.
2. **Retry with jittered exponential backoff** (`retry.py`) around
   transient runtime failures, classified by the error-taxonomy table
   (`taxonomy.py`) so programming errors still fail fast.
3. **Preemption-safe training** (`preempt.py`) — SIGTERM/SIGINT raise
   a flag; the training loop force-checkpoints at the next step
   boundary and exits cleanly; `train_from_dataset(auto_resume=True)`
   restores the latest checkpoint and skips consumed batches.
4. **Deterministic fault injection** (`faultinject.py`) — NaN feeds at
   step N, synthetic transient errors, kill-between-array-write-and-
   marker during checkpoint saves; drives tests and the
   `bench.py fault_tolerance_smoke` CI chaos row.

Plus the fleet-level pillar (ISSUE 11): the **elastic runtime**
(`elastic.py`) — topology-change resharding
(`CheckpointManager.restore_resharded`), rank join/leave through an
`ElasticCoordinator` (heartbeat liveness, bounded-timeout boundary
sync, leave/join intents, shrink/grow transitions gated into
/healthz), and skew-driven policies (`ElasticPolicy`:
warn | rebalance | evict off `monitor.fleet_skew()`), exercised by the
`bench.py elastic_fleet_smoke` kill/reshard/rejoin chaos row.

All recovery events land as `resilience.*` monitor counters/gauges
(visible in `monitor.snapshot()` and the merged Chrome trace), and
checkpoint save/restore wall time is recorded by checkpoint.py.

Usage::

    from paddle_tpu import resilience
    from paddle_tpu.checkpoint import CheckpointManager

    mgr = CheckpointManager("/ckpt", save_interval_steps=50)
    resilience.enable_anomaly_guard(policy="rollback", manager=mgr)
    resilience.enable_retry(resilience.RetryPolicy(max_retries=5))
    with resilience.PreemptionHandler():
        exe.train_from_dataset(prog, dataset, checkpoint=mgr,
                               auto_resume=True)
"""

from .breaker import (CircuitBreaker, CircuitOpenError)      # noqa: F401
from . import elastic                                        # noqa: F401
from .elastic import (ElasticCoordinator, ElasticPolicy,     # noqa: F401
                      TopologyChanged, active_coordinator)
from .faultinject import (FaultPlan, InjectedCrash,          # noqa: F401
                          InjectedTransientError, plan_scope)
from . import faultinject                                    # noqa: F401
from .guard import (AnomalyError, AnomalyGuard,              # noqa: F401
                    RollbackPerformed, active_guard, all_finite,
                    anomaly_guard, disable_anomaly_guard,
                    enable_anomaly_guard)
from .preempt import (PreemptionHandler, clear_drain,        # noqa: F401
                      clear_preemption, drain_requested,
                      preemption_requested, request_drain,
                      request_preemption)
from .retry import RetriesExhausted, RetryPolicy, call_with_retry
from .taxonomy import (DEADLINE, FATAL, PREEMPTION, TRANSIENT, TAXONOMY,
                       DeadlineExceeded, classify, is_deadline, is_oom,
                       is_preemption, is_transient)

__all__ = [
    # guard
    "AnomalyGuard", "AnomalyError", "RollbackPerformed",
    "enable_anomaly_guard", "disable_anomaly_guard", "anomaly_guard",
    "active_guard", "all_finite", "guarded_step",
    # retry
    "RetryPolicy", "RetriesExhausted", "call_with_retry",
    "enable_retry", "disable_retry", "active_retry",
    # breaker
    "CircuitBreaker", "CircuitOpenError",
    # elastic fleet (ISSUE 11)
    "elastic", "ElasticCoordinator", "ElasticPolicy", "TopologyChanged",
    "active_coordinator",
    # taxonomy
    "classify", "is_transient", "is_oom", "is_deadline", "is_preemption",
    "DeadlineExceeded", "TRANSIENT", "FATAL", "DEADLINE", "PREEMPTION",
    "TAXONOMY",
    # preemption / drain
    "PreemptionHandler", "preemption_requested", "request_preemption",
    "clear_preemption", "drain_requested", "request_drain", "clear_drain",
    # fault injection
    "faultinject", "FaultPlan", "plan_scope", "InjectedTransientError",
    "InjectedCrash",
]

_retry_policy = None


def enable_retry(policy=None):
    """Install a process-wide retry policy: Executor.run wraps each
    compiled dispatch in call_with_retry while one is active.

    Caveat: a failure that strikes MID-execution may have consumed
    donated input buffers, in which case the retry itself fails fast
    on deleted arrays — the net effect is still a clean error, never
    silent corruption.  Failures before execution starts (allocation
    RESOURCE_EXHAUSTED, rendezvous errors, injected faults) retry
    cleanly."""
    global _retry_policy
    _retry_policy = policy or RetryPolicy()
    return _retry_policy


def disable_retry():
    global _retry_policy
    _retry_policy = None


def active_retry():
    return _retry_policy


def _mon():
    from .. import monitor

    return monitor


def guarded_step(step, guard=None, template_state=None):
    """Wrap a functional train step (the `make_amp_train_step` /
    `make_train_step` family: ``step(state, *batch) -> (state, loss,
    finite)`` or ``(state, loss)``) with host-side guard-policy
    handling — the eager-mode twin of the executor's fused check.

    AMP steps already compute the `finite` flag from the loss-scale
    path; steps without one get the finiteness of their loss checked.
    Policy ``rollback`` restores through guard.manager and raises
    RollbackPerformed with `.state` set to the restored pytree (the
    caller rewinds its batch cursor to `.step` and continues from
    `.state`)."""
    import numpy as np

    g = guard or active_guard()
    if g is None:
        raise ValueError("no anomaly guard active (pass guard= or "
                         "enable_anomaly_guard first)")

    def wrapped(state, *batch):
        out = step(state, *batch)
        if len(out) == 3:
            new_state, loss, finite = out
        else:
            new_state, loss = out
            finite = np.isfinite(np.asarray(loss)).all()
        ok = bool(np.asarray(finite))
        mon = _mon()
        if ok:
            g.note_ok()
            return new_state, loss, True
        if mon.is_enabled():
            mon.counter("resilience.anomaly_steps").add(1)
        g.note_anomaly()
        if g.policy == "raise":
            raise AnomalyError("guarded step produced non-finite "
                               "loss/gradients (policy=raise)")
        if g.policy == "skip_step":
            if mon.is_enabled():
                mon.counter("resilience.skipped_steps").add(1)
            # AMP steps already selected the old state on overflow;
            # plain steps committed a poisoned update — hand back the
            # INPUT state so the skip really skips
            return (new_state if len(out) == 3 else state), loss, False
        # rollback
        g.note_rollback()
        if mon.is_enabled():
            mon.counter("resilience.rollbacks").add(1)
        template = template_state if template_state is not None \
            else (new_state if len(out) == 3 else state)
        restored, ck_step = g.manager.restore_latest(template)
        exc = RollbackPerformed(ck_step)
        exc.state = restored
        raise exc

    return wrapped
