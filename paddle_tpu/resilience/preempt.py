"""Preemption-safe shutdown — SIGTERM/SIGINT to clean checkpoint.

Parity role: the reference's pserver `checkpoint_notify` + trainer
restart contract assumes workers are killed mid-run; TPU preemptions
arrive the same way (SIGTERM with a grace window).  The handler does
NOT checkpoint from signal context — async-signal-unsafe and the step
in flight would tear — it only raises a flag; the training loop
(Executor.train_from_dataset, or any user loop polling
`preemption_requested()`) force-checkpoints at the next STEP BOUNDARY
and exits cleanly, which `auto_resume=True` then picks up.

A second SIGINT escalates to the default KeyboardInterrupt — a user
hammering Ctrl-C must still be able to kill a wedged run.

ISSUE 11: an opt-in DRAIN signal (conventionally SIGUSR1, via
``PreemptionHandler(drain_signal=signal.SIGUSR1)``) raises a SEPARATE
flag meaning "finish the step, leave the fleet, stay re-admittable" —
distinct from SIGTERM's "save and exit".  A drained rank under an
ElasticCoordinator writes a leave intent so the survivors shrink
around it without waiting out the dead-peer timeout; the process
itself exits cleanly and can later rejoin via a join intent.
"""

import signal
import threading

__all__ = ["PreemptionHandler", "preemption_requested",
           "request_preemption", "clear_preemption",
           "drain_requested", "request_drain", "clear_drain"]

_event = threading.Event()
_drain_event = threading.Event()


def preemption_requested():
    return _event.is_set()


def request_preemption():
    """Programmatic preemption request (what the signal handler calls;
    also the deterministic hook for tests and external orchestrators
    that learn of preemption out-of-band, e.g. a metadata server).

    Async-signal-safe by design: ONLY the event is set.  No locks, no
    imports, no counters — the handler may be interrupting a frame
    that holds the monitor registry lock, and blocking on it here
    would hang the process through its grace window.  The training
    loop that OBSERVES the flag does the counting."""
    _event.set()


def clear_preemption():
    _event.clear()


def drain_requested():
    """True when a drain-and-leave was requested (SIGUSR1 under an
    opted-in PreemptionHandler, or request_drain) — "finish the step,
    leave the fleet, stay re-admittable", distinct from the preemption
    flag's "save and exit"."""
    return _drain_event.is_set()


def request_drain():
    """Programmatic drain request (what the opt-in drain signal's
    handler calls).  Async-signal-safe for the same reason
    request_preemption is: ONLY the event is set — counting happens in
    the loop that observes the flag."""
    _drain_event.set()


def clear_drain():
    _drain_event.clear()


class PreemptionHandler:
    """Install SIGTERM/SIGINT -> request_preemption while active.

    with PreemptionHandler():
        exe.train_from_dataset(..., checkpoint=mgr, auto_resume=True)

    Previous handlers are restored on exit.  Only the main thread may
    install signal handlers (CPython rule); constructing elsewhere
    raises, so a producer thread can't half-install.

    drain_signal (opt-in, conventionally signal.SIGUSR1): raises the
    DRAIN flag instead of the preemption flag — "leave the fleet at
    the next step boundary, stay re-admittable".  An elastic training
    loop turns it into a leave intent + clean exit; a plain loop that
    never polls drain_requested() simply ignores it, which is why the
    signal is not installed by default.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT),
                 drain_signal=None):
        self.signals = tuple(signals)
        self.drain_signal = drain_signal
        self._prev = {}
        self._sigints = 0

    def _on_signal(self, signum, frame):
        # escalation counts SIGINTs specifically — an earlier SIGTERM
        # (or programmatic request) must not turn the user's FIRST
        # Ctrl-C into a mid-step KeyboardInterrupt that skips the
        # boundary checkpoint
        if signum == signal.SIGINT:
            self._sigints += 1
            if self._sigints > 1:
                # second Ctrl-C: the user means it
                raise KeyboardInterrupt
        request_preemption()

    def _on_drain(self, signum, frame):
        request_drain()

    def install(self):
        if threading.current_thread() is not threading.main_thread():
            raise RuntimeError(
                "PreemptionHandler must be installed from the main thread")
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._on_signal)
        if self.drain_signal is not None:
            self._prev[self.drain_signal] = signal.signal(
                self.drain_signal, self._on_drain)
        return self

    def uninstall(self):
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
