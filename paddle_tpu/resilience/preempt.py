"""Preemption-safe shutdown — SIGTERM/SIGINT to clean checkpoint.

Parity role: the reference's pserver `checkpoint_notify` + trainer
restart contract assumes workers are killed mid-run; TPU preemptions
arrive the same way (SIGTERM with a grace window).  The handler does
NOT checkpoint from signal context — async-signal-unsafe and the step
in flight would tear — it only raises a flag; the training loop
(Executor.train_from_dataset, or any user loop polling
`preemption_requested()`) force-checkpoints at the next STEP BOUNDARY
and exits cleanly, which `auto_resume=True` then picks up.

A second SIGINT escalates to the default KeyboardInterrupt — a user
hammering Ctrl-C must still be able to kill a wedged run.
"""

import signal
import threading

__all__ = ["PreemptionHandler", "preemption_requested",
           "request_preemption", "clear_preemption"]

_event = threading.Event()


def preemption_requested():
    return _event.is_set()


def request_preemption():
    """Programmatic preemption request (what the signal handler calls;
    also the deterministic hook for tests and external orchestrators
    that learn of preemption out-of-band, e.g. a metadata server).

    Async-signal-safe by design: ONLY the event is set.  No locks, no
    imports, no counters — the handler may be interrupting a frame
    that holds the monitor registry lock, and blocking on it here
    would hang the process through its grace window.  The training
    loop that OBSERVES the flag does the counting."""
    _event.set()


def clear_preemption():
    _event.clear()


class PreemptionHandler:
    """Install SIGTERM/SIGINT -> request_preemption while active.

    with PreemptionHandler():
        exe.train_from_dataset(..., checkpoint=mgr, auto_resume=True)

    Previous handlers are restored on exit.  Only the main thread may
    install signal handlers (CPython rule); constructing elsewhere
    raises, so a producer thread can't half-install.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self._prev = {}
        self._sigints = 0

    def _on_signal(self, signum, frame):
        # escalation counts SIGINTs specifically — an earlier SIGTERM
        # (or programmatic request) must not turn the user's FIRST
        # Ctrl-C into a mid-step KeyboardInterrupt that skips the
        # boundary checkpoint
        if signum == signal.SIGINT:
            self._sigints += 1
            if self._sigints > 1:
                # second Ctrl-C: the user means it
                raise KeyboardInterrupt
        request_preemption()

    def install(self):
        if threading.current_thread() is not threading.main_thread():
            raise RuntimeError(
                "PreemptionHandler must be installed from the main thread")
        for s in self.signals:
            self._prev[s] = signal.signal(s, self._on_signal)
        return self

    def uninstall(self):
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False
