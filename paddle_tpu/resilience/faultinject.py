"""Deterministic fault-injection harness.

The fault-tolerance layer is only trustworthy if every recovery path is
exercised on purpose: this module injects the three failure shapes the
runtime claims to survive, at exact, reproducible points —

- **NaN at step N**: the first floating-point feed of the Nth guarded
  Executor.run is replaced (a tainted COPY — the caller's batch array
  is untouched, so a rollback replay of the same batch sees clean
  data) with NaN, which propagates to loss and gradients and trips the
  anomaly guard.
- **transient error at step N**: a synthetic InjectedTransientError is
  raised from inside the retried dispatch region, `times` times in a
  row, exercising classification + backoff + eventual success.
- **crash at a named point**: code that must be crash-safe calls
  `crash_point("name")` at its vulnerable spots (checkpoint.py calls
  `checkpoint.before_marker` between the array write and the
  _COMPLETE marker); an armed plan raises InjectedCrash there —
  a BaseException, so no cleanup handler downstream can complete the
  interrupted operation, exactly like a SIGKILL.

All injections are ONE-SHOT by default (they disarm after firing) and
counted both in the plan (`fired`) and as `resilience.injected_*`
monitor counters, so a test can assert the fault actually happened —
a chaos test that silently injects nothing is worse than no test.
"""

import os
import threading

from .taxonomy import InjectedCrash, InjectedTransientError

__all__ = ["FaultPlan", "arm", "disarm", "active_plan", "is_armed",
           "plan_scope", "on_step_feed", "check_transient", "crash_point",
           "kill_point", "stall_point",
           "InjectedTransientError", "InjectedCrash"]

_lock = threading.Lock()
_plan = None


class FaultPlan:
    """One armed injection schedule.  Step indices are 0-based counts
    of EVERY Executor.run dispatch SINCE ARMING — guarded or not, eval
    programs included (the harness keeps its own counter; arm right
    before the loop under test, and account for any interleaved eval
    runs when picking indices).  Injecting into an UNguarded run is a
    legitimate chaos scenario: it shows what the failure looks like
    with recovery off.

    nan_at_steps:   iterable of step indices whose feeds get tainted
    nan_feed:       feed var name to taint (default: first float feed,
                    in sorted-name order for determinism)
    transient_at_step: step index (or iterable of indices — the
                    serving breaker tests need CONSECUTIVE dispatch
                    failures) that raises InjectedTransientError
    transient_times:   how many raises total before succeeding (shared
                    budget across the scheduled steps)
    crash_points:   {point_name: nth_hit_to_fire} (0-based hit count)
    kill_points:    {point_name: nth_hit_to_fire} like crash_points,
                    but the PROCESS dies via os._exit(1) — a real
                    SIGKILL-equivalent for multi-process chaos (the
                    fleet replica kill, ISSUE 19): no exception, no
                    handler, no atexit; the peer sees a dead socket.
                    InjectedCrash stays the single-process simulation.
    stall_points:   {point_name: spec} latency/hang injection (ISSUE 8):
                    spec is a float (deterministic sleep of that many
                    seconds) or a threading.Event (block until the test
                    sets it — a REAL hang with no wall-clock guess, so
                    watchdog tests are not timing-flaky).  One-shot per
                    point; a (nth_hit, spec) tuple targets a later
                    visit, and ("every", spec) fires on EVERY visit
                    without disarming — the fleet straggler smoke slows
                    one rank on each step (ISSUE 10).
    """

    def __init__(self, nan_at_steps=(), nan_feed=None,
                 transient_at_step=None, transient_times=1,
                 crash_points=None, kill_points=None, stall_points=None):
        self.nan_at_steps = set(int(s) for s in (
            nan_at_steps if not isinstance(nan_at_steps, int)
            else (nan_at_steps,)))
        self.nan_feed = nan_feed
        if transient_at_step is None:
            self.transient_at_steps = set()
        elif isinstance(transient_at_step, int):
            self.transient_at_steps = {transient_at_step}
        else:
            self.transient_at_steps = set(
                int(s) for s in transient_at_step)
        self.transient_remaining = int(transient_times)
        self.crash_points = dict(crash_points or {})
        self._crash_hits = {}
        self.kill_points = dict(kill_points or {})
        self._kill_hits = {}
        self.stall_points = {
            name: (spec if isinstance(spec, tuple) else (0, spec))
            for name, spec in (stall_points or {}).items()}
        self._stall_hits = {}
        self.step = 0
        self.fired = {"nan": 0, "transient": 0, "crash": 0, "kill": 0,
                      "stall": 0}

    @property
    def transient_at_step(self):
        """Back-compat single-step view (None unless exactly one
        step is scheduled — multi-step plans have no single index)."""
        if len(self.transient_at_steps) == 1:
            return next(iter(self.transient_at_steps))
        return None

    def describe(self):
        return {"step": self.step, "fired": dict(self.fired)}


def arm(plan=None, **kw):
    """Install a FaultPlan (or build one from kwargs) process-wide.
    Returns the armed plan."""
    global _plan
    p = plan if plan is not None else FaultPlan(**kw)
    with _lock:
        _plan = p
    return p


def disarm():
    global _plan
    with _lock:
        _plan = None


def active_plan():
    return _plan


def is_armed():
    return _plan is not None


class plan_scope:
    """Context manager: arm on enter, ALWAYS disarm on exit — a
    raising test must not leak its faults into the next one."""

    def __init__(self, plan=None, **kw):
        self._plan = plan if plan is not None else FaultPlan(**kw)

    def __enter__(self):
        return arm(self._plan)

    def __exit__(self, *exc):
        disarm()
        return False


def _mon():
    from .. import monitor

    return monitor


def _fr():
    from ..monitor import flight_recorder

    return flight_recorder


# -- hooks called by the runtime ---------------------------------------

def on_step_feed(feed_arrays):
    """Executor.run calls this once per guarded dispatch with the
    prepared feed dict; returns the (possibly tainted) dict and
    advances the plan's step counter.  The input dict/arrays are never
    mutated — a tainted feed is a fresh NaN-filled array under the
    same name."""
    p = _plan
    if p is None:
        return feed_arrays
    with _lock:
        step = p.step
        p.step += 1
        fire_nan = step in p.nan_at_steps
        if fire_nan:
            p.nan_at_steps.discard(step)       # one-shot
    if not fire_nan:
        return feed_arrays
    import jax.numpy as jnp

    name = p.nan_feed
    if name is None:
        for n in sorted(feed_arrays):
            a = feed_arrays[n]
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype,
                                                      jnp.floating):
                name = n
                break
    if name is None or name not in feed_arrays:
        raise ValueError(
            f"fault plan has no float feed to taint (nan_feed="
            f"{p.nan_feed!r}, feeds={sorted(feed_arrays)})")
    tainted = dict(feed_arrays)
    tainted[name] = jnp.full_like(jnp.asarray(tainted[name]), jnp.nan)
    p.fired["nan"] += 1
    mon = _mon()
    if mon.is_enabled():
        mon.counter("resilience.injected_nan").add(1)
    _fr().note_event("injected_nan", step=step, feed=name)
    return tainted


def check_transient():
    """Called from inside the retried dispatch region: raises the
    scheduled InjectedTransientError while any raises remain for the
    current step.  The step index was fixed by on_step_feed (which
    runs first), so every retry of the SAME step re-enters here."""
    p = _plan
    if p is None or not p.transient_at_steps:
        return
    # on_step_feed already advanced p.step past the current dispatch
    current = p.step - 1
    if current not in p.transient_at_steps:
        return
    with _lock:
        if p.transient_remaining <= 0:
            return
        p.transient_remaining -= 1
        p.fired["transient"] += 1
    mon = _mon()
    if mon.is_enabled():
        mon.counter("resilience.injected_transient").add(1)
    _fr().note_event("injected_transient", step=current)
    raise InjectedTransientError(
        "injected: RESOURCE_EXHAUSTED: synthetic device allocation "
        "failure while trying to allocate 1073741824 bytes "
        "(fault-injection harness)")


def crash_point(name):
    """Instrumented code calls this at its crash-vulnerable points;
    a no-op unless an armed plan schedules `name`.  Fires InjectedCrash
    on the scheduled visit (0-based), then disarms that point."""
    p = _plan
    if p is None or name not in p.crash_points:
        return
    with _lock:
        if name not in p.crash_points:       # re-check under lock
            return
        hit = p._crash_hits.get(name, 0)
        p._crash_hits[name] = hit + 1
        if hit != p.crash_points[name]:
            return
        del p.crash_points[name]             # one-shot
        p.fired["crash"] += 1
    mon = _mon()
    if mon.is_enabled():
        mon.counter("resilience.injected_crash").add(1)
    # post-mortem BEFORE the raise: InjectedCrash models a SIGKILL, so
    # nothing downstream may run — including any dump hook.  (A real
    # SIGKILL can't dump either; the simulation records what the kill
    # interrupted, which is exactly what the chaos test asserts.)
    fr = _fr()
    fr.note_event("injected_crash", severe=True, point=name)
    fr.dump(f"injected_crash:{name}")
    raise InjectedCrash(f"injected crash at point {name!r}")


def kill_point(name):
    """Instrumented code calls this at its kill-vulnerable points (the
    fleet replica worker's request path); a no-op unless an armed plan
    schedules `name`.  On the scheduled visit (0-based hit count) the
    PROCESS dies via ``os._exit(1)`` — the SIGKILL model the shm worker
    established: no exception, no cleanup handler, no atexit hooks, no
    flushed buffers; peers observe a reset socket, which is exactly the
    failure shape the router's failover path must classify (ISSUE 19).
    The counter/flight-recorder notes land BEFORE the exit (a real
    SIGKILL can't note anything; the simulation records what it
    interrupted — the same contract as crash_point's pre-raise dump)."""
    p = _plan
    if p is None or name not in p.kill_points:
        return
    with _lock:
        if name not in p.kill_points:        # re-check under lock
            return
        hit = p._kill_hits.get(name, 0)
        p._kill_hits[name] = hit + 1
        if hit != p.kill_points[name]:
            return
        del p.kill_points[name]              # one-shot
        p.fired["kill"] += 1
    mon = _mon()
    if mon.is_enabled():
        mon.counter("resilience.injected_kill").add(1)
    fr = _fr()
    fr.note_event("injected_kill", severe=True, point=name)
    fr.dump(f"injected_kill:{name}")
    os._exit(1)


def stall_point(name):
    """Instrumented code calls this at its hang-vulnerable points (the
    serving dispatch, the shm consumer loop); a no-op unless an armed
    plan schedules `name`.  Fires the scheduled stall on the scheduled
    visit (0-based hit count), then disarms that point.

    A float spec sleeps that many seconds (deterministic latency); a
    threading.Event spec BLOCKS until the test sets it — the honest
    hang the watchdog must detect, with no wall-clock race.  A stuck
    test can't deadlock CI: event waits are capped at 120s."""
    p = _plan
    if p is None or name not in p.stall_points:
        return
    with _lock:
        if name not in p.stall_points:       # re-check under lock
            return
        hit = p._stall_hits.get(name, 0)
        p._stall_hits[name] = hit + 1
        target_hit, spec = p.stall_points[name]
        if target_hit == "every":
            pass                             # repeating: never disarm
        elif hit != target_hit:
            return
        else:
            del p.stall_points[name]         # one-shot
        p.fired["stall"] += 1
    mon = _mon()
    if mon.is_enabled():
        mon.counter("resilience.injected_stall").add(1)
    _fr().note_event("injected_stall", point=name,
                     spec=("event" if isinstance(spec, threading.Event)
                           else float(spec)))
    if isinstance(spec, threading.Event):
        spec.wait(timeout=120.0)
    else:
        import time

        time.sleep(float(spec))
