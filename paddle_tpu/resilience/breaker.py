"""Circuit breaker — fail fast while a dependency is known-broken.

Parity role: the reference's serving stack sheds load when a backend
is wedged instead of letting every caller time out individually; here
the breaker guards the serving runtime's batched dispatch (ISSUE 8).
The state machine is the classic three-state one:

- **closed** — traffic flows; `failure_threshold` CONSECUTIVE
  classified failures (any success resets the count) trip it open.
- **open** — `allow()` answers False immediately (no dispatch, no
  timeout); the serving layer degrades to its fallback path.  After
  `cooldown_s` on the injectable clock the breaker half-opens.
- **half_open** — exactly ONE caller wins the probe token; its success
  closes the breaker, its failure re-opens it (cooldown restarts).

Every transition lands in `transitions` (inspectable by tests and the
serving table), bumps a `resilience.breaker_*` counter, and is noted
in the flight recorder — an open breaker is exactly the kind of event
a post-mortem must explain.

The clock is injectable so breaker tests never sleep; thread-safe, one
lock, tiny critical sections.
"""

import threading
import time

__all__ = ["CircuitBreaker", "CircuitOpenError",
           "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitOpenError(RuntimeError):
    """Raised (or stored on a request) when the breaker is open and no
    degraded fallback is configured: the dependency is known-broken,
    so failing in microseconds beats timing out in seconds."""


def _mon():
    from .. import monitor

    return monitor


def _fr():
    from ..monitor import flight_recorder

    return flight_recorder


class CircuitBreaker:
    """Three-state breaker with an injectable clock.

    b = CircuitBreaker(failure_threshold=5, cooldown_s=30.0)
    if b.allow():
        try:    ...dispatch...; b.note_success()
        except Exception as e:  b.note_failure(e); raise
    else:       ...fail fast / degraded path...
    """

    def __init__(self, failure_threshold=5, cooldown_s=30.0,
                 clock=time.monotonic, name="breaker"):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.clock = clock
        self.name = name
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = None
        self._probe_taken = False
        self._probe_granted_at = None
        self.transitions = []          # [(ts, from_state, to_state)]
        self.last_error = None

    # -- state ----------------------------------------------------------
    def _advance_locked(self):
        """Open -> half-open once the cooldown has elapsed (lazy: no
        timer thread — the next caller pays one clock read).  A probe
        that never reported back (its requests all expired, the caller
        died) expires after another cooldown period, re-granting the
        token — an unreported probe must not wedge the breaker in
        half-open forever."""
        if self._state == OPEN and \
                self.clock() - self._opened_at >= self.cooldown_s:
            self._transition_locked(HALF_OPEN)
            self._probe_taken = False
            self._probe_granted_at = None
        if self._state == HALF_OPEN and self._probe_taken and \
                self._probe_granted_at is not None and \
                self.clock() - self._probe_granted_at >= self.cooldown_s:
            self._probe_taken = False
            self._probe_granted_at = None

    def _transition_locked(self, to_state):
        frm = self._state
        if frm == to_state:
            return
        self._state = to_state
        self.transitions.append((self.clock(), frm, to_state))
        mon = _mon()
        if mon.is_enabled():
            mon.counter(f"resilience.breaker_{to_state}").add(1)
        _fr().note_event(f"breaker_{to_state}", name=self.name,
                         consecutive_failures=self._consecutive_failures,
                         error=(f"{type(self.last_error).__name__}: "
                                f"{self.last_error}"[:200]
                                if self.last_error is not None else None))

    @property
    def state(self):
        with self._lock:
            self._advance_locked()
            return self._state

    def allow(self):
        """May a dispatch proceed right now?  closed: yes.  open: no
        (fail fast).  half_open: yes for exactly ONE caller — the
        probe; everyone else is treated as open until it reports."""
        with self._lock:
            self._advance_locked()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probe_taken:
                self._probe_taken = True
                self._probe_granted_at = self.clock()
                return True
            mon = _mon()
            if mon.is_enabled():
                mon.counter("resilience.breaker_fast_fail").add(1)
            return False

    # -- outcome reports ------------------------------------------------
    def release_probe(self):
        """The dispatch this breaker allowed ended with NO verdict —
        every waiter expired mid-flight, or the batch was abandoned
        before completing.  Hand the half-open probe token back so the
        next dispatch can probe instead of waiting out the expiry
        backstop."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_taken = False
                self._probe_granted_at = None

    def note_success(self):
        """A dispatch the breaker allowed succeeded.  In half-open this
        is the probe reporting: the dependency healed — close."""
        with self._lock:
            self._consecutive_failures = 0
            self.last_error = None
            if self._state in (HALF_OPEN, OPEN):
                # OPEN can only be seen here by a dispatch that started
                # pre-trip and finished late; its success is still the
                # recovery signal the probe exists to find
                self._transition_locked(CLOSED)

    def note_failure(self, exc=None):
        """A dispatch the breaker allowed failed (with the error
        already classified by the taxonomy — retry has given up, or
        the failure was fail-fast).  Half-open: the probe failed,
        re-open and restart the cooldown.  Closed: count it; the Nth
        consecutive failure trips the breaker."""
        with self._lock:
            self.last_error = exc
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                self._transition_locked(OPEN)
                self._opened_at = self.clock()
                return
            if self._state == CLOSED and \
                    self._consecutive_failures >= self.failure_threshold:
                self._transition_locked(OPEN)
                self._opened_at = self.clock()

    def summary(self):
        """json-safe view for the serving table / kind="serving"
        records."""
        with self._lock:
            self._advance_locked()
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "failure_threshold": self.failure_threshold,
                "cooldown_s": self.cooldown_s,
                "transitions": [
                    {"ts": round(ts, 6), "from": frm, "to": to}
                    for ts, frm, to in self.transitions],
            }
