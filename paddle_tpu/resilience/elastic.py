"""Elastic fleet runtime — topology change as a recoverable variable
(ISSUE 11 tentpole).

Parity role: the reference's distributed Fluid runtime assumes trainers
die and come back — the fleet re-launches a lost worker and the pserver
path tolerates it (PAPER.md layer 6).  Our dp runtime could *name* a
straggler (PR 10 ``monitor.fleet_skew``) and survive in-process faults
(PR 4 guard/retry/preemption), but a lost rank killed the whole run.
This module closes the detection→recovery loop:

**Control plane** — a shared directory (``<ckpt>/_elastic``) next to
the checkpoint store carries the fleet's collective memory: per-rank
heartbeats (step + wall time, rewritten atomically at every step
boundary), *leave intents* (a SIGTERM'd/drained rank announces its
exit so survivors don't wait out the dead-peer timeout), *join
intents* (a fresh rank — or the orchestrator on its behalf — asks to
be admitted), and ``topology.json`` (the current generation: world
size + member ranks).  Files, not RPCs, on purpose: the checkpoint
store is already the one shared, durable medium every rank can reach,
and a recovery protocol must not depend on the very collectives whose
failure it handles.

**Bounded-timeout boundary sync** — :meth:`ElasticCoordinator.
step_boundary` is the per-step hook: write our heartbeat, then wait
(bounded by ``peer_timeout_s``) until every member has either posted
this boundary or posted a leave intent.  A member that does neither is
declared dead.  The sync is also where the SIGTERM/SIGUSR1 flags from
:class:`~.preempt.PreemptionHandler` become *leave intents*, where
join intents surface as grow events, and where the skew policy reads
``monitor.fleet_skew()``.  The return value is an event dict (or None
in the steady state); ``Executor.train_from_dataset(elastic=...)``
turns events into a force-save plus :class:`TopologyChanged`.

**Transitions** — shrink (survivors < world) restores the force-saved
checkpoint onto a new mesh via ``CheckpointManager.restore_resharded``
— IN PROCESS when the survivor set is exactly this rank's local
devices (the jax world needs no cross-process collectives any more),
via orchestrator relaunch otherwise (``action="relaunch"``: jax pins
``num_processes`` at initialize time, so a *changed multi-process
world* must re-rendezvous — the reference's trainer-restart contract).
Grow always relaunches: the joining process cannot enter an existing
gloo/PJRT world.  Every transition is bracketed by ``begin_transition``
/ ``commit_transition`` — between them ``transition_in_flight()`` is
truthy, the /healthz exporter answers 503 ``reason=elastic_transition``
(serving keeps its health gate during the window), and the new
``topology.json`` generation is only written at commit.

**Skew policy** (:class:`ElasticPolicy`) — the ``on_straggler`` table:
``warn`` (record + counter), ``rebalance`` (shift per-rank batch
shares away from the straggler, ``plan_feed`` quantizes them to
integer rows for the host-side feed assembly; under strict SPMD the
device shards stay equal, so shares steer the host input pipeline and
the policy escalates once shares bottom out), ``evict`` (a shrink
event targeting the straggler).  Decisions need ``patience``
consecutive over-threshold windows — one noisy step must not evict a
healthy rank.

Observability: every transition is a ``resilience.elastic_*`` counter
(gate-free, scrape-visible with telemetry off), a ``kind="elastic"``
JSONL record, and a flight-recorder event; ``fleet.process_count`` /
``fleet.topology_gen`` gauges track the current world, and
``tools/telemetry_report.py`` (``--fleet``) renders the topology
history.
"""

import atexit
import json
import os
import threading
import time

from . import preempt
from .faultinject import crash_point
from .taxonomy import is_preemption

__all__ = ["ElasticCoordinator", "ElasticPolicy", "TopologyChanged",
           "active_coordinator", "transition_in_flight", "current_world",
           "transitions_total", "request_join", "local_mesh"]

_CONTROL_DIR = "_elastic"


class TopologyChanged(RuntimeError):
    """The fleet's topology changed at step boundary `step`; the
    current compiled world is stale.  `event` is the coordinator event
    that triggered it and `action` what the catcher should do:

    - ``"reshard_local"`` — this process alone survives its shrink:
      rebuild on ``coordinator.local_mesh()`` via ``restore_resharded``
      and continue in process.
    - ``"relaunch"`` — the new world spans a different multi-process
      set: the force-saved checkpoint + committed topology.json are the
      rendezvous; exit so the orchestrator relaunches at the new size.
    - ``"exit"`` — this rank itself left (drain/preemption under the
      coordinator); state is durable, exit cleanly.
    """

    def __init__(self, step, event, action):
        super().__init__(
            f"fleet topology changed at step {step}: "
            f"{event.get('kind')} -> {action}")
        self.step = step
        self.event = dict(event)
        self.action = action


def _mon():
    from .. import monitor

    return monitor


def _fr():
    from ..monitor import flight_recorder

    return flight_recorder


def _atomic_json(path, payload):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def _read_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def local_mesh(axis_name="dp"):
    """Mesh over THIS process's local devices — the shrink target when
    a survivor continues in process (no cross-process collectives
    remain, so the dead peers' gloo channels are never touched)."""
    import numpy as np

    import jax
    from jax.sharding import Mesh

    return Mesh(np.array(jax.local_devices()), (axis_name,))


def request_join(directory, rank, after_step=None):
    """Write a join intent for `rank` into the control dir of the
    elastic store rooted at checkpoint `directory` — what a freshly
    launched rank (or the orchestrator on its behalf) posts to be
    admitted at the next step boundary.  `after_step` defers admission
    until the fleet reaches that boundary (a scheduled grow)."""
    cdir = os.path.join(os.path.abspath(directory), _CONTROL_DIR)
    os.makedirs(cdir, exist_ok=True)
    _atomic_json(os.path.join(cdir, f"join_r{int(rank)}.json"),
                 {"rank": int(rank), "after_step": after_step,
                  "wall_time": time.time()})
    _mon().counter("resilience.elastic_join_requests").add(1)


# ----------------------------------------------------------------------
# skew-driven policy
# ----------------------------------------------------------------------

class ElasticPolicy:
    """The ``on_straggler`` policy table driven by the rolling
    straggler score of ``monitor.fleet_skew()``.

    on_straggler:     "warn" | "rebalance" | "evict" — the action once
                      a straggler holds the score above
                      `score_threshold` for `patience` consecutive
                      observations (hysteresis: one slow step is not a
                      policy event).
    rebalance_step:   share fraction moved off the straggler per
                      rebalance decision (its share floor is
                      `min_share`; the freed share spreads equally
                      over the other ranks).
    evict_after_rebalances: with on_straggler="rebalance", how many
                      rebalances against the SAME rank before the
                      policy escalates to eviction — the shrink path
                      is the final actuator when shares bottom out.
    """

    ACTIONS = ("warn", "rebalance", "evict")

    def __init__(self, on_straggler="warn", score_threshold=0.25,
                 patience=3, rebalance_step=0.25, min_share=0.5,
                 evict_after_rebalances=2):
        if on_straggler not in self.ACTIONS:
            raise ValueError(
                f"on_straggler must be one of {self.ACTIONS}, "
                f"got {on_straggler!r}")
        self.on_straggler = on_straggler
        self.score_threshold = float(score_threshold)
        self.patience = int(patience)
        self.rebalance_step = float(rebalance_step)
        self.min_share = float(min_share)
        self.evict_after_rebalances = int(evict_after_rebalances)
        self._streak = 0
        self._streak_rank = None
        self._rebalances = {}     # dp_index -> count
        self.shares = None        # {dp_index: share}, sum == nranks

    def note_table(self, table):
        """Feed one skew table (monitor.fleet_skew()); returns a
        decision dict {"action", "straggler", ...} when the policy
        fires, else None."""
        straggler = (table or {}).get("straggler")
        score = (straggler or {}).get("straggler_score")
        if straggler is None or score is None \
                or score < self.score_threshold:
            self._streak = 0
            self._streak_rank = None
            return None
        idx = straggler["dp_index"]
        if idx != self._streak_rank:
            self._streak = 0
            self._streak_rank = idx
        self._streak += 1
        if self._streak < self.patience:
            return None
        self._streak = 0
        base = {"straggler": dict(straggler), "score": score,
                "threshold": self.score_threshold}
        if self.on_straggler == "warn":
            return {"action": "warn", **base}
        if self.on_straggler == "evict":
            return {"action": "evict", **base}
        # rebalance, escalating to evict once the share bottoms out or
        # the same rank keeps straggling through the allowed attempts
        nranks = len((table or {}).get("ranks") or []) or (idx + 1)
        if self.shares is None:
            self.shares = {i: 1.0 for i in range(nranks)}
        share = self.shares.get(idx, 1.0)
        done = self._rebalances.get(idx, 0)
        if share <= self.min_share or done >= self.evict_after_rebalances:
            return {"action": "evict", "escalated_from": "rebalance",
                    "rebalances": done, **base}
        moved = min(self.rebalance_step, share - self.min_share)
        others = [i for i in self.shares if i != idx]
        self.shares[idx] = round(share - moved, 6)
        for i in others:
            self.shares[i] = round(self.shares[i] + moved / len(others), 6)
        self._rebalances[idx] = done + 1
        return {"action": "rebalance", "moved": moved,
                "shares": dict(self.shares), **base}

    def plan_feed(self, global_rows):
        """Quantize the current shares to integer per-rank row counts
        summing exactly to `global_rows` (largest-remainder rounding)
        — the host-side feed assembly plan.  Equal split when no
        rebalance has fired."""
        if not self.shares:
            return None
        n = len(self.shares)
        total = sum(self.shares.values())
        exact = {i: global_rows * s / total for i, s in self.shares.items()}
        rows = {i: int(exact[i]) for i in exact}
        short = global_rows - sum(rows.values())
        for i in sorted(exact, key=lambda i: exact[i] - rows[i],
                        reverse=True)[:short]:
            rows[i] += 1
        assert sum(rows.values()) == global_rows
        return rows


# ----------------------------------------------------------------------
# the coordinator
# ----------------------------------------------------------------------

_ACTIVE = None
_lock = threading.Lock()
# the exporter's view: None, or the begin_transition payload while a
# topology change is in flight (module-level so /healthz needs no
# coordinator handle — and so a scrape can never race a dying one)
_transition = None
_transitions_total = 0
_current_world = None


def active_coordinator():
    """The installed ElasticCoordinator, or None — what retry.py
    consults before blind-retrying a PREEMPTION-shaped failure."""
    return _ACTIVE


def transition_in_flight():
    """The in-flight transition payload (dict) or None — drives the
    /healthz 503 reason=elastic_transition window."""
    return _transition


def transitions_total():
    """Process-lifetime topology transitions (begin events) — the
    exporter's ``elastic_transitions_total``."""
    return _transitions_total


def current_world():
    """World size of the newest committed topology this process knows
    (None before any coordinator activity)."""
    return _current_world


class ElasticCoordinator:
    """Per-rank agent of the elastic protocol.

    manager:         the fleet's shared CheckpointManager — both the
                     durable state AND the control-plane root.
    rank / world:    this rank and the launch world size (default: the
                     fleet rank identity / committed topology.json).
    peer_timeout_s:  bounded-timeout boundary sync — a member that
                     neither reaches the boundary nor posts a leave
                     intent within this window is declared dead.
    sync_interval:   full peer sync every N boundaries (1 = every
                     step); intents/policy are polled at every
                     boundary regardless (non-blocking).
    policy:          ElasticPolicy (None = no skew-driven actions).
    drain_signal:    opt-in drain signal forwarded to the wrapped
                     PreemptionHandler (e.g. signal.SIGUSR1).

    Use as a context manager (installs signal handlers + registers as
    the active coordinator for retry.py), or call install()/
    uninstall() explicitly.
    """

    def __init__(self, manager, rank=None, world=None,
                 peer_timeout_s=10.0, poll_interval_s=0.02,
                 sync_interval=1, heartbeat_interval_s=1.0,
                 progress_timeout_s=600.0, policy=None,
                 drain_signal=None, install_signals=True,
                 on_transition=None):
        if not hasattr(manager, "restore_resharded"):
            from ..checkpoint import CheckpointManager

            manager = CheckpointManager(manager) \
                if isinstance(manager, str) else manager
        self.manager = manager
        self.control_dir = os.path.join(manager.directory, _CONTROL_DIR)
        os.makedirs(self.control_dir, exist_ok=True)
        info = self._rank_info()
        self.rank = int(info["process_index"] if rank is None else rank)
        topo = _read_json(os.path.join(self.control_dir, "topology.json"))
        if world is not None:
            self.world = int(world)
            self.members = sorted(set(topo["members"]) if topo and
                                  topo.get("world") == self.world
                                  else range(self.world))
        elif topo:
            self.world = int(topo["world"])
            self.members = sorted(topo["members"])
        else:
            self.world = int(info["process_count"])
            self.members = list(range(self.world))
        self.gen = int(topo["gen"]) if topo else 1
        self.peer_timeout_s = float(peer_timeout_s)
        self.poll_interval_s = float(poll_interval_s)
        self.sync_interval = max(1, int(sync_interval))
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.progress_timeout_s = float(progress_timeout_s)
        self.policy = policy
        self.on_transition = on_transition
        self._handler = None
        self._install_signals = install_signals
        self._drain_signal = drain_signal
        self._boundaries = 0
        self._left = False
        self._last_step = -1
        self._hb_stop = None
        self._hb_thread = None
        self._note_world()

    @staticmethod
    def _rank_info():
        from ..monitor import fleet

        return fleet.rank_info()

    # -- lifecycle -----------------------------------------------------

    def install(self):
        global _ACTIVE
        with _lock:
            _ACTIVE = self
        if self._install_signals:
            self._handler = preempt.PreemptionHandler(
                drain_signal=self._drain_signal).install()
        # liveness is decoupled from step PROGRESS on purpose: a peer
        # wedged in a 30s first-step compile writes no boundary, but
        # its heart keeps beating — only a dead PROCESS goes silent.
        # The daemon thread re-stamps this rank's heartbeat (latest
        # boundary + fresh wall time) every heartbeat_interval_s.
        self._hb_stop = threading.Event()
        self._write_heartbeat(self._last_step)

        def _beat():
            while not self._hb_stop.wait(self.heartbeat_interval_s):
                try:
                    self._write_heartbeat(self._last_step)
                except OSError:
                    pass

        self._hb_thread = threading.Thread(
            target=_beat, name="paddle_tpu-elastic-hb", daemon=True)
        self._hb_thread.start()
        # the heart must STOP when this process starts dying: a daemon
        # thread outlives the main thread's unhandled-exception unwind
        # and keeps beating while atexit hooks (jax.distributed's
        # shutdown barrier, wedged on the very peers waiting for us)
        # run — survivors would see a fresh heartbeat from a corpse
        # forever.  Registered AFTER jax's shutdown hook, so it runs
        # FIRST (atexit is LIFO), exactly like a real SIGKILL taking
        # the whole process.
        atexit.register(self._stop_heartbeat)
        self._record("install", world=self.world, gen=self.gen,
                     members=self.members)
        return self

    def _stop_heartbeat(self):
        stop, thread = self._hb_stop, self._hb_thread
        if stop is not None:
            stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout=2.0)
        self._hb_stop = self._hb_thread = None

    def uninstall(self):
        global _ACTIVE
        self._stop_heartbeat()
        try:
            atexit.unregister(self._stop_heartbeat)
        except Exception:
            pass
        if self._handler is not None:
            self._handler.uninstall()
            self._handler = None
        with _lock:
            if _ACTIVE is self:
                _ACTIVE = None

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # -- control-plane file helpers ------------------------------------

    def _path(self, name):
        return os.path.join(self.control_dir, name)

    def _write_heartbeat(self, step):
        self._last_step = max(self._last_step, int(step))
        _atomic_json(self._path(f"hb_r{self.rank}.json"),
                     {"rank": self.rank, "step": self._last_step,
                      "gen": self.gen, "pid": os.getpid(),
                      "wall_time": time.time()})

    def _heartbeats(self):
        out = {}
        for m in self.members:
            hb = _read_json(self._path(f"hb_r{m}.json"))
            if hb is not None:
                out[m] = hb
        return out

    def _leave_intents(self):
        out = {}
        try:
            names = os.listdir(self.control_dir)
        except OSError:
            return out
        for n in names:
            if n.startswith("leave_r") and n.endswith(".json"):
                rec = _read_json(self._path(n))
                if rec is not None:
                    out[int(rec["rank"])] = rec
        return out

    def _join_intents(self, step):
        out = {}
        try:
            names = os.listdir(self.control_dir)
        except OSError:
            return out
        for n in names:
            if n.startswith("join_r") and n.endswith(".json"):
                rec = _read_json(self._path(n))
                if rec is None or int(rec["rank"]) in self.members:
                    continue
                after = rec.get("after_step")
                if after is None or int(step) >= int(after):
                    out[int(rec["rank"])] = rec
        return out

    def leave_intent(self, step, reason):
        """Announce this rank's departure so survivors shrink around
        it instead of waiting out the dead-peer timeout."""
        _atomic_json(self._path(f"leave_r{self.rank}.json"),
                     {"rank": self.rank, "step": int(step),
                      "reason": reason, "wall_time": time.time()})
        self._left = True
        _mon().counter("resilience.elastic_rank_leaves").add(1)
        self._record("leave_intent", step=int(step), reason=reason)

    def request_join(self, rank, after_step=None):
        request_join(self.manager.directory, rank, after_step=after_step)

    # -- the per-boundary hook -----------------------------------------

    def step_boundary(self, step, skew_table=None):
        """The elastic hook at step boundary `step` (= batches
        consumed).  Returns an event dict when the topology must
        change, else None:

        - ``{"kind": "self_leave", "reason": ...}`` — THIS rank was
          preempted (SIGTERM) or drained (SIGUSR1): its leave intent is
          already posted; the loop force-saves and exits.
        - ``{"kind": "rank_leave"|"rank_death", "ranks": [...]}`` —
          peers left/died: the loop force-saves and shrinks.
        - ``{"kind": "rank_join", "ranks": [...]}`` — admitted join
          intents: the loop force-saves and grows (relaunch).
        - ``{"kind": "evict", "ranks": [...]}`` — the skew policy
          escalated to eviction of a persistent straggler.
        """
        # deterministic chaos hook: the bench kills a rank exactly here
        # — after completing step-1, before any heartbeat for `step` —
        # modeling a SIGKILL landing between two steps
        crash_point("elastic.step_boundary")
        step = int(step)
        self._boundaries += 1
        if preempt.drain_requested():
            preempt.clear_drain()
            _mon().counter("resilience.elastic_drains").add(1)
            self.leave_intent(step, "drain")
            return {"kind": "self_leave", "reason": "drain", "step": step}
        if preempt.preemption_requested():
            # the loop's own preemption path force-saves + clears the
            # flag; the coordinator's job is the leave intent
            self.leave_intent(step, "preempt")
            return {"kind": "self_leave", "reason": "preempt",
                    "step": step}
        self._write_heartbeat(step)
        # non-blocking sweeps first: an announced departure beats the
        # timeout, and a scheduled join is visible immediately
        leaves = {r: rec for r, rec in self._leave_intents().items()
                  if r in self.members and r != self.rank}
        if leaves:
            return {"kind": "rank_leave", "ranks": sorted(leaves),
                    "step": step,
                    "reasons": {r: rec.get("reason")
                                for r, rec in leaves.items()}}
        joins = self._join_intents(step)
        if joins:
            return {"kind": "rank_join", "ranks": sorted(joins),
                    "step": step}
        if len(self.members) > 1 and \
                self._boundaries % self.sync_interval == 0:
            ev = self._sync_peers(step)
            if ev is not None:
                return ev
        if self.policy is not None:
            table = skew_table
            if table is None:
                table = _mon().fleet_skew()
            decision = self.policy.note_table(table)
            if decision is not None:
                return self._apply_policy(decision, step)
        return None

    def _sync_peers(self, step):
        """Bounded-timeout barrier on the control plane: every member
        must reach boundary `step` (heartbeat step), announce departure
        (leave intent), or keep its LIVENESS stamp fresh.  Death is
        silence — a peer whose background heartbeat goes stale for
        peer_timeout_s — never mere slowness: a rank wedged in a long
        compile still beats, so it is waited for (up to the
        progress_timeout_s backstop, after which a live-but-wedged
        peer is treated as dead too: the fleet must not hang forever
        on a zombie)."""
        hard_deadline = time.monotonic() + self.progress_timeout_s
        # a peer with NO heartbeat file yet (still initializing, or a
        # shared-fs lag) ages from the start of THIS wait, not from
        # epoch — a slow-to-boot rank must not read as long-dead
        t0_wall = time.time()
        while True:
            hbs = self._heartbeats()
            now = time.time()
            waiting = [m for m in self.members
                       if m != self.rank
                       and int(hbs.get(m, {}).get("step", -1)) < step]
            if not waiting:
                return None
            leaves = self._leave_intents()
            gone = sorted(m for m in waiting if m in leaves)
            if gone:
                return {"kind": "rank_leave", "ranks": gone,
                        "step": step,
                        "reasons": {m: leaves[m].get("reason")
                                    for m in gone}}
            stale = sorted(
                m for m in waiting
                if now - hbs.get(m, {}).get("wall_time", t0_wall)
                > self.peer_timeout_s)
            if stale or time.monotonic() >= hard_deadline:
                dead = stale or sorted(waiting)
                _mon().counter("resilience.elastic_rank_deaths") \
                    .add(len(dead))
                self._record("rank_death", step=step, ranks=dead,
                             timeout_s=self.peer_timeout_s,
                             wedged=not stale,
                             last_seen={m: hbs.get(m, {}).get("step")
                                        for m in dead})
                return {"kind": "rank_death", "ranks": dead,
                        "step": step, "timeout_s": self.peer_timeout_s}
            time.sleep(self.poll_interval_s)

    def on_dispatch_error(self, exc, step=None):
        """Classify a dispatch failure: preemption-shaped (dead peer,
        lost heartbeat, reset transport — taxonomy.is_preemption) means
        a rank MAY have died mid-step.  Returns a rank_death event
        naming the members whose heartbeats went stale within the
        probe window, or None — both for failures that are not the
        elastic layer's to handle AND for preemption-shaped blips
        where every peer's heart still beats (those go back to the
        caller's retry/propagation path)."""
        if not is_preemption(exc):
            return None
        # probe: give a just-died peer's heartbeat up to peer_timeout_s
        # (plus slack) to go stale before blaming anyone.  The probe
        # window exceeds the staleness threshold, so a peer that truly
        # died mid-step WILL read stale here; if every heart is still
        # fresh after the full window, the failure was a transport
        # blip between LIVE peers — hand it back (retry/propagate)
        # rather than shrink around the whole fleet and split-brain
        # against peers that keep training.
        deadline = time.monotonic() + self.peer_timeout_s + 1.0
        t0_wall = time.time()
        stale = []
        while not stale and time.monotonic() < deadline:
            hbs = self._heartbeats()
            now = time.time()
            stale = [m for m in self.members if m != self.rank
                     and now - hbs.get(m, {}).get("wall_time", t0_wall)
                     > self.peer_timeout_s]
            if not stale:
                time.sleep(self.poll_interval_s * 5)
        if not stale:
            _mon().counter("resilience.elastic_blips_ignored").add(1)
            self._record("dispatch_blip", step=step,
                         error=f"{type(exc).__name__}: {exc}"[:200])
            return None
        _mon().counter("resilience.elastic_rank_deaths").add(len(stale))
        self._record("rank_death", step=step, ranks=stale,
                     source="dispatch_error",
                     error=f"{type(exc).__name__}: {exc}"[:200])
        return {"kind": "rank_death", "ranks": sorted(stale),
                "step": step, "source": "dispatch_error"}

    def _apply_policy(self, decision, step):
        """Turn a policy decision into counters/records, and into an
        evict event when the ladder ends at the shrink path."""
        action = decision["action"]
        _mon().counter(f"resilience.elastic_policy_{action}").add(1)
        self._record("policy", step=step, **decision)
        if action == "evict":
            target = decision["straggler"].get("process_index")
            if target is None:
                target = decision["straggler"]["dp_index"]
            return {"kind": "evict", "ranks": [int(target)],
                    "step": step, "decision": decision}
        return None       # warn/rebalance act in place, training goes on

    def topology(self):
        """The current committed topology stamp ({world, gen, members})
        — what every elastic save records as checkpoint provenance."""
        return {"world": self.world, "gen": self.gen,
                "members": list(self.members)}

    def batch_shares(self):
        """The policy's current per-rank batch shares (None before any
        rebalance) — what an elastic input pipeline consults when
        assembling the global batch."""
        return None if self.policy is None else self.policy.shares

    # -- transitions ---------------------------------------------------

    def begin_transition(self, kind, step, to_world, reason=None,
                         ranks=()):
        """Open the transition window: /healthz flips to 503
        reason=elastic_transition until commit_transition."""
        global _transition, _transitions_total
        payload = {"kind": kind, "step": int(step), "gen": self.gen,
                   "from_world": self.world, "to_world": int(to_world),
                   "reason": reason, "ranks": sorted(ranks),
                   "wall_time": time.time()}
        with _lock:
            _transition = payload
            _transitions_total += 1
        _mon().counter("resilience.elastic_transitions").add(1)
        _mon().counter(f"resilience.elastic_{kind}s").add(1)
        self._record("transition_begin", **payload)
        fr = _fr()
        # "transition" not "kind": the recorder's own event kind is the
        # first positional of note_event
        fr.note_event("elastic_transition", phase="begin",
                      transition=kind, step=int(step),
                      from_world=self.world, to_world=int(to_world))
        if self.on_transition is not None:
            self.on_transition(dict(payload))
        return payload

    def commit_transition(self, members, step):
        """Seal the new topology: write topology.json gen+1, sweep the
        control files of departed members and consumed join intents,
        close the /healthz window."""
        global _transition
        members = sorted(int(m) for m in members)
        self.gen += 1
        old_members = self.members
        self.members = members
        self.world = len(members)
        _atomic_json(self._path("topology.json"),
                     {"gen": self.gen, "world": self.world,
                      "members": members, "step": int(step),
                      "wall_time": time.time()})
        for m in old_members:
            if m not in members:
                for prefix in ("hb_r", "leave_r"):
                    try:
                        os.remove(self._path(f"{prefix}{m}.json"))
                    except OSError:
                        pass
        joined = []
        for m in members:
            if m not in old_members:
                joined.append(m)
                try:
                    os.remove(self._path(f"join_r{m}.json"))
                except OSError:
                    pass
        if joined:
            _mon().counter("resilience.elastic_rank_joins") \
                .add(len(joined))
        with _lock:
            _transition = None
        self._note_world()
        self._record("transition_commit", step=int(step), gen=self.gen,
                     world=self.world, members=members, joined=joined)
        _fr().note_event("elastic_transition", phase="commit",
                         gen=self.gen, world=self.world, step=int(step))

    def shrink(self, template_state, step, dead, save_state=None,
               extras=None):
        """The shrink recipe: force-save (when the survivor still holds
        a consistent boundary state), drop `dead` from the membership,
        and either reshard IN PROCESS (survivor set == {this rank}:
        restore the shared checkpoint replicated onto the local mesh
        and return (state, ck_step, mesh)) or commit + raise
        TopologyChanged(action="relaunch") for multi-survivor worlds.
        """
        dead = set(int(d) for d in dead)
        survivors = [m for m in self.members if m not in dead]
        if self.rank not in survivors:
            raise ValueError(f"rank {self.rank} cannot drive a shrink "
                             f"it does not survive ({survivors})")
        self.begin_transition("shrink", step, len(survivors),
                              reason="rank_loss", ranks=dead)
        if save_state is not None:
            self.force_save(save_state, step, extras=extras)
        if survivors != [self.rank]:
            self.commit_transition(survivors, step)
            raise TopologyChanged(step, {"kind": "shrink",
                                         "ranks": sorted(dead)},
                                  "relaunch")
        mesh = local_mesh()
        state, ck_step = self.manager.restore_resharded(
            template_state, mesh=mesh, step=None)
        self.commit_transition(survivors, step)
        return state, ck_step, mesh

    def grow(self, step, joiners, save_state=None, extras=None):
        """The grow recipe: force-save the rendezvous checkpoint,
        commit the enlarged membership, and raise TopologyChanged
        (action="relaunch") — a process cannot join an existing
        initialized jax world, so admission happens through the
        checkpoint + topology.json at the next launch."""
        joiners = sorted(int(j) for j in joiners)
        members = sorted(set(self.members) | set(joiners))
        self.begin_transition("grow", step, len(members),
                              reason="rank_join", ranks=joiners)
        if save_state is not None:
            self.force_save(save_state, step, extras=extras)
        self.commit_transition(members, step)
        raise TopologyChanged(step, {"kind": "rank_join",
                                     "ranks": joiners}, "relaunch")

    def force_save(self, state, step, extras=None):
        """Durable boundary state for the NEXT topology, stamped with
        the CURRENT one (the provenance restore_resharded reads)."""
        if self.manager.latest_step() != int(step):
            self.manager.save(state, int(step), force=True, extras=extras,
                              topology=self.topology())
            _mon().counter("resilience.elastic_force_saves").add(1)

    def resume(self, step=None):
        """Called by every member of a freshly-launched (grown or
        relaunched) fleet: adopt the committed topology, clear this
        rank's own stale leave intent, and record the resume."""
        topo = _read_json(self._path("topology.json"))
        if topo is not None:
            self.gen = int(topo["gen"])
            self.members = sorted(topo["members"])
            self.world = int(topo["world"])
        try:
            os.remove(self._path(f"leave_r{self.rank}.json"))
        except OSError:
            pass
        self._left = False
        self._note_world()
        _mon().counter("resilience.elastic_resumes").add(1)
        self._record("resume", step=step, gen=self.gen,
                     world=self.world, members=self.members)
        return topo

    # -- bookkeeping ---------------------------------------------------

    def _note_world(self):
        global _current_world
        with _lock:
            _current_world = self.world
        mon = _mon()
        mon.gauge("fleet.process_count").set(self.world)
        mon.gauge("fleet.topology_gen").set(self.gen)

    def _record(self, event, **fields):
        mon = _mon()
        if "kind" in fields:
            # a transition payload's own "kind" (shrink/grow) must not
            # shadow the JSONL record kind ("elastic")
            fields["transition"] = fields.pop("kind")
        try:
            mon.record_elastic({"kind": "elastic", "event": event,
                                "rank": self.rank, "gen": self.gen,
                                "world": self.world, **fields})
        except Exception:
            pass
        if event not in ("transition_begin", "transition_commit"):
            try:
                _fr().note_event(f"elastic_{event}", rank=self.rank,
                                 **{k: v for k, v in fields.items()
                                    if isinstance(v, (int, float, str,
                                                      list, tuple))})
            except Exception:
                pass
