"""Version info for paddle-tpu."""

full_version = "0.1.0"
major = 0
minor = 1
patch = 0
