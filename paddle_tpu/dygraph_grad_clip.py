"""`fluid.dygraph_grad_clip` import-path compatibility.

Parity: python/paddle/fluid/dygraph_grad_clip.py — the dygraph-era
GradClipBy* classes over the one clip implementation (clip.py).  NOTE
the argument-order difference between the two reference surfaces:
dygraph_grad_clip.GradClipByValue takes (min_value, max_value) (:92)
while clip.GradientClipByValue takes (max, min=None) — this shim
preserves each surface's own order rather than aliasing them.
"""

from .clip import GradientClipBase as GradClipBase  # noqa: F401
from .clip import GradientClipByGlobalNorm, GradientClipByNorm
from .clip import GradientClipByValue as _ByValueImpl

__all__ = ["GradClipBase", "GradClipByValue", "GradClipByNorm",
           "GradClipByGlobalNorm"]


class GradClipByValue(_ByValueImpl):
    """dygraph_grad_clip.py:92 — (min_value, max_value); min_value=None
    means -max_value (max_value must then be positive)."""

    def __init__(self, min_value, max_value=None):
        if min_value is None:
            assert max_value is not None and max_value > 0.0, \
                "max_value must be positive when min_value is None"
            min_value = -max_value
        if max_value is None:
            # single-arg form: the given value is the magnitude bound
            max_value = abs(float(min_value))
            min_value = -max_value
        super().__init__(max=max_value, min=min_value)


class GradClipByNorm(GradientClipByNorm):
    """dygraph_grad_clip.py:171 — same (clip_norm) signature."""


class GradClipByGlobalNorm(GradientClipByGlobalNorm):
    """dygraph_grad_clip.py:250 — (max_global_norm); the dtype arg is
    accepted and ignored (jax promotes as needed)."""

    def __init__(self, max_global_norm, dtype="float32"):
        super().__init__(max_global_norm)
