"""Training checkpoint/resume for full train states.

Parity surface: the reference's persistable-var save/load
(/root/reference/python/paddle/fluid/io.py:556 save_persistables and the
distributed variant :405 that gathers pserver-resident slices, plus
dygraph/checkpoint.py:33 save_dygraph). Here the unit of checkpointing
is the whole TrainState pytree (params + optimizer moments + buffers +
step + rng) via orbax — which restores arrays onto their original
NamedShardings, the TPU analogue of "distributed-aware save" — and the
PS sparse tables ride along as a full-row (ids, values+accumulators)
payload the way checkpoint_notify snapshots pserver lookup tables.

Crash safety: a step directory counts as a checkpoint only once its
_COMPLETE marker exists (written last), so a SIGKILL mid-save leaves the
previous complete checkpoint as the resume point.  Two hardenings on
top of the marker protocol (ISSUE 4):

- a per-file checksum MANIFEST (size + crc32 of every payload file,
  written after the arrays, before the marker): a checkpoint whose
  marker exists but whose bytes were truncated/corrupted after the
  marker write (partial disk, torn copy) is DETECTED and skipped by
  `latest_step`, falling back to the previous complete step instead of
  feeding garbage into restore;
- `CheckpointManager._gc` also removes incomplete/corrupt `step_*`
  dirs older than the newest complete checkpoint, so crashed save
  attempts can no longer leak disk forever (an incomplete dir NEWER
  than the best complete step is kept — it may be a save in flight).

Fault injection: `save_checkpoint` visits the
`checkpoint.before_marker` crash point between the array write and the
marker, so the kill-during-save recovery path is testable on purpose
(resilience.faultinject).
"""

import json
import os
import re
import shutil
import time
import zlib

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp

    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False

__all__ = ["save_checkpoint", "load_checkpoint", "latest_step",
           "load_extras", "load_topology", "restore_resharded",
           "resharded_cursor", "CheckpointManager"]

_STEP_DIR = re.compile(r"^step_(\d+)$")
_MARKER = "_COMPLETE"
_MANIFEST = "_MANIFEST.json"
_TOPOLOGY = "_TOPOLOGY.json"

_checkpointer = None

# verification memo: abs step path -> (manifest mtime_ns, ok).  A
# training loop calls latest_step via _gc on every save; re-crc'ing
# every complete checkpoint each time would double the save's IO.
_verify_memo = {}


def _mon():
    from . import monitor

    return monitor


def _crash_point(name):
    from .resilience import faultinject

    faultinject.crash_point(name)


def _stall_point(name):
    from .resilience import faultinject

    if faultinject.is_armed():
        faultinject.stall_point(name)


def _iter_payload_files(path):
    """Every file under the step dir except the marker/manifest
    themselves, as (relpath, abspath) in sorted order."""
    for root, dirs, files in os.walk(path):
        dirs.sort()
        for f in sorted(files):
            if root == path and f in (_MARKER, _MANIFEST):
                continue
            ap = os.path.join(root, f)
            yield os.path.relpath(ap, path), ap


def _file_crc32(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                return crc
            crc = zlib.crc32(b, crc)


def _write_manifest(path):
    entries = {}
    for rel, ap in _iter_payload_files(path):
        entries[rel] = {"size": os.path.getsize(ap),
                        "crc32": _file_crc32(ap)}
    tmp = os.path.join(path, _MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump({"version": 1, "files": entries}, f)
    os.replace(tmp, os.path.join(path, _MANIFEST))


def _payload_stat_sig(path):
    """Cheap (stat-only, no reads) fingerprint of the payload files:
    any truncation/rewrite changes a size or mtime and forces the crc
    pass to re-run, while an untouched checkpoint re-verifies for the
    cost of a directory walk."""
    sig = []
    for rel, ap in _iter_payload_files(path):
        try:
            st = os.stat(ap)
        except OSError:
            sig.append((rel, -1, -1))
            continue
        sig.append((rel, st.st_size, st.st_mtime_ns))
    return tuple(sig)


def _verify_manifest(path):
    """True when every manifested file exists with matching size and
    crc32.  A step dir WITHOUT a manifest (pre-manifest checkpoints)
    passes — the marker protocol is its only guarantee."""
    mpath = os.path.join(path, _MANIFEST)
    try:
        mstat = os.stat(mpath)
    except OSError:
        return True        # legacy checkpoint: marker-only protocol
    sig = (mstat.st_mtime_ns, _payload_stat_sig(path))
    memo = _verify_memo.get(path)
    if memo is not None and memo[0] == sig:
        return memo[1]
    try:
        with open(mpath) as f:
            manifest = json.load(f)
        ok = True
        for rel, want in manifest.get("files", {}).items():
            ap = os.path.join(path, rel)
            if not os.path.isfile(ap) \
                    or os.path.getsize(ap) != want["size"] \
                    or _file_crc32(ap) != want["crc32"]:
                ok = False
                break
    except (OSError, ValueError, KeyError):
        ok = False
    _verify_memo[path] = (sig, ok)
    return ok


def _ckptr():
    # one StandardCheckpointer per process: constructing one per save
    # spins up fresh async-IO machinery every step
    global _checkpointer
    if _checkpointer is None:
        _checkpointer = ocp.StandardCheckpointer()
    return _checkpointer


def _step_path(directory, step):
    return os.path.join(os.path.abspath(directory), f"step_{step}")


def _scan_steps(directory, verify=True):
    """One directory pass: sorted [(step, complete)].  With verify,
    `complete` demands the _COMPLETE marker AND a verified manifest —
    a markered-but-truncated checkpoint is not a checkpoint (memo-
    served for unchanged dirs: a stat walk, no payload reads).
    verify=False trusts the marker alone — the retention/GC criterion,
    which must not cold-CRC-read gigabytes of retained checkpoints
    from inside the training loop; corruption is caught where it
    matters, at restore-target selection (latest_step)."""
    if not os.path.isdir(directory):
        return []
    out = []
    for d in os.listdir(directory):
        m = _STEP_DIR.match(d)
        if not m:
            continue
        path = os.path.join(directory, d)
        complete = os.path.exists(os.path.join(path, _MARKER)) \
            and (not verify or _verify_manifest(path))
        out.append((int(m.group(1)), complete))
    return sorted(out)


def _list_steps(directory, complete_only=True):
    return [s for s, complete in _scan_steps(directory)
            if complete or not complete_only]


def latest_step(directory):
    """Highest COMPLETE (markered + checksum-verified) checkpointed
    step in `directory`, or None.

    Lazy: verifies newest-first and stops at the first good dir, so a
    cold-process resume reads ~one checkpoint's bytes, not every
    retained step's (older dirs get verified when _gc next looks)."""
    if not os.path.isdir(directory):
        return None
    marked = []
    for d in os.listdir(directory):
        m = _STEP_DIR.match(d)
        if m and os.path.exists(os.path.join(directory, d, _MARKER)):
            marked.append(int(m.group(1)))
    for s in sorted(marked, reverse=True):
        if _verify_manifest(_step_path(directory, s)):
            return s
    return None


def _current_topology():
    """Best-effort fleet shape at save time: the launcher env contract
    plus jax's own process/device counts once the backend is up (read
    through monitor.fleet.rank_info, which never initializes it).  This
    is the provenance restore_resharded and the elastic runtime read
    back — a checkpoint knows what world wrote it."""
    try:
        from .monitor import fleet

        info = fleet.rank_info()
        topo = {"process_count": info.get("process_count"),
                "process_index": info.get("process_index"),
                "host": info.get("host")}
        if info.get("local_device_ids") is not None:
            topo["local_device_count"] = len(info["local_device_ids"])
        try:
            from jax._src import xla_bridge as xb

            if xb.backends_are_initialized():
                topo["device_count"] = int(jax.device_count())
        except Exception:
            pass
        return topo
    except Exception:
        return {}


def _state_mesh_axes(state):
    """{axis: size} of the mesh the state's device arrays live on
    (the first NamedSharding-carrying leaf — a train state lives on
    ONE mesh), or None for host/numpy states.  Written into
    `_TOPOLOGY.json` so `restore_resharded` callers can see the
    WRITER's {dp,mp} shape without reconstructing its mesh."""
    for v in jax.tree_util.tree_leaves(state):
        sh = getattr(v, "sharding", None)
        m = getattr(sh, "mesh", None)
        if m is not None and getattr(m, "axis_names", None):
            try:
                return {str(a): int(m.shape[a]) for a in m.axis_names}
            except Exception:
                return None
    return None


def _leaf_name(kpath):
    """Last component of a tree_flatten_with_path key path as the
    plain var name state_specs are keyed by ('fc_0.w_0' etc.)."""
    if not kpath:
        return None
    last = kpath[-1]
    for attr in ("key", "name", "idx"):
        v = getattr(last, attr, None)
        if v is not None:
            return str(v)
    return str(last)


def save_checkpoint(directory, state, step, sparse_tables=None,
                    extras=None, topology=None, writer=None):
    """Write `state` (any pytree of jax/np arrays) at `step`.

    sparse_tables: optional {name: SparseEmbedding} — exported host-side
    with optimizer accumulators and restored into whatever sharding
    layout the loader uses.

    extras: optional {name: ndarray} sidecar riding OUTSIDE the
    template-matched state tree (read back with `load_extras`), so
    loaders with a different template still restore — the executor
    checkpoints its PRNG root key here, which is what makes a rollback
    replay of a stochastic (dropout) program bitwise-identical to the
    uninterrupted run.

    topology: optional dict merged over the auto-captured fleet shape
    (process/device counts) written as a `_TOPOLOGY.json` sidecar — the
    provenance `restore_resharded` and the elastic coordinator read
    back (`load_topology`).  Covered by the checksum manifest like any
    payload file.

    writer: "orbax" (default when available) or "npz".  The npz writer
    is COLLECTIVE-FREE: orbax's save runs a cross-process sync barrier
    in a multi-process jax world, which (a) desynchronizes single-
    writer saves against peers' training collectives and (b) can never
    complete once a peer is dead — exactly the moment the elastic
    runtime force-saves.  Elastic stores therefore use writer="npz"
    with host-replicated snapshots; the loaders auto-detect the format
    per checkpoint, so the two writers can share one directory.
    """
    t0 = time.perf_counter()
    # the whole synchronous write is badput the goodput ledger charges
    # to checkpoint_save (a no-op when no ledger is active); the stall
    # point lets the chaos bench inject a known-duration slow save
    gled = _mon().goodput.active()
    gpushed = gled is not None and gled.push("checkpoint_save")
    try:
        _stall_point("checkpoint.save")
        return _save_checkpoint_body(directory, state, step,
                                     sparse_tables=sparse_tables,
                                     extras=extras, topology=topology,
                                     writer=writer, t0=t0)
    finally:
        if gpushed:
            gled.pop()


def _save_checkpoint_body(directory, state, step, sparse_tables=None,
                          extras=None, topology=None, writer=None,
                          t0=None):
    if t0 is None:
        t0 = time.perf_counter()
    path = _step_path(directory, step)
    if os.path.isdir(path):  # overwrite an old/incomplete attempt
        shutil.rmtree(path)
        _verify_memo.pop(path, None)
    if writer is None:
        writer = "orbax" if _HAS_ORBAX else "npz"
    if writer == "orbax":
        ckptr = _ckptr()
        ckptr.save(os.path.join(path, "state"), state, force=True)
        ckptr.wait_until_finished()
    elif writer == "npz":
        os.makedirs(os.path.join(path, "state"), exist_ok=True)
        flat, _ = jax.tree_util.tree_flatten_with_path(state)
        arrays = {}
        for k, v in flat:
            if hasattr(v, "addressable_data"):
                if getattr(v, "is_fully_replicated", True):
                    # a replicated global array's shard 0 IS the value
                    # — np.asarray on a non-fully-addressable array
                    # would raise
                    v = v.addressable_data(0)
                elif getattr(v, "is_fully_addressable", False):
                    # sharded but local (single-process mesh):
                    # np.asarray gathers the shards on host
                    pass
                else:
                    # shard 0 of a cross-process SHARDED array is NOT
                    # the array; silently writing it would produce a
                    # checkpoint whose corruption only surfaces at
                    # restore time — after the other shards' owners
                    # may be dead.  The collective-free writer cannot
                    # gather them; refuse loudly at save time.
                    raise ValueError(
                        f"npz checkpoint writer: leaf "
                        f"{jax.tree_util.keystr(k)} is sharded across "
                        f"processes ({v.sharding}); the collective-"
                        f"free writer only handles replicated or "
                        f"locally-addressable arrays — pass a host "
                        f"snapshot or use the orbax writer")
            arrays[jax.tree_util.keystr(k)] = np.asarray(v)
        tmp = os.path.join(path, "state", "arrays.npz.tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(tmp, os.path.join(path, "state", "arrays.npz"))
    else:
        raise ValueError(f"unknown checkpoint writer {writer!r}")
    if sparse_tables:
        os.makedirs(path, exist_ok=True)
        payload = {}
        for name, table in sparse_tables.items():
            st = table.state_dict()
            payload[f"{name}.ids"] = st["ids"]
            payload[f"{name}.values"] = st["values"]
        np.savez(os.path.join(path, "sparse_tables.npz"), **payload)
    if extras:
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, "extras.npz"),
                 **{k: np.asarray(v) for k, v in extras.items()})
    # topology provenance: what fleet shape wrote this checkpoint.
    # Written BEFORE the manifest so its bytes are checksum-covered.
    topo = _current_topology()
    mesh_axes = _state_mesh_axes(state)
    if mesh_axes is not None:
        topo["mesh_axes"] = mesh_axes
    topo.update(topology or {})
    topo["step"] = step
    topo["wall_time"] = time.time()
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, _TOPOLOGY), "w") as f:
        json.dump(topo, f)
    # the crash window under test: arrays are on disk, the marker is
    # not — a kill here must leave the PREVIOUS checkpoint as the
    # resume point (resilience.faultinject fires InjectedCrash here
    # when armed)
    _crash_point("checkpoint.before_marker")
    _write_manifest(path)
    # marker last: only now does this step count as a checkpoint
    with open(os.path.join(path, _MARKER), "w") as f:
        f.write("ok\n")
    # seed the verification memo: the writer just computed these CRCs,
    # so the next _list_steps (the manager's own _gc, one line from
    # now) must not re-read the whole checkpoint to re-derive them
    _verify_memo[path] = ((os.stat(os.path.join(path, _MANIFEST))
                           .st_mtime_ns, _payload_stat_sig(path)), True)
    mon = _mon()
    if mon.is_enabled():
        mon.counter("resilience.checkpoint_saves").add(1)
        mon.gauge("resilience.last_save_s").set(
            round(time.perf_counter() - t0, 4))
    return path


def load_checkpoint(directory, template_state, step=None,
                    sparse_tables=None):
    """Restore a checkpoint into the structure/shardings of
    `template_state` (e.g. a freshly-initialised TrainState — sharded
    leaves come back with their NamedShardings). Returns (state, step)."""
    t0 = time.perf_counter()
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _step_path(directory, step)
    npz = os.path.join(path, "state", "arrays.npz")
    if _HAS_ORBAX and not os.path.isfile(npz):
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct,
                                template_state)
        state = _ckptr().restore(os.path.join(path, "state"), abstract)
    else:
        data = np.load(npz)
        flat, treedef = jax.tree_util.tree_flatten_with_path(template_state)
        leaves = [data[jax.tree_util.keystr(k)] for k, _ in flat]
        state = jax.tree.unflatten(treedef, leaves)
        state = jax.tree.map(
            lambda t, v: jax.device_put(v, t.sharding)
            if hasattr(t, "sharding") else v, template_state, state)
    if sparse_tables:
        npz = np.load(os.path.join(path, "sparse_tables.npz"))
        for name, table in sparse_tables.items():
            table.load_state_dict({"ids": npz[f"{name}.ids"],
                                   "values": npz[f"{name}.values"]})
    mon = _mon()
    if mon.is_enabled():
        mon.counter("resilience.checkpoint_restores").add(1)
        mon.gauge("resilience.last_restore_s").set(
            round(time.perf_counter() - t0, 4))
    return state, step


def load_extras(directory, step=None):
    """The extras sidecar of checkpoint `step` (default: latest
    complete) as {name: np.ndarray}; {} when the checkpoint has none."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    p = os.path.join(_step_path(directory, step), "extras.npz")
    if not os.path.isfile(p):
        return {}
    with np.load(p) as npz:
        return {k: npz[k] for k in npz.files}


def load_topology(directory, step=None):
    """The `_TOPOLOGY.json` provenance of checkpoint `step` (default:
    latest complete): what fleet shape (process/device counts) wrote
    it.  None for pre-topology checkpoints."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    p = os.path.join(_step_path(directory, step), _TOPOLOGY)
    if not os.path.isfile(p):
        return None
    with open(p) as f:
        return json.load(f)


def resharded_cursor(step, old_world=None, new_world=None,
                     preserve_global_batch=True):
    """The data cursor (consumed GLOBAL batches) after restoring
    checkpoint `step` onto a different world size.

    The checkpoint counts steps in global batches.  When the global
    batch is PRESERVED across the reshard (each survivor feeds a larger
    slice — the bitwise-identical-math mode), one step still consumes
    one global batch and the cursor is unchanged.  When the PER-RANK
    batch is preserved instead (the global batch scales with the
    world), each old step consumed `old_world` rank-batches, so the
    resumed loop's cursor in NEW global batches is
    ``step * old_world // new_world`` (floor: a partial new-batch is
    re-consumed rather than skipped — never silently drop data)."""
    step = int(step)
    if preserve_global_batch:
        return step
    if not old_world or not new_world:
        raise ValueError("per-rank-batch cursor scaling needs old_world "
                         "and new_world")
    return (step * int(old_world)) // int(new_world)


def restore_resharded(directory, template_state, mesh=None, step=None,
                      sparse_tables=None, state_specs=None):
    """Restore checkpoint `step` (default: newest COMPLETE — a
    truncated/corrupt newest dir is skipped by latest_step's checksum
    pass, falling back to the previous complete step) onto a DIFFERENT
    topology than the one that saved it.

    Unlike load_checkpoint, the template is used for STRUCTURE ONLY
    (shape/dtype — its leaves are never materialized, so a template
    holding arrays committed to a dead mesh is safe); arrays are
    restored to host and re-placed REPLICATED on `mesh` (or returned as
    host arrays when mesh is None, for callers doing their own
    placement).  Replication is what makes the reshard bitwise-exact:
    every device of the new mesh sees the identical bytes the old
    world saved, whatever either world's shape.

    state_specs (ISSUE 16): optional {leaf_name: ShardSpec-or-
    PartitionSpec} — leaves named in it are placed SHARDED on `mesh`
    instead of replicated (a ShardingPlan.state_specs lowers a TP
    checkpoint straight onto another {dp,mp} shape: the host bytes are
    identical either way, placement only decides which slice each
    device holds, so the reshard stays bitwise).  Leaves without a
    spec, and every leaf when state_specs is None, replicate as
    before.

    Returns (state, step).  Counted as `resilience.elastic_reshards`
    next to the ordinary restore counters."""
    t0 = time.perf_counter()
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _step_path(directory, step)

    def _abstract(v):
        # metadata-only: np.shape/.dtype never touch device buffers, so
        # a template leaf living on an unreachable mesh cannot hang us
        dt = getattr(v, "dtype", None)
        if dt is None:
            dt = np.asarray(v).dtype
        return np.empty(np.shape(v), dt)

    npz = os.path.join(path, "state", "arrays.npz")
    if _HAS_ORBAX and not os.path.isfile(npz):
        # numpy-template restore: orbax reads the bytes WITHOUT
        # consulting the saved sharding file, which references the
        # WRITER's (possibly no longer constructible) mesh
        abstract = jax.tree.map(_abstract, template_state)
        state = _ckptr().restore(os.path.join(path, "state"), abstract)
    else:
        data = np.load(npz)
        flat, treedef = jax.tree_util.tree_flatten_with_path(template_state)
        state = jax.tree.unflatten(
            treedef, [data[jax.tree_util.keystr(k)] for k, _ in flat])
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        rep = NamedSharding(mesh, PartitionSpec())
        multiproc = len({getattr(d, "process_index", 0)
                         for d in mesh.devices.flat}) > 1

        def _target(kpath):
            if not state_specs:
                return rep
            spec = state_specs.get(_leaf_name(kpath))
            if spec is None:
                return rep
            if hasattr(spec, "to_jax"):     # analyzer ShardSpec
                spec = spec.to_jax()
            return NamedSharding(mesh, spec)

        def _place(kpath, v):
            sh = _target(kpath)
            arr = np.asarray(v)
            if multiproc:
                # every process restored identical full bytes from the
                # shared store; each serves the shards it addresses by
                # slicing its own copy (replicated target: the full
                # index — same path as before)
                return jax.make_array_from_callback(
                    arr.shape, sh, lambda idx, a=arr: a[idx])
            return jax.device_put(v, sh)

        state = jax.tree_util.tree_map_with_path(_place, state)
    if sparse_tables:
        npz = np.load(os.path.join(path, "sparse_tables.npz"))
        for name, table in sparse_tables.items():
            table.load_state_dict({"ids": npz[f"{name}.ids"],
                                   "values": npz[f"{name}.values"]})
    mon = _mon()
    mon.counter("resilience.elastic_reshards").add(1)
    if mon.is_enabled():
        mon.counter("resilience.checkpoint_restores").add(1)
        mon.gauge("resilience.last_restore_s").set(
            round(time.perf_counter() - t0, 4))
    try:
        from .monitor import flight_recorder

        flight_recorder.note_event(
            "elastic_reshard", step=step,
            mesh_shape=(None if mesh is None
                        else list(np.shape(mesh.devices))))
    except Exception:
        pass
    return state, step


class CheckpointManager:
    """Keep-last-N rolling checkpoints with save_interval gating
    (fleet_util save-model cadence parity, minus HDFS)."""

    def __init__(self, directory, max_to_keep=3, save_interval_steps=1,
                 writer=None):
        """writer: None (orbax when available) or "npz" — the
        collective-free writer elastic fleet stores need (a survivor
        force-saving after a peer died cannot join orbax's cross-
        process sync barrier).  Loaders auto-detect per checkpoint."""
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self.save_interval_steps = save_interval_steps
        self.writer = writer

    def should_save(self, step):
        return step % self.save_interval_steps == 0

    def save(self, state, step, sparse_tables=None, force=False,
             extras=None, topology=None):
        """Checkpoint if `step` is on the save interval (or force=True).
        Returns the path, or None when gated off."""
        if not force and not self.should_save(step):
            return None
        path = save_checkpoint(self.directory, state, step, sparse_tables,
                               extras=extras, topology=topology,
                               writer=self.writer)
        self._gc()
        return path

    def load_extras(self, step=None):
        return load_extras(self.directory, step)

    def load_topology(self, step=None):
        return load_topology(self.directory, step)

    def latest_step(self):
        return latest_step(self.directory)

    def restore_latest(self, template_state, sparse_tables=None):
        return load_checkpoint(self.directory, template_state,
                               sparse_tables=sparse_tables)

    def restore_resharded(self, template_state, mesh=None, step=None,
                          sparse_tables=None, state_specs=None):
        """Topology-change restore (ISSUE 11): bring the newest
        complete checkpoint — whatever world size saved it — up
        REPLICATED on `mesh` (or as host arrays when mesh is None);
        `state_specs` places named leaves SHARDED instead (ISSUE 16).
        See module-level restore_resharded."""
        return restore_resharded(self.directory, template_state,
                                 mesh=mesh, step=step,
                                 sparse_tables=sparse_tables,
                                 state_specs=state_specs)

    def _gc(self):
        """Rolling retention PLUS orphan cleanup: crashed save
        attempts (no marker) older than the newest markered checkpoint
        are dead weight — without this they leak disk forever.  An
        incomplete dir NEWER than the best markered step is left
        alone: it may be a save currently in flight.  Retention
        trusts the MARKER only (verify=False): a markered-but-corrupt
        dir occupies a keep slot until rotation, and `latest_step`'s
        lazy checksum pass skips it at restore time — the alternative
        is cold-CRC-reading every retained checkpoint on the first
        save of a resumed process."""
        scan = _scan_steps(self.directory, verify=False)  # ONE stat pass
        complete = [s for s, ok in scan if ok]
        doomed = complete[:-self.max_to_keep]
        if doomed:
            # rotation must never delete the last verified-GOOD
            # checkpoint: on a store whose newer markered dirs were
            # corrupted post-marker, the oldest (good) one is all that
            # stands between a rollback and total run loss.  Normal
            # path stays cheap: latest_step stops at the newest dir,
            # whose verification the save that triggered this _gc just
            # memo-seeded.
            newest_good = latest_step(self.directory)
            doomed = [s for s in doomed
                      if newest_good is not None and s < newest_good]
        if complete:
            newest = complete[-1]
            doomed += [s for s, ok in scan if not ok and s < newest]
        for s in doomed:
            path = _step_path(self.directory, s)
            shutil.rmtree(path, ignore_errors=True)
            _verify_memo.pop(path, None)
