"""Training checkpoint/resume for full train states.

Parity surface: the reference's persistable-var save/load
(/root/reference/python/paddle/fluid/io.py:556 save_persistables and the
distributed variant :405 that gathers pserver-resident slices, plus
dygraph/checkpoint.py:33 save_dygraph). Here the unit of checkpointing
is the whole TrainState pytree (params + optimizer moments + buffers +
step + rng) via orbax — which restores arrays onto their original
NamedShardings, the TPU analogue of "distributed-aware save" — and the
PS sparse tables ride along as a full-row (ids, values+accumulators)
payload the way checkpoint_notify snapshots pserver lookup tables.

Crash safety: a step directory counts as a checkpoint only once its
_COMPLETE marker exists (written last), so a SIGKILL mid-save leaves the
previous complete checkpoint as the resume point.
"""

import os
import re
import shutil

import jax
import numpy as np

try:
    import orbax.checkpoint as ocp

    _HAS_ORBAX = True
except Exception:  # pragma: no cover
    _HAS_ORBAX = False

__all__ = ["save_checkpoint", "load_checkpoint", "latest_step",
           "CheckpointManager"]

_STEP_DIR = re.compile(r"^step_(\d+)$")
_MARKER = "_COMPLETE"

_checkpointer = None


def _ckptr():
    # one StandardCheckpointer per process: constructing one per save
    # spins up fresh async-IO machinery every step
    global _checkpointer
    if _checkpointer is None:
        _checkpointer = ocp.StandardCheckpointer()
    return _checkpointer


def _step_path(directory, step):
    return os.path.join(os.path.abspath(directory), f"step_{step}")


def _list_steps(directory, complete_only=True):
    if not os.path.isdir(directory):
        return []
    steps = []
    for d in os.listdir(directory):
        m = _STEP_DIR.match(d)
        if not m:
            continue
        if complete_only and not os.path.exists(
                os.path.join(directory, d, _MARKER)):
            continue
        steps.append(int(m.group(1)))
    return sorted(steps)


def latest_step(directory):
    """Highest COMPLETE checkpointed step in `directory`, or None."""
    steps = _list_steps(directory)
    return steps[-1] if steps else None


def save_checkpoint(directory, state, step, sparse_tables=None):
    """Write `state` (any pytree of jax/np arrays) at `step`.

    sparse_tables: optional {name: SparseEmbedding} — exported host-side
    with optimizer accumulators and restored into whatever sharding
    layout the loader uses.
    """
    path = _step_path(directory, step)
    if os.path.isdir(path):  # overwrite an old/incomplete attempt
        shutil.rmtree(path)
    if _HAS_ORBAX:
        ckptr = _ckptr()
        ckptr.save(os.path.join(path, "state"), state, force=True)
        ckptr.wait_until_finished()
    else:  # pragma: no cover
        os.makedirs(os.path.join(path, "state"), exist_ok=True)
        flat, _ = jax.tree.flatten_with_path(state)
        np.savez(os.path.join(path, "state", "arrays.npz"),
                 **{jax.tree_util.keystr(k): np.asarray(v)
                    for k, v in flat})
    if sparse_tables:
        os.makedirs(path, exist_ok=True)
        payload = {}
        for name, table in sparse_tables.items():
            st = table.state_dict()
            payload[f"{name}.ids"] = st["ids"]
            payload[f"{name}.values"] = st["values"]
        np.savez(os.path.join(path, "sparse_tables.npz"), **payload)
    # marker last: only now does this step count as a checkpoint
    with open(os.path.join(path, _MARKER), "w") as f:
        f.write("ok\n")
    return path


def load_checkpoint(directory, template_state, step=None,
                    sparse_tables=None):
    """Restore a checkpoint into the structure/shardings of
    `template_state` (e.g. a freshly-initialised TrainState — sharded
    leaves come back with their NamedShardings). Returns (state, step)."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _step_path(directory, step)
    if _HAS_ORBAX:
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct,
                                template_state)
        state = _ckptr().restore(os.path.join(path, "state"), abstract)
    else:  # pragma: no cover
        data = np.load(os.path.join(path, "state", "arrays.npz"))
        flat, treedef = jax.tree.flatten_with_path(template_state)
        leaves = [data[jax.tree_util.keystr(k)] for k, _ in flat]
        state = jax.tree.unflatten(treedef, leaves)
        state = jax.tree.map(
            lambda t, v: jax.device_put(v, t.sharding)
            if hasattr(t, "sharding") else v, template_state, state)
    if sparse_tables:
        npz = np.load(os.path.join(path, "sparse_tables.npz"))
        for name, table in sparse_tables.items():
            table.load_state_dict({"ids": npz[f"{name}.ids"],
                                   "values": npz[f"{name}.values"]})
    return state, step


class CheckpointManager:
    """Keep-last-N rolling checkpoints with save_interval gating
    (fleet_util save-model cadence parity, minus HDFS)."""

    def __init__(self, directory, max_to_keep=3, save_interval_steps=1):
        self.directory = os.path.abspath(directory)
        self.max_to_keep = max_to_keep
        self.save_interval_steps = save_interval_steps

    def should_save(self, step):
        return step % self.save_interval_steps == 0

    def save(self, state, step, sparse_tables=None, force=False):
        """Checkpoint if `step` is on the save interval (or force=True).
        Returns the path, or None when gated off."""
        if not force and not self.should_save(step):
            return None
        path = save_checkpoint(self.directory, state, step, sparse_tables)
        self._gc()
        return path

    def restore_latest(self, template_state, sparse_tables=None):
        return load_checkpoint(self.directory, template_state,
                               sparse_tables=sparse_tables)

    def _gc(self):
        for s in _list_steps(self.directory)[:-self.max_to_keep]:
            shutil.rmtree(_step_path(self.directory, s),
                          ignore_errors=True)
