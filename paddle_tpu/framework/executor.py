"""Executor + Scope.

TPU-native replacement for the reference Executor stack:
- `Executor::Run` hot loop (/root/reference/paddle/fluid/framework/executor.cc:449)
- the Python feed/fetch façade (python/paddle/fluid/executor.py:676)
- ParallelExecutor/graph passes (framework/parallel_executor.cc) — subsumed
  by XLA: the whole program becomes ONE jitted function, so fusion, memory
  planning and scheduling belong to the compiler, and the per-op dynamic
  dispatch loop only exists at trace time.

Execution model: a Program's op list is interpreted once while tracing; the
traced function `step(state, feeds, rng) -> (new_state, fetches)` is jitted
with state-buffer donation (the analogue of the reference's in-place
variable mutation).  BackwardSection markers (see program.py) are realized
with jax.value_and_grad over the preceding forward segment.

Scope maps variable names to device arrays (parity: framework/scope.h:46,
minus the parent-chain — programs here resolve names at trace time).
"""

import contextlib
import time

import numpy as np

import jax
import jax.numpy as jnp

from .. import flags
from ..core.dtype import to_jax_dtype
from ..core.place import default_place
from ..ops.registry import get_op
from .compiler import apply_precision_policy, resolve_precision
from .program import Variable, default_main_program

_profiler = None
_monitor = None
_resilience = None
_op_sampler_slot = None
_flight = None
_fleet_mod = None
_goodput_mod = None


def _dispatch_span(name):
    """profiler.RecordEvent span when a profiling session is active,
    else a no-op context — the steady-state dispatch path must not grow
    the profiler's event list on every step of a long training run."""
    global _profiler
    if _profiler is None:
        from .. import profiler

        _profiler = profiler
    if _profiler.is_profiling():
        return _profiler.RecordEvent(name)
    return contextlib.nullcontext()


def _mon():
    """Lazy paddle_tpu.monitor handle (same import-cycle discipline as
    _profiler): the telemetry subsystem Executor.run feeds per-step
    metrics and compile events into while monitor.is_enabled()."""
    global _monitor
    if _monitor is None:
        from .. import monitor

        _monitor = monitor
    return _monitor


def _res():
    """Lazy paddle_tpu.resilience handle: anomaly guard, retry policy,
    preemption flag, and the fault-injection harness the dispatch path
    consults.  When nothing is enabled the whole fault-tolerance layer
    costs the steady state three None checks per run."""
    global _resilience
    if _resilience is None:
        from .. import resilience

        _resilience = resilience
    return _resilience


def _sampler():
    """Active per-op sampler (monitor.op_profile.sampling scope) or
    None — resolved through the module's single-slot list so the
    interpreter loop pays one list load per op while sampling is off."""
    global _op_sampler_slot
    if _op_sampler_slot is None:
        from ..monitor import op_profile

        _op_sampler_slot = op_profile._ACTIVE
    return _op_sampler_slot[0]


def _fr():
    """The always-on flight recorder (monitor.flight_recorder): a
    bounded ring of step/compile/recovery records that costs one deque
    append per step while healthy and dumps a post-mortem on crash."""
    global _flight
    if _flight is None:
        from ..monitor import flight_recorder

        _flight = flight_recorder.get()
    return _flight


def _fleet():
    """Lazy paddle_tpu.monitor.fleet handle (ISSUE 10): rank identity,
    the dp timestamp feeds, and the skew ring the straggler probe's
    gathered wait vectors land in."""
    global _fleet_mod
    if _fleet_mod is None:
        from ..monitor import fleet

        _fleet_mod = fleet
    return _fleet_mod


def _gp():
    """Lazy paddle_tpu.monitor.goodput handle (ISSUE 20): the run
    ledger the dispatch path charges wall time into.  goodput.active()
    is None unless FLAGS_goodput armed one — the whole off path is one
    module-global read."""
    global _goodput_mod
    if _goodput_mod is None:
        from ..monitor import goodput

        _goodput_mod = goodput
    return _goodput_mod


# reusable (contextlib.nullcontext is reentrant) — the off path must
# not allocate a context object per span site
_NULL_CTX = contextlib.nullcontext()


def _gspan(category):
    """Goodput span context for `category`: a real ledger span while a
    run ledger is active, the shared nullcontext otherwise."""
    gled = _gp().active()
    if gled is None:
        return _NULL_CTX
    return gled.span(category)


def _goodput_batches(gen):
    """Iterate `gen` charging the wait for each next prepared batch to
    the active ledger's data_wait bucket (reader / prefetch / sparse-
    pull starvation as seen by the consuming thread); a plain
    passthrough when no ledger is active."""
    gen = iter(gen)
    end = object()
    while True:
        gled = _gp().active()
        if gled is None:
            item = next(gen, end)
        else:
            with gled.span("data_wait"):
                item = next(gen, end)
        if item is end:
            return
        yield item


def _materialize(fetches):
    """Block on device fetches and copy them to host numpy arrays — the
    ONE sync point of the dispatch path.  Every host materialization the
    executor performs goes through here so the no-sync steady-state
    contract of train_from_dataset is testable (a counting wrapper over
    this function observes every sync)."""
    return [np.asarray(f) for f in fetches]


class Scope:
    """name -> array store for persistable variables."""

    def __init__(self):
        self.vars = {}

    def find_var(self, name):
        return self.vars.get(name)

    def var(self, name):
        return self.vars.setdefault(name, None)

    def set_var(self, name, value):
        self.vars[name] = value

    def drop_kids(self):
        self.vars.clear()

    def local_var_names(self):
        return list(self.vars)


_global_scope = Scope()


def global_scope():
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def guard():
        global _global_scope
        old = _global_scope
        _global_scope = scope
        try:
            yield
        finally:
            _global_scope = old

    return guard()


class _RngBox:
    """Mutable PRNG key holder threaded through op interpretation."""

    __slots__ = ("key",)

    def __init__(self, key):
        self.key = key

    def next(self):
        self.key, sub = jax.random.split(self.key)
        return sub


def _resolve_slot(env, names):
    vals = []
    for n in names:
        if n not in env:
            raise KeyError(
                f"variable '{n}' has no value: not fed, not initialized "
                f"(did you run the startup program?)"
            )
        vals.append(env[n])
    if len(vals) == 1:
        return vals[0]
    return vals


# Ops whose outputs are trace-time constants (static attrs only). Their
# concrete numpy values are tracked in a side const_env so that ops with
# value-dependent output SHAPES (range, linspace) can still resolve under
# jit — the analogue of the reference's compile-time shape inference for
# fill_constant-fed shape ops.
_CONST_EVAL = {
    "fill_constant": lambda ins, attrs: {
        "Out": np.full(tuple(attrs.get("shape", [])),
                       float(attrs.get("value", 0.0)))},
    "assign_value": lambda ins, attrs: {
        "Out": np.array(
            attrs.get("fp32_values") or attrs.get("int32_values")
            or attrs.get("int64_values") or attrs.get("bool_values")
        ).reshape(attrs.get("shape"))},
}

# Ops that need CONCRETE input values (output shape depends on them).
_NEEDS_CONST_INPUTS = {"range", "linspace"}

# Ops with data-dependent output shapes: impossible under jit by
# construction (XLA static shapes); they work in the eager executor.
_DYNAMIC_SHAPE_OPS = {"where_index", "masked_select", "unique",
                      "shrink_memory"}


def _branch_env(env):
    # lists are tensor-arrays with trace-time mutation semantics; both
    # lax.cond branches get traced, so they must NOT share the outer list
    return {k: (list(v) if isinstance(v, list) else v)
            for k, v in env.items()}


def _branch_fn(ops, env, key, out_names, const_env=None):
    """Interpret a sub-block against a copy of the outer env, returning
    the named results — the body of a lax.cond/while/scan closure. `key`
    seeds a branch-local RngBox so rng draws inside the traced closure
    never mutate the outer box with an inner-trace tracer."""
    def fn(bound, key=key):
        benv = _branch_env(env)
        benv.update(bound)
        box = _RngBox(key)
        interpret(ops, benv, box, const_env)
        return tuple(benv[n] for n in out_names)

    return fn


def _run_cond(op, env, rng_box, const_env=None):
    """conditional_block pair -> lax.cond (layers/control_flow.py cond)."""
    program = op.block.program
    a = op.attrs
    pred = env[op.inputs["Pred"][0]]
    pred = jnp.asarray(pred).reshape(())
    t_ops = program.blocks[a["true_block"]].ops
    f_ops = program.blocks[a["false_block"]].ops
    k = rng_box.next()  # outer-level split; branches fold a branch id in
    outs = jax.lax.cond(
        pred,
        lambda _: _branch_fn(t_ops, env, jax.random.fold_in(k, 0),
                             a["true_outs"], const_env)({}),
        lambda _: _branch_fn(f_ops, env, jax.random.fold_in(k, 1),
                             a["false_outs"], const_env)({}),
        None)
    for n, v in zip(op.outputs["Out"], outs):
        env[n] = v


def _run_switch(op, env, rng_box, const_env=None):
    """Switch -> right-folded lax.cond chain (layers Switch parity:
    first true case wins, else default, else values unchanged)."""
    program = op.block.program
    a = op.attrs
    out_names = a["out_names"]
    for n in out_names:
        if n not in env:
            raise KeyError(
                f"Switch writes '{n}' but it has no value before the "
                f"switch (cases only run conditionally)")
    k = rng_box.next()
    result = tuple(env[n] for n in out_names)
    if a.get("default_block") is not None:
        d_ops = program.blocks[a["default_block"]].ops
        # branch id past all case ids; fold_in rejects negative ints
        result = _branch_fn(d_ops, env,
                            jax.random.fold_in(k, len(a["case_blocks"])),
                            out_names, const_env)({})
    for i in range(len(a["case_blocks"]) - 1, -1, -1):
        pred = jnp.asarray(env[a["case_preds"][i]]).reshape(())
        c_ops = program.blocks[a["case_blocks"][i]].ops
        taken = _branch_fn(c_ops, env, jax.random.fold_in(k, i),
                           out_names, const_env)
        result = jax.lax.cond(pred, lambda _, t=taken: t({}),
                              lambda _, r=result: r, None)
    for n, v in zip(op.outputs["Out"], result):
        env[n] = v


def _run_while(op, env, rng_box, const_env=None):
    """while_op.cc -> lax.while_loop."""
    program = op.block.program
    a = op.attrs
    loop_names = op.inputs["LoopVars"]
    init_vars = tuple(jnp.asarray(env[n]) for n in loop_names)
    c_ops = program.blocks[a["cond_block"]].ops
    b_ops = program.blocks[a["body_block"]].ops
    # rng key rides in the carry so each iteration draws fresh randomness
    init = init_vars + (rng_box.next(),)

    def cond_fn(carry):
        (out,) = _branch_fn(c_ops, env, carry[-1], [a["cond_out"]],
                            const_env)(dict(zip(a["cond_inner"],
                                                carry[:-1])))
        return jnp.asarray(out).reshape(())

    def body_fn(carry):
        key, sub = jax.random.split(carry[-1])
        outs = _branch_fn(b_ops, env, sub, a["body_outs"], const_env)(
            dict(zip(a["body_inner"], carry[:-1])))
        return tuple(jnp.asarray(o, init_vars[i].dtype)
                     for i, o in enumerate(outs)) + (key,)

    max_iters = a.get("max_iters")
    if max_iters:
        # bounded lowering onto lax.scan so reverse-mode AD works (the
        # while_grad parity path): iterate max_iters times, freezing the
        # carry once the condition goes false
        def scan_body(carry, _):
            run = cond_fn(carry)
            new = body_fn(carry)
            frozen = tuple(jnp.where(run, n, c)
                           for n, c in zip(new[:-1], carry[:-1]))
            return frozen + (new[-1],), None

        outs, _ = jax.lax.scan(scan_body, init, None, length=int(max_iters))
    else:
        outs = jax.lax.while_loop(cond_fn, body_fn, init)
    for n, v in zip(op.outputs["Out"], outs[:-1]):
        env[n] = v


def _run_static_rnn(op, env, rng_box, const_env=None):
    """StaticRNN -> lax.scan over the leading (time) axis."""
    program = op.block.program
    a = op.attrs
    ops = program.blocks[a["block"]].ops
    xs = tuple(jnp.asarray(env[n]) for n in op.inputs["StepInputs"])
    init_mem = tuple(jnp.asarray(env[n]) for n in op.inputs["InitMemories"])
    init = init_mem + (rng_box.next(),)

    def body(carry, x_t):
        key, sub = jax.random.split(carry[-1])
        bound = dict(zip(a["memory_inner"], carry[:-1]))
        bound.update(zip(a["input_inner"], x_t))
        outs = _branch_fn(ops, env, sub,
                          list(a["memory_update"]) + list(a["step_outs"]),
                          const_env)(bound)
        n_mem = len(a["memory_update"])
        new_carry = tuple(jnp.asarray(o, init_mem[i].dtype)
                          for i, o in enumerate(outs[:n_mem]))
        return new_carry + (key,), tuple(outs[n_mem:])

    _, stacked = jax.lax.scan(body, init, xs)
    for n, v in zip(op.outputs["Out"], stacked):
        env[n] = v


def _array_index(name, env, const_env):
    v = env.get(name)
    try:
        return int(np.asarray(v))
    except Exception:
        if const_env is not None and name in const_env:
            return int(np.asarray(const_env[name]))
        raise NotImplementedError(
            "tensor-array indices must be compile-time constants under "
            "the jitted executor (use while_loop/scan state for dynamic "
            "indexing, or FLAGS_eager_executor)")


def _run_array_op(op, env, rng_box, const_env=None):
    """LoDTensorArray ops: trace-time python-list semantics. The index
    must be trace-time static under jit (use while_loop/scan otherwise)."""
    t = op.type
    if t == "create_array":
        env[op.outputs["Out"][0]] = []
        return
    if t == "array_write":
        arr = env[op.inputs["Array"][0]]
        i = _array_index(op.inputs["I"][0], env, const_env)
        x = env[op.inputs["X"][0]]
        if i == len(arr):
            arr.append(x)
        elif i < len(arr):
            arr[i] = x
        else:
            raise IndexError(f"array_write index {i} > length {len(arr)}")
        return
    if t == "array_read":
        arr = env[op.inputs["Array"][0]]
        i = _array_index(op.inputs["I"][0], env, const_env)
        env[op.outputs["Out"][0]] = arr[i]
        return
    if t == "array_length":
        arr = env[op.inputs["Array"][0]]
        env[op.outputs["Out"][0]] = jnp.asarray(len(arr), jnp.int32)
        return
    if t in ("lod_tensor_to_array", "array_to_lod_tensor"):
        # row counts are value-dependent -> concrete values only, same
        # contract as _DYNAMIC_SHAPE_OPS but routed via the array table
        import jax.core as _core

        probe = jax.tree.leaves(
            [env.get(n) for names in op.inputs.values() for n in names])
        if any(isinstance(v, _core.Tracer) for v in probe):
            raise NotImplementedError(
                f"op '{t}' has data-dependent output shapes and cannot "
                f"run under the jitted executor; set "
                f"FLAGS_eager_executor=1 for this program")
    if t == "lod_tensor_to_array":
        # control_flow.py:1132 parity: split [B, T, ...] into
        # per-timestep slices over the rank-table's still-active prefix.
        # Row counts are value-dependent -> concrete lengths only
        # (FLAGS_eager_executor), like the reference's LoD machinery.
        x = np.asarray(env[op.inputs["X"][0]])
        table = np.asarray(env[op.inputs["RankTable"][0]])
        order, lengths = table[:, 0].astype(int), table[:, 1]
        max_len = int(lengths[0]) if len(lengths) else 0
        out = []
        for t_step in range(max_len):
            active = int((lengths > t_step).sum())
            out.append(jnp.asarray(x[order[:active], t_step]))
        env[op.outputs["Out"][0]] = out
        return
    if t == "array_to_lod_tensor":
        # control_flow.py:1174 parity: inverse of the split above,
        # restoring original row order and right-padding short rows
        arr = env[op.inputs["X"][0]]
        table = np.asarray(env[op.inputs["RankTable"][0]])
        order, lengths = table[:, 0].astype(int), table[:, 1]
        b = len(order)
        max_len = len(arr)
        feat = np.asarray(arr[0]).shape[1:] if arr else ()
        dtype = np.asarray(arr[0]).dtype if arr else np.float32
        out = np.zeros((b, max_len) + tuple(feat), dtype)
        for t_step, step_rows in enumerate(arr):
            step_rows = np.asarray(step_rows)
            active = step_rows.shape[0]
            out[order[:active], t_step] = step_rows
        env[op.outputs["Out"][0]] = jnp.asarray(out)
        return


def _run_while_block(op, env, rng_box, const_env=None):
    """Block-style While (the reference's while_op used via
    fluid.layers.While): loop state is every outer variable the body
    block assigns, plus the condition variable; iteration stops when the
    body's assign to the condition goes false."""
    program = op.block.program
    a = op.attrs
    body = program.blocks[a["body_block"]]
    cond_name = a["cond_name"]
    written = set()
    for o in body.ops:
        written.update(o.output_names())
    carry_names = sorted({cond_name} | {n for n in written if n in env})
    cond_pos = carry_names.index(cond_name)
    init = tuple(jnp.asarray(env[n]) for n in carry_names) \
        + (rng_box.next(),)

    def cond_fn(carry):
        return jnp.asarray(carry[cond_pos]).reshape(()).astype(bool)

    def body_fn(carry):
        key, sub = jax.random.split(carry[-1])
        local = _branch_env(env)
        local.update(dict(zip(carry_names, carry[:-1])))
        interpret(body.ops, local, _RngBox(sub), const_env)
        return tuple(jnp.asarray(local[n], init[i].dtype)
                     for i, n in enumerate(carry_names)) + (key,)

    max_iters = a.get("max_iters")
    if max_iters:
        # bounded lax.scan lowering so reverse-mode AD can flow through
        # the loop (same contract as the functional while_loop op)
        def scan_body(carry, _):
            run = cond_fn(carry)
            new = body_fn(carry)
            frozen = tuple(jnp.where(run, n, c)
                           for n, c in zip(new[:-1], carry[:-1]))
            return frozen + (new[-1],), None

        outs, _ = jax.lax.scan(scan_body, init, None,
                               length=int(max_iters))
    else:
        outs = jax.lax.while_loop(cond_fn, body_fn, init)
    for n, v in zip(carry_names, outs[:-1]):
        env[n] = v


# the single definition shared with the PT201 lint and the DCE pass
# (analysis/facts.py): an op type added there must survive _live_ops
# pruning too, or its side effect is silently dropped on fetch-pruned
# runs while the lint still calls it live
from ..analysis.facts import SIDE_EFFECT_TYPES as _SIDE_EFFECT_OPS

_CONTROL_FLOW_OPS = {
    "cond": _run_cond,
    "switch": _run_switch,
    "while_loop": _run_while,
    "while_block": _run_while_block,
    "static_rnn": _run_static_rnn,
    "create_array": _run_array_op,
    "array_write": _run_array_op,
    "array_read": _run_array_op,
    "array_length": _run_array_op,
    "lod_tensor_to_array": _run_array_op,
    "array_to_lod_tensor": _run_array_op,
}


def run_op(op, env, rng_box, const_env=None, scope=None):
    """Execute one recorded op against env (used at trace time).

    With `scope` ("{section}/{op_type}_{idx}", see op_scopes), the
    whole emission — control-flow sub-traces included — runs inside
    jax.named_scope(scope), so every HLO instruction this op stages
    carries its ProgramDesc identity in metadata.op_name (the
    provenance monitor.op_profile attributes device cost by).  Pure
    trace-time cost: compiled steps never re-enter here."""
    if scope is not None:
        with jax.named_scope(scope):
            return _run_op(op, env, rng_box, const_env)
    return _run_op(op, env, rng_box, const_env)


def _run_op(op, env, rng_box, const_env=None):
    if op.type in _CONTROL_FLOW_OPS:
        _CONTROL_FLOW_OPS[op.type](op, env, rng_box, const_env)
        return
    opdef = get_op(op.type)
    ins = {}
    for slot, names in op.inputs.items():
        if not names:
            continue
        ins[slot] = _resolve_slot(env, names)
    attrs = op.attrs
    if opdef.needs_rng:
        attrs = dict(attrs)
        attrs["_rng"] = rng_box.next()
    if flags.flag("executor_log_ops"):
        print(f"[paddle_tpu.executor] {op.type} {list(op.inputs)} -> {list(op.outputs)}")

    if op.type in _NEEDS_CONST_INPUTS and const_env is not None:
        const_ins = {}
        ok = True
        for slot, names in op.inputs.items():
            if not names:
                continue
            if all(n in const_env for n in names):
                vals = [const_env[n] for n in names]
                const_ins[slot] = vals[0] if len(vals) == 1 else vals
            else:
                ok = False
        if ok:
            # keep as numpy: jnp.asarray would stage a tracer under jit
            ins = {k: np.asarray(v) for k, v in const_ins.items()}
        else:
            raise NotImplementedError(
                f"op '{op.type}' has a value-dependent output shape; its "
                f"inputs must be compile-time constants under the jitted "
                f"executor (or use FLAGS_eager_executor)")
    elif op.type in _DYNAMIC_SHAPE_OPS:
        import jax.core as _core

        if any(isinstance(v, _core.Tracer)
               for v in jax.tree.leaves(ins)):
            raise NotImplementedError(
                f"op '{op.type}' has a data-dependent output shape and "
                f"cannot run under the jitted executor; set "
                f"FLAGS_eager_executor=1 for this program")

    try:
        outs = opdef.fn(ins, attrs)
    except Exception as e:
        # decorate with the op identity + creation site (the reference
        # attaches the Python stack to op errors, op_call_stack.cc)
        where = getattr(op, "callsite", None)
        note = (f"[operator '{op.type}' "
                f"(inputs {list(op.inputs)}, outputs {list(op.outputs)})"
                + (f", created at {where}" if where else "") + "]")
        if hasattr(e, "add_note"):
            e.add_note(note)
            raise
        try:
            decorated = type(e)(f"{e} {note}")
        except Exception:
            # exception classes with non-str __init__ (UnicodeDecodeError
            # etc.) can't be reconstructed from a message — re-raise as-is
            raise e
        raise decorated from e
    for slot, names in op.outputs.items():
        if slot not in outs:
            continue
        vals = outs[slot]
        if len(names) == 1 and not isinstance(vals, (list, tuple)):
            env[names[0]] = vals
        else:
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            for n, v in zip(names, vals):
                env[n] = v
    if const_env is not None and op.type in _CONST_EVAL:
        try:
            c_outs = _CONST_EVAL[op.type](ins, attrs)
            for slot, names in op.outputs.items():
                if slot in c_outs:
                    const_env[names[0]] = c_outs[slot]
        except Exception:
            pass


def interpret(ops, env, rng_box, const_env=None, scopes=None,
              allow_sampling=True, pins=None):
    """Run `ops` in order.  `scopes` maps id(op) -> scope name (built
    once per program by op_scopes); while a monitor.op_profile sampler
    is active (the eager/dygraph sampling mode), each op is wall-timed
    with block_until_ready on its outputs and recorded under its scope
    — plus a profiler span when a profiling session is on, so the
    chrome trace grows per-op rows.  allow_sampling=False marks a
    jit-STAGING caller (_make_step_fn): its per-op durations would be
    pure trace time masquerading as measurements, so the sampler is
    bypassed there even when active.

    `pins` ({var_name: NamedSharding}, GSPMD tier) constrains each
    listed var right after the op producing it — the activation-edge
    with_sharding_constraint insertion of the lowered ShardingPlan."""
    sampler = _sampler() if allow_sampling else None
    if sampler is None:
        for op in ops:
            run_op(op, env, rng_box, const_env,
                   scopes.get(id(op)) if scopes else None)
            if pins:
                _apply_pins(op, env, pins)
        return
    global _profiler
    if _profiler is None:
        from .. import profiler

        _profiler = profiler
    for op in ops:
        scope = (scopes.get(id(op)) if scopes else None) \
            or f"main/{op.type}"
        t0 = time.perf_counter_ns()
        run_op(op, env, rng_box, const_env, scope)
        if pins:
            _apply_pins(op, env, pins)
        outs = [env[n] for n in op.output_names() if n in env]
        try:
            # concrete arrays block until device-done (the honest per-op
            # time); tracers under an autodiff/jit trace have nothing to
            # block on and record host dispatch time instead
            jax.block_until_ready(outs)
        except Exception:
            pass
        t1 = time.perf_counter_ns()
        sampler.note(scope, (t1 - t0) / 1e3)
        _profiler.add_span(scope, t0, t1)


def _apply_pins(op, env, pins):
    """Constrain `op`'s just-produced outputs listed in `pins` — the
    trace-time with_sharding_constraint emission of the GSPMD tier.
    Scoped under the op's own named_scope caller, so the pin's HLO
    carries the same provenance as the op it anchors."""
    for n in op.output_names():
        s = pins.get(n)
        if s is not None and n in env:
            env[n] = jax.lax.with_sharding_constraint(env[n], s)


def op_scopes(ops, sections):
    """Deterministic per-op scope names for one live-op list:
    "{section}/{op_type}_{idx}" with idx the op's position in the list
    and section fwd<k> for ops feeding backward section k, update for
    ops after the last section (optimizer/stats), main when the
    program has no backward sections.  Derived from program structure
    alone, so names are STABLE across recompiles of the same program
    (the property the attribution tests pin)."""
    section_ends = [(bs.pos, f"fwd{k}") for k, bs in enumerate(sections)]
    tail = "update" if sections else "main"
    names = []
    for i, op in enumerate(ops):
        prefix = tail
        for pos, name in section_ends:
            if i < pos:
                prefix = name
                break
        names.append(f"{prefix}/{op.type}_{i}")
    return names


def op_scope_names(program, fetch_names=(), train_loop=False):
    """Public provenance map for one program: [(scope, op)] in
    execution order, exactly the scopes the compiled step will emit —
    what monitor.op_profile checks attribution coverage against.

    With FLAGS_graph_opt=on the executor traces the OPTIMIZED
    substitute, so the map resolves through it: fused/folded ops appear
    under their own (emitted) scopes and carry ``op.folded_from`` — the
    source ops' scope names — so attribution tools can map device time
    on a rewritten op back to what the user built instead of landing it
    in ``(unattributed)``.  ``train_loop=True`` additionally resolves
    the FLAGS_amp / FLAGS_graph_opt_fuse train tier exactly as a
    ``train_from_dataset`` dispatch would (their "train" default only
    fires on that path)."""
    if hasattr(program, "_get_executable_program"):
        program = program._get_executable_program()
    do_amp, do_fuse = Executor._train_tier_modes(program, train_loop)
    if do_amp or do_fuse:
        program = Executor._resolve_train_optimized(
            program, list(fetch_names), do_amp, do_fuse)
    if flags.flag("graph_opt") == "on":
        program = Executor._resolve_optimized(program, list(fetch_names))
    ops = Executor._live_ops(program, list(fetch_names))
    sections = [] if program._is_test else list(program.backward_sections)
    return list(zip(op_scopes(ops, sections), ops))


def _checkpoint_chunks(seg, checkpoint_names):
    """Split a forward segment at the ops producing each checkpoint var.
    Returns [(ops, remat?)]: chunks between checkpoints are wrapped in
    jax.checkpoint (recompute) — parity with the recompute_segments of
    backward.py:639."""
    if not checkpoint_names:
        return [(seg, False)]
    ckpts = set(checkpoint_names)
    boundaries = []
    for i, op in enumerate(seg):
        if set(op.output_names()) & ckpts:
            boundaries.append(i + 1)
    if not boundaries:
        return [(seg, False)]
    chunks = []
    start = 0
    for b in boundaries:
        if seg[start:b]:
            chunks.append((seg[start:b], True))
        start = b
    if seg[start:]:
        chunks.append((seg[start:], False))
    return chunks


class _RunPlan:
    """Steady-state dispatch analysis for one (program, version).

    The Fluid reference keeps its hot loop fast by doing program
    analysis once (feed/fetch-targeted pruning, executor.py:236/274);
    the per-call analogue here — the persist-name list, the
    produced/read op-name sets, and the feed-name -> dtype map — is
    computed ONCE per program mutation so a cached-hit Executor.run is
    a dict lookup plus one compiled call, with no list_vars() scan.

    The plan is stored on the Program itself (program._run_plan_cache),
    so a recycled id() of a garbage-collected program can never alias
    another program's plan; `version` pins it to the _version counter
    every graph mutation bumps (Block.append_op / create_var), and
    `program` guards against a foreign plan object being rebound onto
    a different Program instance."""

    __slots__ = ("program", "version", "persist_names", "produced",
                 "read_names", "_feed_dtypes")

    def __init__(self, program):
        self.program = program
        self.version = program._version
        self.persist_names = tuple(sorted(
            v.name for v in program.list_vars() if v.persistable))
        produced, read = set(), set()
        for op in program.global_block().ops:
            produced.update(op.output_names())
            read.update(op.input_names())
        self.produced = produced
        self.read_names = read
        self._feed_dtypes = {}

    def feed_dtype(self, name):
        """Declared jax dtype of a feed var (None when undeclared) —
        resolved through the block chain once per name, then served
        from the plan."""
        try:
            return self._feed_dtypes[name]
        except KeyError:
            v = self.program.global_block()._find_var_recursive(name)
            dt = to_jax_dtype(v.dtype) if v is not None and v.dtype else None
            self._feed_dtypes[name] = dt
            return dt


class Executor:
    """Parity: fluid.Executor (executor.py:437)."""

    def __init__(self, place=None):
        self.place = place or default_place()
        self._cache = {}
        seed = flags.flag("global_seed")
        self._root_key = jax.random.PRNGKey(seed)
        # True while scope state may hold arrays committed to devices
        # a dp mesh doesn't cover (fresh executor over a user-restored
        # scope; re-armed by checkpoint restore paths).  Gates the dp
        # re-placement scan so the steady-state dispatch path never
        # pays per-var sharding checks.
        self._check_state_placement = True
        # GSPMD runtime tier (ISSUE 16): memoized ShardingPlan per
        # (program, version, rule fingerprint, feed shapes), and a
        # placement stamp per program so the per-leaf sharded
        # device_put scan runs once per (program, mesh, rules) — the
        # steady-state dispatch pays one dict probe.
        self._spmd_plans = {}
        self._spmd_place_stamps = {}

    def close(self):
        self._cache.clear()

    @staticmethod
    def _get_plan(program, use_program_cache=True):
        """The program's run-plan: served from program._run_plan_cache
        on a (same program, same _version) hit, rebuilt otherwise.
        use_program_cache=False bypasses the cache entirely — neither
        reads nor stores it (the same contract as the compiled-fn
        cache)."""
        mon = _mon()
        if use_program_cache:
            plan = getattr(program, "_run_plan_cache", None)
            if plan is not None and plan.program is program \
                    and plan.version == program._version:
                if mon.is_enabled():
                    mon.counter("run_plan.hit").add(1)
                return plan
        if mon.is_enabled():
            mon.counter("run_plan.miss").add(1)
        plan = _RunPlan(program)
        if use_program_cache:
            program._run_plan_cache = plan
        return plan

    def _get_spmd_plan(self, program, rules, fetch_names, feed_arrays):
        """Memoized ShardingPlan for the GSPMD tier: one
        ``analysis.sharding.lower`` per (program identity, version,
        rule fingerprint, feed shapes) — a rule re-attachment or a
        feed-shape change re-lowers, the steady state pays a dict
        probe.  Entries hold the program so a recycled id() after GC
        can't serve a stale plan."""
        shapes = {n: tuple(np.shape(a)) for n, a in feed_arrays.items()
                  if not n.startswith("__fleet_")}
        key = (id(program), program._version, rules.fingerprint(),
               tuple(sorted(shapes.items())), tuple(fetch_names))
        ent = self._spmd_plans.get(key)
        if ent is not None and ent[0] is program:
            return ent[1]
        from ..analysis import sharding as _sh

        plan = _sh.lower(program, rules, fetch_names=fetch_names,
                         feed_names=sorted(shapes),
                         feed_shapes=shapes)
        if len(self._spmd_plans) >= 8:
            self._spmd_plans.clear()
        self._spmd_plans[key] = (program, plan)
        return plan

    # ------------------------------------------------------------------
    def run(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        scope=None,
        return_numpy=True,
        use_program_cache=True,
        _train_loop=False,
    ):
        # Goodput accounting (ISSUE 20): while a run ledger is active,
        # the whole dispatch body is a host_dispatch span — re-labeled
        # compile on a fresh trace, with the device-sync points inside
        # charging productive_step (innermost span wins).  With no
        # ledger (FLAGS_goodput off) this is one global read and a
        # direct call into the unchanged dispatch path.
        gled = _gp().active()
        if gled is None:
            return self._run_impl(program, feed, fetch_list, scope,
                                  return_numpy, use_program_cache,
                                  _train_loop)
        pushed = gled.push("host_dispatch")
        try:
            return self._run_impl(program, feed, fetch_list, scope,
                                  return_numpy, use_program_cache,
                                  _train_loop)
        finally:
            if pushed:
                gled.pop()

    def _run_impl(
        self,
        program=None,
        feed=None,
        fetch_list=None,
        scope=None,
        return_numpy=True,
        use_program_cache=True,
        _train_loop=False,
    ):
        program = program if program is not None else default_main_program()
        mon = _mon()
        mon_on = mon.is_enabled()
        # t0 is read unconditionally: the always-on flight recorder's
        # minimal step record wants the dispatch time too (one clock
        # read — far under the <2% fast-path budget)
        t0 = time.perf_counter_ns()
        # CompiledProgram / parallel wrapper support
        dp_mesh = None
        dp_key = None
        precision = resolve_precision(program)
        telemetry_key = getattr(program, "_telemetry_label", None)
        spmd_rules = None
        spmd_plan = None
        if hasattr(program, "_get_executable_program"):
            if getattr(program, "_is_spmd", False):
                # GSPMD runtime tier (ISSUE 16): the attached partition
                # rules EXECUTE — state placed per-leaf on the rule
                # mesh, model axes handed to XLA as auto axes, the dp
                # axis staying the manual grad-sync axis below.
                spmd_rules = program._spmd_rules
                dp_mesh = program._spmd_mesh()
                if "dp" not in dp_mesh.axis_names \
                        or spmd_rules.data_axis != "dp":
                    raise ValueError(
                        "executable sharding rules need a 'dp' data "
                        "axis on the mesh (size 1 is fine); got axes "
                        f"{dp_mesh.axis_names} with data axis "
                        f"{spmd_rules.data_axis!r}")
                # rule fingerprint + mesh device identity: re-attaching
                # rules or retargeting the mesh retraces instead of
                # serving a stale layout
                dp_key = program._spmd_key()
            elif getattr(program, "_is_data_parallel", False):
                dp_mesh = program._dp_mesh()
                # device-IDENTITY key (memoized with the mesh): an
                # elastic retarget_dp onto a same-sized different
                # device set must retrace, not reuse the dead world's
                # executable
                dp_key = program._dp_mesh_key()
            program = program._get_executable_program()
        if telemetry_key is None:
            telemetry_key = getattr(program, "_telemetry_label", None)
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope if scope is not None else _global_scope

        fetch_names = [
            f.name if isinstance(f, Variable) else str(f) for f in fetch_list
        ]

        # Performance tier (ISSUE 14): bf16 AMP rewrite + fused-kernel
        # pattern matching on a cloned substitute, in the canonical
        # order AMP rewrite -> fusion -> structural passes (the
        # graph_opt substitution below composes third).  FLAGS_amp /
        # FLAGS_graph_opt_fuse default "train": they fire for programs
        # dispatched by train_from_dataset (the zoo train path) and
        # stay out of bare Executor.run unless set to "on" — with both
        # "off", this costs two flag reads and the dispatch path is
        # byte-for-byte the pre-fusion executor.
        do_amp, do_fuse = self._train_tier_modes(program, _train_loop)
        if do_amp or do_fuse:
            tier_opt = self._resolve_train_optimized(
                program, fetch_names, do_amp, do_fuse)
            if tier_opt is not program:
                # mirror the CURRENT sharding-rule attachment (same
                # contract as the graph_opt substitution below): a
                # re-attached or removed rule set must not keep linting
                # a cached substitute against stale rules
                rules = getattr(program, "_sharding_rules", None)
                if getattr(tier_opt, "_sharding_rules", None) is not \
                        rules:
                    tier_opt._sharding_rules = rules
            program = tier_opt

        # Graph-optimizer substitution (FLAGS_graph_opt=on): trace the
        # OPTIMIZED twin of the program — CSE/const-fold/identity/DCE
        # applied by paddle_tpu.passes — cached per (version, fetches,
        # pass config) on the program, so a flag flip or a pass-config
        # change re-optimizes while the steady state pays one flag
        # read + one dict probe.  The substitute is a different object
        # with its own _version, so the run-plan and compiled-step
        # caches key on the pass config for free.
        if flags.flag("graph_opt") == "on":
            opt = self._resolve_optimized(program, fetch_names)
            if opt is not program:
                # the substitute is a clone — mirror the CURRENT
                # sharding-rule attachment (analysis metadata, not
                # graph state) so the PT3xx lints neither vanish under
                # graph_opt=on nor keep linting a cached clone against
                # rules the user has since replaced or removed
                rules = getattr(program, "_sharding_rules", None)
                if getattr(opt, "_sharding_rules", None) is not rules:
                    opt._sharding_rules = rules
            program = opt

        # Optimize-time-folded constants become initialized
        # persistables; their values live on the program — seed them
        # into the scope so both the compiled and eager paths resolve
        # them like any other persistable state.  Stamped per
        # (program, version): a re-optimized program OVERWRITES its
        # stale constants instead of first-write-wins serving them,
        # and the steady state pays one getattr compare.
        fc = getattr(program, "_folded_constants", None)
        if fc:
            # per-(program, version) seed memo on the scope, so an
            # alternating train/eval pair doesn't re-device-put its
            # constants every step.  Entries hold the PROGRAM, not its
            # id(): a recycled address after GC must not make a new
            # program's constants look already-seeded (same defense as
            # the compiled-step cache storing the program in its
            # value).
            stamps = getattr(scope, "_folded_seed_stamps", None)
            if stamps is None:
                stamps = scope._folded_seed_stamps = {}
            ent = stamps.get(id(program))
            if ent is None or ent[0] is not program \
                    or ent[1] != program._version:
                for n, v in fc.items():
                    scope.set_var(n, jnp.asarray(v))
                if len(stamps) >= 8:
                    stamps.clear()
                stamps[id(program)] = (program, program._version)

        # Static program verification (FLAGS_static_check=off|warn|error):
        # the pre-trace InferShape/def-use/donation/dp lint pass of
        # paddle_tpu.analysis.  Results are cached per (program,
        # _version, fetches, feeds, dp) — _bump() invalidates — so the
        # steady-state dispatch path pays one flag read + one dict
        # probe; "off" (the default) costs the flag read alone.
        check_mode = flags.flag("static_check")
        if check_mode and check_mode != "off":
            self._static_check(program, fetch_names, feed, dp_mesh,
                               check_mode, telemetry_key, mon, mon_on,
                               dp_ndev=(int(dp_mesh.shape["dp"])
                                        if spmd_rules is not None
                                        else None))

        res = _res()
        guard = res.active_guard()
        # the fused finite check only exists where loss/grads exist:
        # train programs with backward sections on the compiled path
        guard_on = (guard is not None and not program._is_test
                    and bool(program.backward_sections))

        with _dispatch_span("executor.run.prepare"):
            plan = self._get_plan(program, use_program_cache)

            feed_arrays = {}
            feed_casts = {}
            for name, value in feed.items():
                dtype = plan.feed_dtype(name)
                if isinstance(value, jax.Array):
                    # already on device (reader.device_prefetch path): a
                    # mismatched dtype is cast INSIDE the compiled step
                    # (feed_casts), so the prefetched buffer costs the
                    # dispatch path neither a host round-trip nor a
                    # separate per-call cast dispatch
                    if dtype is not None and value.dtype != dtype:
                        feed_casts[name] = dtype
                    feed_arrays[name] = value
                else:
                    feed_arrays[name] = jnp.asarray(np.asarray(value),
                                                    dtype=dtype)
            if spmd_rules is not None:
                # lower the rules into the executable ShardingPlan
                # (state placement, activation pins, model-collective
                # records) — memoized per (program, version, rule
                # fingerprint, feed shapes), so the steady state pays
                # one dict probe
                spmd_plan = self._get_spmd_plan(
                    program, spmd_rules, fetch_names, feed_arrays)
            if res.faultinject.is_armed():
                # fault-injection harness: counts this dispatch and may
                # hand back a NaN-tainted COPY of the feed dict (the
                # caller's arrays are never touched, so a rollback
                # replay of the same batch sees clean data)
                feed_arrays = res.faultinject.on_step_feed(feed_arrays)
                # latency/hang injection (fleet straggler smoke): the
                # stall happens BEFORE the skew probe's timestamp is
                # taken, so an injected slow rank looks exactly like a
                # real one to the barrier-wait attribution
                res.faultinject.stall_point("executor.step")

            self._root_key, run_key = jax.random.split(self._root_key)

        if flags.flag("eager_executor") or flags.flag("check_nan_inf"):
            # the debug path must execute at the SAME precision as the
            # compiled step it stands in for, or the numerics being
            # hunted (e.g. a NaN under check_nan_inf) need not reproduce.
            # It interprets op-by-op, so feed casts happen up front.
            if feed_casts:
                feed_arrays = {
                    n: (a.astype(feed_casts[n]) if n in feed_casts else a)
                    for n, a in feed_arrays.items()}
            out = apply_precision_policy(
                lambda: self._run_eager(program, feed_arrays, fetch_names,
                                        scope, run_key, return_numpy),
                precision)()
            step_rec = None
            if mon_on:
                # the debug interpreter EXECUTES inline — elapsed time
                # here is execution, not dispatch, so no
                # host_dispatch_us is recorded (it would contaminate
                # the dispatch aggregates ~1000x)
                step_rec = self._record_step_metrics(mon, None,
                                                     feed_arrays, out)
            fr = _fr()
            if fr.enabled:
                fr.note_step(step_rec)
            return out

        with _dispatch_span("executor.run.state"):
            state = {}
            missing = []
            for n in plan.persist_names:
                val = scope.find_var(n)
                if val is None:
                    missing.append(n)
                else:
                    state[n] = val
            # Vars never written before and not produced by this program
            # are an error only if some op reads them; let interpretation
            # raise lazily.
            state_names = tuple(sorted(state))
            for n in missing:
                if n not in plan.produced and n in plan.read_names:
                    raise RuntimeError(
                        f"persistable variable '{n}' is uninitialized; run "
                        f"the startup program first"
                    )

            if spmd_plan is not None:
                # per-leaf SHARDED placement (the tentpole's HBM win):
                # params and the donated optimizer state go onto the
                # rule mesh under their lowered NamedSharding — an
                # mp-sharded leaf's per-shard bytes shrink by ~1/mp.
                # Scanned once per (program, mesh identity, rule
                # fingerprint) via the placement stamp, re-armed by the
                # restore paths through _check_state_placement.
                stamp = self._spmd_place_stamps.get(id(program))
                if (self._check_state_placement or stamp is None
                        or stamp[0] is not program
                        or stamp[1] != dp_key):
                    from jax.sharding import (NamedSharding,
                                              PartitionSpec as _P)

                    for n, v in state.items():
                        spec = spmd_plan.state_specs.get(n)
                        sh = NamedSharding(
                            dp_mesh,
                            spec.to_jax() if spec is not None else _P())
                        if getattr(v, "sharding", None) != sh:
                            state[n] = jax.device_put(v, sh)
                    if len(self._spmd_place_stamps) >= 8:
                        self._spmd_place_stamps.clear()
                    self._spmd_place_stamps[id(program)] = (program,
                                                            dp_key)
                    self._check_state_placement = False
            elif dp_mesh is not None and self._check_state_placement:
                # a checkpoint restore (auto_resume / guard rollback
                # into a cold scope) hands back arrays COMMITTED to the
                # template's devices; shard_map refuses committed
                # arrays that don't cover the mesh, so re-place them
                # replicated.  The scan runs only while the placement
                # flag is armed (executor construction + restore
                # paths): steady-state dispatch pays nothing for it.
                from jax.sharding import (NamedSharding,
                                          PartitionSpec as _P)

                mesh_devs = set(dp_mesh.devices.flat)
                rep = None
                for n, v in state.items():
                    devs = getattr(getattr(v, "sharding", None),
                                   "device_set", None)
                    if devs is not None and devs != mesh_devs:
                        if rep is None:
                            rep = NamedSharding(dp_mesh, _P())
                        state[n] = jax.device_put(v, rep)
                self._check_state_placement = False

            if dp_mesh is not None:
                # feeds split over the DATA axis only: the full device
                # count for pure dp, the dp-axis extent on a {dp,mp}
                # rule mesh (mp shards see the whole local batch)
                ndev = (int(dp_mesh.shape["dp"])
                        if spmd_rules is not None
                        else dp_mesh.devices.size)
                for n, a in feed_arrays.items():
                    if a.ndim == 0 or a.shape[0] % ndev != 0:
                        raise ValueError(
                            f"data-parallel feed '{n}' needs a leading "
                            f"batch dim divisible by {ndev} devices, got "
                            f"{a.shape}")

            # Fleet skew probe (ISSUE 10): dp programs carry this
            # rank's host pre-sync timestamp on device as two reserved
            # int32 feeds; the compiled step turns them into a
            # replicated per-shard barrier-wait vector returned as one
            # extra (popped) fetch.  Constant shape/dtype, so the
            # compiled-step cache key and memoized shard_map signature
            # stay stable across steps.
            fleet_on = (dp_mesh is not None
                        and flags.flag("fleet_skew"))
            if fleet_on:
                feed_arrays = _fleet().add_timestamp_feeds(feed_arrays,
                                                           dp_mesh)

            if spmd_plan is not None:
                # jax.lax.axis_index on a manual axis lowers to a
                # PartitionId op, which XLA's SPMD partitioner rejects
                # in partial-manual (auto mp) modules — so the per-dp-
                # shard rng fold happens HERE on the host, and the
                # [dp, 2] key stack ships sharded over dp instead of
                # being folded inside the body
                run_key = jax.vmap(
                    lambda i, k=run_key: jax.random.fold_in(k, i))(
                    jnp.arange(int(dp_mesh.shape["dp"]),
                               dtype=jnp.uint32))

            feed_sig = tuple(
                (n, feed_arrays[n].shape, str(feed_arrays[n].dtype))
                for n in sorted(feed_arrays)
            )

            key = (id(program), plan.version, feed_sig, tuple(fetch_names),
                   state_names,
                   (dp_key or dp_mesh.shape_tuple)
                   if dp_mesh is not None else None,
                   precision, guard_on,
                   # the grad-sync bucket capacity is read at TRACE
                   # time (transpiler.collective.sync_gradients), so a
                   # flag change must retrace dp steps — key on it for
                   # dp programs only (non-dp traces never read it)
                   None if dp_mesh is None
                   else int(flags.flag("dp_bucket_bytes")))
            # cache value holds the program so id() can't be recycled by a
            # new Program allocated at the same address after GC
            entry = self._cache.get(key) if use_program_cache else None
        fresh_compile = entry is None or entry[1] is not program
        gled = _gp().active()
        if gled is not None and fresh_compile:
            # jit compiles on FIRST INVOCATION, so trace + XLA compile
            # both happen between here and the end of the dispatch
            # block: re-label the enclosing host_dispatch span until
            # then (time already charged stays host_dispatch — the
            # plan/feed prep above really was dispatch work)
            gled.retag("compile")
        if fresh_compile:
            if mon_on:
                mon.counter("compiled_step.miss").add(1)
            else:
                # with telemetry on, the compile ledger mirrors its
                # (fully analyzed) event into the recorder; off, this
                # marker still timestamps the recompile in a post-mortem
                fr = _fr()
                if fr.enabled:
                    fr.note_compile_marker(
                        telemetry_key or "prog%x" % id(program))
            try:
                with _dispatch_span("executor.run.trace"):
                    compiled = self._build(program, fetch_names,
                                           plan.persist_names,
                                           dp_mesh=dp_mesh,
                                           precision=precision,
                                           feed_casts=feed_casts,
                                           telemetry_key=telemetry_key,
                                           guard_on=guard_on,
                                           spmd_plan=spmd_plan)
            except Exception as e:
                # a program too big to even COMPILE dies with the same
                # RESOURCE_EXHAUSTED shape an execution OOM does
                self._oom_postmortem(e, mon_on)
                raise
            if use_program_cache:
                self._cache[key] = (compiled, program)
        else:
            if mon_on:
                mon.counter("compiled_step.hit").add(1)
            compiled = entry[0]

        try:
            with _dispatch_span("executor.run.dispatch"):
                retry_policy = res.active_retry()

                def _dispatch():
                    # an injected transient error fires here, INSIDE
                    # the retried region, so backoff + re-dispatch is
                    # the real recovery path under test
                    if res.faultinject.is_armed():
                        res.faultinject.check_transient()
                    out = compiled(state, feed_arrays, run_key)
                    if retry_policy is not None:
                        # async dispatch defers real XLA/PJRT failures
                        # to the next sync point — which would sit
                        # OUTSIDE this retried region.  With retry on,
                        # block here so a transient execution error
                        # surfaces where backoff can catch it: fault
                        # tolerance trades the steps-ahead pipeline
                        # for retryability.  The wait IS the step's
                        # device execution — goodput's productive time.
                        with _gspan("productive_step"):
                            jax.block_until_ready(out)
                    return out

                # async dispatch (retry off): this returns device
                # futures without a sync, and the donated `state`
                # buffers are rebound to the NEW device arrays — never
                # via a host copy, which would both block and
                # resurrect freed donated buffers as host memory
                if retry_policy is not None:
                    new_state, fetches = res.call_with_retry(
                        _dispatch, retry_policy)
                else:
                    new_state, fetches = _dispatch()
                for n, v in new_state.items():
                    scope.set_var(n, v)
        except Exception as e:
            # RESOURCE_EXHAUSTED is a taxonomy dump trigger: write the
            # peak-HBM post-mortem (peak table, live-bytes timeline,
            # requested-vs-device bytes, last-K steps) BEFORE the
            # error propagates — a run that died of OOM must explain
            # what was resident.  (With retry enabled an OOM is
            # retried first; only the error that finally escapes —
            # RetriesExhausted chains it — lands here.)
            self._oom_postmortem(e, mon_on)
            raise
        if gled is not None and fresh_compile:
            # compile is done (first invocation returned): the rest of
            # this run is ordinary dispatch bookkeeping again
            gled.retag("host_dispatch")
        if spmd_plan is not None:
            # record the model-axis collectives XLA inserted from the
            # auto-axis constraints: the plan's OWN implied records, so
            # last_sync_stats()["model"] equals the analyzer's table by
            # construction (the mp half of the conformance loop)
            from ..transpiler import collective as _coll

            _coll.note_model_sync(spmd_plan.model_sync_records(),
                                  key=telemetry_key)
        skew_fetch = None
        if fleet_on:
            # the skew probe's replicated wait vector rides back as the
            # very last fetch (after the guard flag); popped here,
            # handed to the fleet ring WITHOUT materializing — the
            # async dispatch pipeline never syncs on a diagnostic
            skew_fetch = fetches[-1]
            fetches = fetches[:-1]
        guard_flag = None
        if guard_on:
            # the fused all-finite flag rides back as the LAST fetch;
            # popped before metrics so fetch-byte accounting and the
            # caller's fetch list never see it
            guard_flag = fetches[-1]
            fetches = fetches[:-1]
        step_rec = None
        if mon_on:
            # recorded BEFORE any materialization so host_dispatch_us is
            # the pure dispatch cost; fetch bytes read from the device
            # array metadata (no sync).  A step that paid trace+compile
            # is tagged warmup so it can't skew the steady-state
            # aggregates (mean step time / dispatch μs / MFU).
            step_rec = self._record_step_metrics(mon, t0, feed_arrays,
                                                 fetches,
                                                 warmup=fresh_compile)
        fr = _fr()
        if fr.enabled:
            # always-on: with telemetry enabled the ring shares the
            # session's record; without it, a minimal record (one dict
            # + deque append) keeps the post-mortem window alive
            fr.note_step(step_rec,
                         host_dispatch_us=(time.perf_counter_ns() - t0)
                         / 1e3,
                         warmup=fresh_compile)
        if skew_fetch is not None:
            _fleet().note_sync(skew_fetch, step_record=step_rec,
                               mesh=dp_mesh, key=telemetry_key)
        if guard_flag is not None:
            # ONE host sync per guarded step (the policy decision needs
            # the scalar): the price of the guard, paid only when it is
            # enabled.  State selection already happened on device — an
            # anomalous step committed nothing.
            self._apply_guard_policy(res, guard, guard_flag, plan, scope)
        if return_numpy:
            with _dispatch_span("executor.run.fetch"):
                try:
                    # the one sync point of the synchronous path: the
                    # block covers the step's device execution
                    with _gspan("productive_step"):
                        return _materialize(fetches)
                except Exception as e:
                    # async dispatch (retry off) defers execution
                    # failures to this sync point — an OOM surfacing
                    # here still gets its post-mortem
                    self._oom_postmortem(e, mon_on)
                    raise
        # a fetch naming a persistable var ALIASES the buffer just bound
        # into the scope; the NEXT run donates that buffer, which would
        # invalidate a still-held device fetch.  A device-side copy (no
        # sync) decouples it — donation stays sound across the no-sync
        # steady state.
        return [jnp.copy(f) if n in new_state else f
                for n, f in zip(fetch_names, fetches)]

    @staticmethod
    def _train_tier_modes(program, train_loop):
        """(do_amp, do_fuse) for one dispatch: the ISSUE-14 performance
        tier applies only to TRAIN programs (backward sections, not a
        test clone); "train" mode further requires the dataset train
        loop (train_from_dataset), "on" covers every Executor.run.
        AMP is additionally skipped for programs the user already
        rewrote (amp_enabled)."""
        if program._is_test or not program.backward_sections:
            return False, False
        amp_mode = flags.flag("amp")
        fuse_mode = flags.flag("graph_opt_fuse")
        do_amp = (amp_mode == "on"
                  or (amp_mode == "train" and train_loop)) \
            and not program.amp_enabled
        do_fuse = (fuse_mode == "on"
                   or (fuse_mode == "train" and train_loop))
        return do_amp, do_fuse

    @staticmethod
    def _resolve_train_optimized(program, fetch_names, do_amp, do_fuse):
        """The AMP+fusion substitute for a train program — built once
        per (version, fetch set, amp dtype, fusion config) and cached
        in the same on-program ``_opt_cache`` the structural substitute
        uses (``_bump()`` clears it), so the steady-state dispatch path
        pays two flag reads and a dict probe.  Canonical order inside:
        AMP rewrite first, fusion second; the FLAGS_graph_opt
        structural tier (if on) then composes on the RESULT."""
        from .. import passes as _passes

        try:
            fuse_names = (_passes.enabled_fusion_passes()
                          if do_fuse else ())
        except KeyError as e:
            raise ValueError(
                f"FLAGS_graph_opt_fuse_disable names an unknown "
                f"fusion pass: {e}") from e
        key = ("train_tier", program._version, tuple(fetch_names),
               flags.flag("amp_dtype") if do_amp else None, fuse_names)
        cache = getattr(program, "_opt_cache", None)
        if cache:
            hit = cache.get(key)
            if hit is not None:
                return hit
        label = getattr(program, "_telemetry_label", None)
        pkey = label or "prog%x:v%d" % (id(program), program._version)
        opt = program.clone()
        if do_amp:
            from .. import amp as _amp

            _amp.rewrite_train_program(opt)
        if do_fuse:
            _passes.fuse_program(opt, fetch_names=fetch_names,
                                 clone=False, program_key=pkey)
        opt._telemetry_label = label
        # provenance for the PT4xx numerics lint and post-mortems:
        # WHICH train-tier config produced this substitute (the lint
        # runs against it — _static_check fires after this
        # substitution — and a cached substitute outlives the flag
        # state that built it)
        opt._train_tier = {
            "amp": flags.flag("amp_dtype") if do_amp else None,
            "fuse": list(fuse_names)}
        if cache is None:
            cache = program._opt_cache = {}
        elif len(cache) >= 8:
            cache.clear()
        cache[key] = opt
        return opt

    @staticmethod
    def _resolve_optimized(program, fetch_names):
        """The optimized substitute for `program` under the current
        pass config — built once per (program version, fetch set, pass
        config) and cached on the program (``_opt_cache``; ``_bump()``
        clears it, so a mutation can never serve a stale substitute).
        Value-based folds are NOT applied here: executor-run programs
        own mutable parameters, so only the structural passes are
        legal."""
        from .. import passes as _passes

        try:
            names = _passes.enabled_passes()
            # the fusion tier composes with this pipeline when
            # explicitly global — fusion FIRST (canonical order),
            # structural cleanup after.  Programs the train tier
            # already fused skip it (idempotent, but a re-scan per
            # substitute build is pure waste and its report would be
            # all-zero noise).
            fuse_names = (
                _passes.enabled_fusion_passes()
                if flags.flag("graph_opt_fuse") == "on"
                and not getattr(program, "_fusion_applied", False)
                else ())
        except KeyError as e:
            raise ValueError(
                f"FLAGS_graph_opt_disable / "
                f"FLAGS_graph_opt_fuse_disable names an unknown pass: "
                f"{e}") from e
        key = (program._version, tuple(fetch_names), names, fuse_names)
        cache = getattr(program, "_opt_cache", None)
        if cache:
            hit = cache.get(key)
            if hit is not None:
                return hit
        label = getattr(program, "_telemetry_label", None)
        pkey = label or "prog%x:v%d" % (id(program), program._version)
        src = program
        if fuse_names:
            # a separate, tier-tagged fuse_program run (not fuse_*
            # names folded into optimize_program): the telemetry
            # Fusion section keys on tier="fusion", and the structural
            # section must not absorb pattern rows
            src, _freport = _passes.fuse_program(
                program, fetch_names=fetch_names, program_key=pkey)
        opt, _report = _passes.optimize_program(
            src, fetch_names=fetch_names, passes=names,
            program_key=pkey,
            # fuse_program already cloned; don't deep-copy twice
            clone=src is program)
        opt._telemetry_label = label
        if cache is None:
            cache = program._opt_cache = {}
        elif len(cache) >= 4:
            cache.clear()
        cache[key] = opt
        return opt

    @staticmethod
    def _static_check(program, fetch_names, feed, dp_mesh, mode,
                      telemetry_key, mon, mon_on, dp_ndev=None):
        """Run the static verifier before tracing (the reference's
        build-time InferShape parity point).  A fresh analysis emits
        ONE ProgramLintWarning (warn mode), a kind="lint" telemetry
        record, and a flight-recorder event; a cache hit re-raises in
        error mode but never re-reports — a long training loop lints
        each program version exactly once."""
        from .. import analysis

        key = telemetry_key or "prog%x:v%d" % (id(program),
                                               program._version)
        result, fresh = analysis.cached_check(
            program, fetch_names=fetch_names,
            feed_names=list(feed or ()),
            dp_ndev=(dp_ndev if dp_ndev is not None
                     else None if dp_mesh is None
                     else int(dp_mesh.devices.size)),
            program_key=key)
        if fresh:
            if mon_on:
                mon.record_lint(result.to_record())
            fr = _fr()
            if fr.enabled and result.diagnostics:
                # the full kind="lint" record for post-mortem dumps
                # plus a recovery-style event marking WHEN it happened
                fr.note_lint(result.to_record())
                fr.note_event("lint", key=key,
                              errors=len(result.errors),
                              warnings=len(result.warnings),
                              codes=result.by_code())
            if result.diagnostics and (mode != "error" or result.ok):
                analysis.warn_result(result, stacklevel=4)
        if mode == "error" and not result.ok:
            raise analysis.ProgramLintError(result)

    @staticmethod
    def _oom_postmortem(exc, mon_on):
        """OOM dump trigger (resilience.taxonomy.is_oom): count the
        event and have the flight recorder write the peak-HBM
        post-mortem before the caller re-raises.  Never raises itself
        — forensics must not mask the real error."""
        try:
            if not _res().is_oom(exc):
                return
            if mon_on:
                _mon().counter("resilience.oom_events").add(1)
            fr = _fr()
            if fr.enabled:
                fr.dump_oom(exc)
        except Exception:
            pass

    @staticmethod
    def _record_step_metrics(mon, t0, feed_arrays, fetches,
                             warmup=False):
        """One telemetry step record per Executor.run: host-dispatch μs
        (entry to here; t0=None skips it — the eager debug path has no
        dispatch phase), examples (leading feed dim), feed/fetch bytes.
        Wall step time is derived by the session from the gap between
        consecutive records; warmup=True marks a run that paid
        trace+compile (excluded from steady-state means).  Returns the
        session record so the flight recorder can share it (one dict
        in both rings, no duplicate bookkeeping)."""
        examples = 0
        feed_bytes = 0
        for n, a in feed_arrays.items():
            if n.startswith("__fleet_"):
                # the skew probe's timestamp feeds are diagnostics, not
                # workload — byte/example accounting must not see them
                continue
            feed_bytes += int(getattr(a, "nbytes", 0) or 0)
            shape = getattr(a, "shape", ())
            if shape:
                examples = max(examples, int(shape[0]))
        fetch_bytes = sum(int(getattr(f, "nbytes", 0) or 0)
                          for f in fetches)
        return mon.record_step(
            host_dispatch_us=(None if t0 is None
                              else (time.perf_counter_ns() - t0) / 1e3),
            examples=examples or None, feed_bytes=feed_bytes,
            fetch_bytes=fetch_bytes, warmup=warmup)

    def _apply_guard_policy(self, res, guard, guard_flag, plan, scope):
        """Host side of the anomaly guard: read the fused finite flag
        (a float — 1.0 when every section's loss/grads were finite on
        every dp shard) and apply the active policy.

        skip_step needs no state action (the compiled step selected the
        old state on device); rollback restores the newest complete
        checkpoint into the scope and raises RollbackPerformed so the
        training loop rewinds its data cursor."""
        # the flag materialization is where the guarded step's device
        # execution is awaited: productive time — unless the policy
        # decides below that the step was wasted
        with _gspan("productive_step") as gs:
            ok = float(np.asarray(guard_flag)) >= 1.0
        if ok:
            guard.note_ok()
            return
        mon = _mon()
        if mon.is_enabled():
            mon.counter("resilience.anomaly_steps").add(1)
        fr = _fr()
        if fr.enabled:
            fr.note_event("anomaly", policy=guard.policy)
        guard.note_anomaly()         # escalates past max_consecutive
        guard.last_skipped = False
        if guard.policy == "raise":
            raise res.AnomalyError(
                "anomaly guard: non-finite loss/gradients in guarded "
                "step (policy=raise)")
        if guard.policy == "skip_step":
            guard.last_skipped = True
            if mon.is_enabled():
                mon.counter("resilience.skipped_steps").add(1)
            gled = _gp().active()
            if gled is not None:
                # the step committed nothing: the execution wait just
                # charged as productive was really recovery (sum-
                # preserving move of exactly the span's own ns)
                gled.reclassify("productive_step", "recovery",
                                getattr(gs, "ns", 0))
            return
        # rollback: restore newest complete checkpoint into the scope
        guard.note_rollback()        # escalates past max_rollbacks
        template = {}
        for n in plan.persist_names:
            v = scope.find_var(n)
            if v is not None:
                template[n] = v
        with _dispatch_span("resilience.rollback_restore"), \
                _gspan("recovery"):
            try:
                state, ck_step = guard.manager.restore_latest(template)
            except FileNotFoundError as e:
                # no complete checkpoint yet: the on-device select
                # already kept the params clean, but there is nothing
                # to roll back TO — escalate with the real story
                # instead of a bare IO error
                raise res.AnomalyError(
                    "rollback policy hit an anomaly before any complete "
                    "checkpoint existed; save one up front (train_from_"
                    "dataset does this automatically) or use "
                    "policy='skip_step'") from e
        for n, v in state.items():
            scope.set_var(n, v)
        # restored arrays may be committed off-mesh: the next dp
        # dispatch re-places them
        self._check_state_placement = True
        # checkpoints written by train_from_dataset carry the executor
        # PRNG root key: restoring it rewinds the rng STREAM along with
        # the params, so a replay of a stochastic (dropout) program is
        # bitwise-identical to the uninterrupted run
        loader = getattr(guard.manager, "load_extras", None)
        extras = loader(ck_step) if loader is not None else {}
        if "executor_rng_key" in extras:
            self._root_key = jnp.asarray(extras["executor_rng_key"])
        if mon.is_enabled():
            mon.counter("resilience.rollbacks").add(1)
        if fr.enabled:
            fr.note_event("rollback", checkpoint_step=ck_step)
        raise res.RollbackPerformed(ck_step)

    # ------------------------------------------------------------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           sparse_config=None, _sparse_push=True,
                           prefetch=None, checkpoint=None,
                           auto_resume=False, elastic=None):
        """Dataset-driven training loop — the industrial CTR path.

        Parity: /root/reference/python/paddle/fluid/executor.py:1187
        (train_from_dataset -> _run_from_dataset -> MultiTrainer /
        HogwildWorker::TrainFiles, hogwild_worker.cc:237). The reference
        spawns N DeviceWorker threads each draining a DataFeed; here the
        native MultiSlot reader threads (csrc/data_feed.cpp) keep the
        input queue full while ONE jitted program consumes batches — on
        TPU the parallelism belongs inside the compiled step, not in
        host worker threads.

        sparse_config enables the Downpour/PS flow
        (DistMultiTrainer + DownpourWorker::TrainFiles parity —
        device_worker.h:203): {"table": SparseEmbedding-or-Communicator,
        "ids_var": slot name with ids, "emb_var": data var fed with
        pulled rows, "lr": optional} — pull before each step, push the
        embedding gradient after (the program must mark emb_var in
        append_backward's parameter_list so its @GRAD is addressable).

        prefetch: overlap batch N+1's host work (dataset iteration +
        sparse embedding pull over TCP) with batch N's device step on a
        producer thread — the reference's buffered_reader double-buffer
        (operators/reader/buffered_reader.cc) + Communicator send-overlap.
        Default (None) enables it for dense programs and for tables
        behind async/half_async/geo Communicators, where one-step-stale
        pulls are already the semantics; plain sync tables keep the
        strict pull->step->push order.

        checkpoint: fault-tolerance cadence (fleet_util save-model
        parity) — a checkpoint.CheckpointManager, a directory path, or
        a kwargs dict for CheckpointManager.  The loop saves the
        program's persistable vars every save_interval_steps, force-
        saves at the next step boundary when a preemption was requested
        (resilience.PreemptionHandler / request_preemption) and exits
        cleanly, and — when the active anomaly guard's policy is
        ``rollback`` — keeps the prepared batches since the last save
        so a rollback can replay the data cursor in place.

        auto_resume: restore the newest complete checkpoint before
        training and skip the already-consumed batches, so a re-launch
        of the SAME command continues the run (trainer-restart parity).

        elastic: an resilience.ElasticCoordinator (ISSUE 11) — its
        step_boundary hook runs before every dispatch: heartbeat +
        bounded peer sync + leave/join intents + the skew policy.  A
        topology event force-saves at THIS boundary and raises
        TopologyChanged (action "reshard_local"/"relaunch") so the
        caller rebuilds on the new world and resumes from the shared
        checkpoint; a drain (SIGUSR1) or preemption under the
        coordinator additionally posts a leave intent so survivors
        shrink without waiting out the dead-peer timeout.  The
        coordinator's manager doubles as checkpoint= when none is
        passed.

        Returns the list of final-batch fetch values (or None, like the
        reference, when fetch_list is empty).
        """
        # Goodput ledger lifecycle (ISSUE 20): one ledger per run while
        # FLAGS_goodput is on (start_run returns None otherwise, and
        # also when an enclosing run already owns the wall clock).  The
        # kind="goodput" record is emitted on EVERY exit — a run that
        # died still reports where its wall time went.
        gp = _gp()
        gled = gp.start_run(
            key=getattr(program, "_telemetry_label", None)
            or "train_from_dataset")
        if gled is None:
            return self._train_from_dataset_impl(
                program=program, dataset=dataset, scope=scope,
                thread=thread, debug=debug, fetch_list=fetch_list,
                fetch_info=fetch_info, print_period=print_period,
                sparse_config=sparse_config, _sparse_push=_sparse_push,
                prefetch=prefetch, checkpoint=checkpoint,
                auto_resume=auto_resume, elastic=elastic)
        outcome = "error"
        try:
            out = self._train_from_dataset_impl(
                program=program, dataset=dataset, scope=scope,
                thread=thread, debug=debug, fetch_list=fetch_list,
                fetch_info=fetch_info, print_period=print_period,
                sparse_config=sparse_config, _sparse_push=_sparse_push,
                prefetch=prefetch, checkpoint=checkpoint,
                auto_resume=auto_resume, elastic=elastic)
            outcome = "ok"
            return out
        finally:
            # the dp barrier wait the skew probe measured hid inside
            # the productive sync points: move it to its own bucket
            # (sum-preserving) before the record is built
            gled.fold_dp_sync(_fleet().fleet_skew())
            gp.finish_run(gled, extra={"outcome": outcome})

    def _train_from_dataset_impl(self, program=None, dataset=None,
                                 scope=None, thread=0, debug=False,
                                 fetch_list=None, fetch_info=None,
                                 print_period=100, sparse_config=None,
                                 _sparse_push=True, prefetch=None,
                                 checkpoint=None, auto_resume=False,
                                 elastic=None):
        program = program if program is not None else default_main_program()
        real_prog = program
        if hasattr(real_prog, "_get_executable_program"):
            real_prog = real_prog._get_executable_program()
        if dataset is None:
            raise ValueError("train_from_dataset needs a dataset")
        fetch_list = list(fetch_list or [])
        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]
        fetch_info = list(fetch_info or fetch_names)
        blk = real_prog.global_block()

        # -- fault-tolerance plumbing ----------------------------------
        res = _res()
        mon = _mon()
        # live /metrics exporter (ISSUE 10): session-entry hook, never
        # per step — a no-op unless FLAGS_metrics_port says otherwise
        from ..monitor import exporter as _exporter

        _exporter.ensure_started()
        mgr = checkpoint
        if mgr is not None and not hasattr(mgr, "restore_latest"):
            from ..checkpoint import CheckpointManager

            if isinstance(mgr, str):
                mgr = CheckpointManager(mgr)
            elif isinstance(mgr, dict):
                mgr = CheckpointManager(**mgr)
            else:
                raise TypeError(
                    f"checkpoint= wants a CheckpointManager, path, or "
                    f"kwargs dict, got {type(checkpoint).__name__}")
        if elastic is not None:
            # the coordinator's manager IS the fleet's shared store:
            # the force-saves its transitions take and the loop's
            # interval saves must land in one place, or the shrink
            # path resumes from the wrong history
            if mgr is None:
                mgr = elastic.manager
            elif mgr is not elastic.manager:
                raise ValueError(
                    "checkpoint= and the elastic coordinator's manager "
                    "are different CheckpointManagers; pass the same "
                    "one so topology transitions and interval saves "
                    "share a store")
        ckpt_scope = scope if scope is not None else _global_scope
        persist_names = sorted(v.name for v in real_prog.list_vars()
                               if v.persistable)

        def _ckpt_state():
            return {n: ckpt_scope.find_var(n) for n in persist_names
                    if ckpt_scope.find_var(n) is not None}

        def _ckpt_extras():
            return {"executor_rng_key": np.asarray(self._root_key)}

        guard = res.active_guard()
        # rollback/replay only exists for TRAIN programs: an eval drain
        # (infer_from_dataset, clone(for_test=True)) is never guarded
        # (no backward sections), and adopting the guard's manager for
        # it would interval-save EVAL vars into the TRAINING store —
        # _gc would then rotate out real restore points
        is_train_prog = (not real_prog._is_test
                         and bool(real_prog.backward_sections))
        keep_replay = (guard is not None and guard.policy == "rollback"
                       and is_train_prog)
        if keep_replay:
            # the guard restores through ITS manager; the loop's saves
            # and replay numbering must point at the same store or a
            # RollbackPerformed.step means nothing here
            if mgr is None:
                mgr = guard.manager
            elif mgr is not guard.manager:
                raise ValueError(
                    "checkpoint= and the rollback guard's manager are "
                    "different CheckpointManagers; pass the same one so "
                    "rollback steps line up with the loop's saves")

        if auto_resume and mgr is None:
            raise ValueError(
                "auto_resume=True needs a checkpoint store (pass "
                "checkpoint=...); silently retraining from step 0 "
                "would re-consume data")
        start_step = 0
        if mgr is not None and auto_resume:
            template = _ckpt_state()
            if template:
                try:
                    restored, start_step = mgr.restore_latest(template)
                except FileNotFoundError:
                    start_step = 0      # cold start: nothing to resume
                else:
                    for n, v in restored.items():
                        ckpt_scope.set_var(n, v)
                    self._check_state_placement = True
                    extras = mgr.load_extras(start_step)
                    if "executor_rng_key" in extras:
                        # resume the rng STREAM, not just the params —
                        # dropout continues exactly where the
                        # interrupted run left off
                        self._root_key = jnp.asarray(
                            extras["executor_rng_key"])
                    if mon.is_enabled():
                        mon.counter("resilience.auto_resume").add(1)
                        mon.counter("resilience.batches_skipped").add(
                            start_step)
        if start_step:
            import itertools

            # skip already-consumed RAW batches (before prepare(): no
            # wasted sparse pulls), preserving the data cursor of the
            # interrupted run
            dataset = itertools.islice(iter(dataset), start_step, None)

        # sparse_config: one entry dict, a list of them, or (when None)
        # whatever the DistributeTranspiler attached to the program
        sp = sparse_config
        if sp is None:
            sp = getattr(program, "_ps_sparse_config", None) \
                or getattr(real_prog, "_ps_sparse_config", None)
        entries = list(sp) if isinstance(sp, (list, tuple)) \
            else ([sp] if sp else [])
        # tolerate partial/dense configs: no table -> dense path
        entries = [e for e in entries if e and e.get("table") is not None]
        if keep_replay and entries and _sparse_push:
            raise ValueError(
                "anomaly-guard rollback cannot be combined with sparse "
                "gradient push: pushed rows can't be unwound by a "
                "checkpoint restore (use policy='skip_step' or drop the "
                "sparse tables)")
        for e in entries:
            # Communicator wraps a table: pull reads through, push goes
            # via the communicator's mode (sync/async/half_async/geo)
            e["_pull"] = getattr(e["table"], "table", e["table"])
            e["_grad"] = e["emb_var"] + "@GRAD"

        if prefetch is None:
            # auto: overlap only where concurrent pull/push is already
            # the table's contract — async/half_async Communicators push
            # from their own background thread (locked shards). geo
            # flushes on the CALLING thread, and plain SparseEmbedding
            # is strictly synchronous: both stay un-overlapped.
            # Read-only draining (infer_from_dataset) never pushes, so
            # it has no ordering constraint at all.
            def _is_async(e):
                mode = getattr(e["table"], "mode", None)
                return mode in ("async", "half_async")

            prefetch = (not _sparse_push
                        or all(_is_async(e) for e in entries))

        def prepare(batch):
            # latency injection for the input pipeline (the goodput
            # chaos bench stalls batch preparation here): armed-gated,
            # so the unarmed path pays one None check
            if res.faultinject.is_armed():
                res.faultinject.stall_point("reader.prepare")
            feed = {k: v for k, v in batch.items()
                    if blk._find_var_recursive(k) is not None}
            fl = list(fetch_names)
            batch_ids = {}
            for e in entries:
                ids = np.asarray(batch[e["ids_var"]])
                batch_ids[e["emb_var"]] = ids
                feed[e["emb_var"]] = e["_pull"].pull(ids)
                if _sparse_push:
                    fl.append(e["_grad"])
            return feed, fl, batch_ids

        if prefetch:
            # producer thread keeps one prepared batch in flight: batch
            # N+1's iteration + embedding pull overlap batch N's step
            import queue as _queue
            import threading as _threading

            q = _queue.Queue(maxsize=2)
            stop = _threading.Event()
            _END, _ERR = object(), object()

            def _offer(item):
                # bounded put that gives up when the consumer is gone,
                # so a raising train loop can't strand this thread
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.1)
                        return True
                    except _queue.Full:
                        continue
                return False

            def produce():
                try:
                    for b in dataset:
                        if not _offer(prepare(b)):
                            return
                    _offer(_END)
                except BaseException as exc:   # propagate to consumer
                    _offer((_ERR, exc))

            t = _threading.Thread(target=produce, daemon=True)
            t.start()

            def _host_batches():
                try:
                    while True:
                        item = q.get()
                        if item is _END:
                            return
                        if isinstance(item, tuple) and item[0] is _ERR:
                            raise item[1]
                        yield item
                finally:
                    stop.set()        # unblock + retire the producer

            def prepared_batches():
                gen = _host_batches()
                if not entries and not keep_replay and \
                        not getattr(program, "_is_data_parallel", False):
                    # dense single-device path: double-buffered DEVICE
                    # prefetch on top of the host producer thread — feed
                    # arrays are device_put while the previous step runs
                    # (buffered_reader.cc's device double buffer).  The
                    # sparse path keeps host batches: ids must stay host
                    # arrays for the gradient push, and its overlap win
                    # (the TCP pull) already lives on the producer
                    # thread.  The data-parallel path also keeps host
                    # batches: device_put would land the FULL batch on
                    # device 0 for jit to reshard (an extra d2d hop +
                    # device-0 memory spike), whereas the numpy feed
                    # lets jit place each dp shard directly.  The
                    # rollback-replay path also keeps host batches: the
                    # replay buffer retains every feed since the last
                    # save, and pinning those as DEVICE arrays would
                    # burn HBM proportional to the save interval (host
                    # RAM is the right place for a recovery window).
                    from ..reader import device_prefetch

                    gen = device_prefetch(gen, size=2)
                return gen
        else:
            def prepared_batches():
                for b in dataset:
                    yield prepare(b)

        # Steady-state no-sync contract: fetches come back as DEVICE
        # arrays (return_numpy=False) and are only materialized on host
        # at print_period boundaries and for the final batch, so jax's
        # async dispatch pipelines the host several steps ahead of the
        # device (composing with the producer thread + device_prefetch
        # double buffer above).  The sparse push is the one per-step
        # exception: the gradient rows must reach the host to be pushed.
        if keep_replay and mgr.latest_step() is None:
            # rollback needs a restore point covering the WHOLE loop:
            # without this, an anomaly before the first interval save
            # has nowhere to roll back to.  (After the sparse-config
            # validation — a config error must win over a save.)
            initial = _ckpt_state()
            if initial:
                mgr.save(initial, start_step, force=True,
                         extras=_ckpt_extras())
        last = None
        step_i = start_step
        replay = []          # [(step_no, feed, fl)] since the last save

        def _elastic_rethrow(e):
            # a preemption-shaped dispatch failure (dead peer, lost
            # heartbeat, reset transport) under the coordinator is a
            # TOPOLOGY event, not a retryable blip: the state of this
            # step may be consumed (donated buffers), so the catcher
            # reshards from the newest complete checkpoint and replays
            # its cursor — no force-save here
            if elastic is None:
                return
            ev = elastic.on_dispatch_error(e, step=step_i)
            if ev is None:
                return
            survivors = [m for m in elastic.members
                         if m not in ev["ranks"]]
            action = ("reshard_local"
                      if survivors == [elastic.rank] else "relaunch")
            from ..resilience.elastic import TopologyChanged

            raise TopologyChanged(step_i, ev, action) from e

        for feed, fl, batch_ids in _goodput_batches(prepared_batches()):
            if elastic is not None:
                with _gspan("elastic_transition"):
                    ev = elastic.step_boundary(step_i)
                if ev is not None:
                    kind = ev["kind"]
                    if kind == "self_leave" and ev.get("reason") == \
                            "drain":
                        # SIGUSR1 drain-and-leave: durable boundary
                        # state, leave intent already posted, exit
                        # cleanly and stay re-admittable
                        with _gspan("elastic_transition"):
                            elastic.force_save(_ckpt_state(), step_i,
                                               extras=_ckpt_extras())
                        if mon.is_enabled():
                            mon.counter(
                                "resilience.elastic_drain_exits").add(1)
                        break
                    if kind == "rank_join":
                        # grow force-saves the rendezvous checkpoint,
                        # commits the enlarged topology, and raises
                        # TopologyChanged(action="relaunch")
                        with _gspan("elastic_transition"):
                            elastic.grow(step_i, ev["ranks"],
                                         save_state=_ckpt_state(),
                                         extras=_ckpt_extras())
                    if kind in ("rank_leave", "rank_death", "evict"):
                        # survivors force-save at THIS boundary; the
                        # caller drives the shrink (reshard in process
                        # or orchestrator relaunch) from the durable
                        # state — the loop's compiled world is stale
                        with _gspan("elastic_transition"):
                            elastic.force_save(_ckpt_state(), step_i,
                                               extras=_ckpt_extras())
                        survivors = [m for m in elastic.members
                                     if m not in ev["ranks"]]
                        action = ("reshard_local"
                                  if survivors == [elastic.rank]
                                  else "relaunch")
                        from ..resilience.elastic import TopologyChanged

                        raise TopologyChanged(step_i, ev, action)
                    # kind == "self_leave"/"preempt": fall through to
                    # the preemption block below, which force-saves,
                    # clears the flag, and exits
            if res.preemption_requested():
                # preemption-safe exit: force-checkpoint at this STEP
                # BOUNDARY (never mid-step) and leave the loop cleanly;
                # a re-launch with auto_resume=True continues here.
                # (Counted HERE, not in the signal handler — the
                # handler must stay async-signal-safe.)
                if mon.is_enabled():
                    mon.counter("resilience.preempt_requested").add(1)
                fr = _fr()
                if fr.enabled:
                    fr.note_event("preemption", step=step_i,
                                  checkpointed=mgr is not None)
                if mgr is None:
                    # stopping is still right, but a checkpoint-less
                    # loop can't consume the flag (an enclosing
                    # checkpointed loop might) — without this warning a
                    # process with NO such loop silently turns every
                    # later train_from_dataset into a 0-step no-op
                    import warnings

                    warnings.warn(
                        "preemption requested but this train_from_"
                        "dataset has no checkpoint= store; stopping "
                        "WITHOUT saving.  Pass checkpoint=<dir|"
                        "CheckpointManager> (with auto_resume=True to "
                        "continue on relaunch) to make this exit "
                        "durable; for a fleet leave that peers should "
                        "shrink around, install PreemptionHandler("
                        "drain_signal=signal.SIGUSR1) under an "
                        "ElasticCoordinator instead.  The flag stays "
                        "set for an enclosing checkpointed loop — call "
                        "resilience.clear_preemption() if none exists.",
                        RuntimeWarning, stacklevel=2)
                if mgr is not None:
                    if mgr.latest_step() != step_i:
                        # already durable at this exact boundary?  Then
                        # do NOT rewrite it: save_checkpoint rmtree's
                        # the existing dir first, and a SIGKILL during
                        # the rewrite — the grace window running out,
                        # the very scenario this path serves — would
                        # lose the only fresh restore point
                        mgr.save(_ckpt_state(), step_i, force=True,
                                 extras=_ckpt_extras(),
                                 topology=(elastic.topology()
                                           if elastic is not None
                                           else None))
                    if mon.is_enabled():
                        mon.counter("resilience.preempt_checkpoint").add(1)
                    # HANDLED (durable checkpoint taken): leaving the
                    # flag up would make every later train_from_dataset
                    # in this process train zero steps (notebook
                    # re-runs, per-epoch loops).  A checkpoint-LESS
                    # drain (eval pass, ad-hoc loop) must NOT clear it:
                    # the enclosing training loop still has to see the
                    # request and take the real force-checkpoint.
                    res.clear_preemption()
                break
            if keep_replay:
                # run with data-cursor replay: a RollbackPerformed from
                # the guard restored checkpoint step S into the scope;
                # re-run the buffered batches S+1..current in order
                # (the failing batch included — injected faults are
                # one-shot; a persistent anomaly escalates via the
                # guard's max_rollbacks)
                pending = [(step_i + 1, feed, fl)]
                while pending:
                    sno, f, flx = pending.pop(0)
                    try:
                        out = self.run(program, feed=f, fetch_list=flx,
                                       scope=scope, return_numpy=False,
                                       _train_loop=True)
                    except res.RollbackPerformed as rb:
                        redo = [it for it in replay if it[0] > rb.step]
                        replay = [it for it in replay
                                  if it[0] <= rb.step]
                        pending = redo + [(sno, f, flx)] + pending
                        continue
                    except Exception as e:
                        _elastic_rethrow(e)
                        raise
                    replay.append((sno, f, flx))
            else:
                try:
                    out = self.run(program, feed=feed, fetch_list=fl,
                                   scope=scope, return_numpy=False,
                                   _train_loop=True)
                except Exception as e:
                    _elastic_rethrow(e)
                    raise
            if entries and _sparse_push:
                n = len(entries)
                if guard is not None and guard.last_skipped:
                    # a skipped step commits NOTHING — that contract
                    # covers the sparse half too: these gradient rows
                    # are the NaNs the guard just refused to apply
                    out = out[:-n]
                else:
                    # per-step sparse sync point: awaiting the gradient
                    # rows is awaiting the step's device execution
                    with _gspan("productive_step"):
                        grads = _materialize(out[-n:])
                    for e, g in zip(entries, grads):
                        e["table"].push(batch_ids[e["emb_var"]], g)
                    out = out[:-n]
            last = out
            step_i += 1
            gled = _gp().active()
            if gled is not None:
                gled.note_step()
            if mgr is not None and mgr.should_save(step_i):
                # interval-gated BEFORE building the state dict: the
                # 999 gated-off steps of a 1000-step interval must not
                # pay per-var scope lookups or the rng-key host copy
                # (the loop's no-sync contract).  Under a coordinator,
                # every save carries the committed topology stamp —
                # restore_resharded's provenance must name the world
                # that WROTE the checkpoint, whichever save path won
                # the boundary.
                saved = mgr.save(_ckpt_state(), step_i,
                                 extras=_ckpt_extras(),
                                 topology=(elastic.topology()
                                           if elastic is not None
                                           else None))
                if saved is not None:
                    # everything up to step_i is durable: the replay
                    # window restarts here
                    replay = [it for it in replay if it[0] > step_i]
            if (debug or fetch_info) and fetch_names \
                    and step_i % print_period == 0:
                # print-period sync: draining the async pipeline here
                # waits on the steps it had in flight
                with _gspan("productive_step"):
                    vals = _materialize(out)
                msg = ", ".join(
                    f"{info}={v.mean():.6f}"
                    for info, v in zip(fetch_info, vals))
                print(f"[train_from_dataset] step {step_i}: {msg}")
        if mon.is_enabled():
            # loop-end fleet record (ISSUE 10): the rolling skew table
            # rides the telemetry stream once per loop, so a JSONL
            # report (or a post-mortem) names the straggler without
            # asking the live process
            mon.record_fleet_skew(
                key=getattr(program, "_telemetry_label", None))
        if not fetch_names:
            return None
        if last is None:
            return None
        # final sync: the async pipeline's remaining in-flight steps
        # complete here
        with _gspan("productive_step"):
            return _materialize(last)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           prefetch=None):
        """executor.py:1130 parity — same drain loop but READ-ONLY on the
        sparse tables: embedding rows are still pulled to feed the
        program, gradients are neither fetched nor pushed (so prefetch
        auto-enables: there is no pull/push ordering constraint)."""
        return self.train_from_dataset(
            program=program, dataset=dataset, scope=scope, thread=thread,
            debug=debug, fetch_list=fetch_list, fetch_info=fetch_info,
            print_period=print_period, _sparse_push=False,
            prefetch=prefetch)

    # ------------------------------------------------------------------
    @staticmethod
    def _live_ops(program, fetch_names):
        """Run-time dead-op elimination (the reference achieves this via
        feed/fetch-targeted pruning in executor.py:236/274 + _prune): keep
        ops that contribute to a fetch or to a persistable-variable update
        (optimizer steps, batch-norm stats).  Programs with backward
        sections run unpruned — everything feeds the update."""
        ops = list(program.global_block().ops)
        if program.backward_sections and not program._is_test:
            return ops
        persist = {v.name for v in program.list_vars() if v.persistable}
        needed = set(fetch_names)
        keep = [False] * len(ops)
        for i in range(len(ops) - 1, -1, -1):
            outs = set(ops[i].output_names())
            # side-effecting ops (runtime printing) survive regardless of
            # consumers — their output IS the side effect
            if outs & needed or outs & persist \
                    or ops[i].type in _SIDE_EFFECT_OPS:
                keep[i] = True
                needed |= set(ops[i].input_names())
        return [op for i, op in enumerate(ops) if keep[i]]

    def _build(self, program, fetch_names, persist_names, dp_mesh=None,
               precision=None, feed_casts=None, telemetry_key=None,
               guard_on=False, spmd_plan=None):
        ops = self._live_ops(program, fetch_names)
        sections = [] if program._is_test else list(program.backward_sections)
        if telemetry_key is None:
            # stable, readable ledger key: program identity + mutation
            # version + what it fetches (CompiledProgram.with_telemetry
            # overrides with a human-chosen label)
            telemetry_key = "prog%x:v%d" % (id(program), program._version)
        return self._build_step(ops, sections, fetch_names, persist_names,
                                dp_mesh, precision=precision,
                                feed_casts=feed_casts,
                                telemetry_key=telemetry_key,
                                guard_on=guard_on, spmd_plan=spmd_plan)

    def _build_step(self, ops, sections, fetch_names, persist_names,
                    dp_mesh, precision=None, feed_casts=None,
                    telemetry_key="program", guard_on=False,
                    spmd_plan=None):
        dp = dp_mesh is not None
        spmd = spmd_plan is not None
        # var maps for the mem-profile's variable-class attribution:
        # which entry arguments are optimizer-updated parameters vs
        # other persistable state (stats buffers, optimizer moments)
        var_info = {
            "params": frozenset(n for bs in sections
                                for n in bs.param_names),
            "persist": frozenset(persist_names),
        }

        pins = None
        state_pins = None
        model_axes = frozenset()
        if spmd:
            from jax.sharding import NamedSharding

            # inside the shard_map body the dp axis is manual, so the
            # lowered constraints name only the GSPMD auto (model)
            # axes — body_spec strips the data axis
            model_axes = frozenset(a for a in dp_mesh.axis_names
                                   if a != "dp")

            def _ns(spec):
                return NamedSharding(
                    dp_mesh, spmd_plan.body_spec(spec).to_jax())

            # activation pins at the propagator-marked edges, keyed by
            # var name (the producing op pins its output right after
            # emission — see interpret)
            pins = {name: _ns(spec)
                    for _i, name, spec in spmd_plan.constraints}
            # output-state pins: the donated state's layout is pinned
            # to its input placement, or XLA's own inference would
            # re-layout the donated buffers and retrace every step
            # (the distributed.sharded make_sharded_train_step lesson)
            state_pins = {n: _ns(s)
                          for n, s in spmd_plan.state_specs.items()}

        def make_step(dp, with_pins=True):
            return self._make_step_fn(ops, sections, fetch_names,
                                      persist_names, dp,
                                      feed_casts=feed_casts,
                                      guard_on=guard_on,
                                      telemetry_key=telemetry_key,
                                      pins=pins if with_pins else None,
                                      state_pins=(state_pins
                                                  if with_pins else None),
                                      spmd=spmd)
        step = make_step(dp)

        if not dp:
            # instrument_jit routes each new input signature's compile
            # through the monitor's AOT path (timed, cost/memory
            # analyzed) while telemetry is on; a pass-through implicit
            # jit call otherwise
            return _mon().instrument_jit(
                jax.jit(apply_precision_policy(step, precision),
                        donate_argnums=(0,)), key=telemetry_key,
                var_info=var_info)

        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        def dp_step(state, feeds, key):
            # per-device rng diversity (dropout) while state stays in
            # sync.  GSPMD tier: the fold already happened on host (a
            # manual-axis axis_index would lower to the PartitionId op
            # partial-manual modules reject) — the [dp, 2] key stack
            # arrives sharded over dp, each shard takes its row.
            if spmd:
                key = key[0]
            else:
                key = jax.random.fold_in(key, jax.lax.axis_index("dp"))
            return step(state, feeds, key)

        # for shape-only evaluation: no pins (they don't change shapes)
        plain_step = make_step(False, with_pins=False)
        memo = {}

        def compiled(state, feeds, key):
            # rank-0 fetches are replicated (pmean'd reductions); rank>=1
            # fetches concatenate over dp like ParallelExecutor's fetch
            # merge (pybind fetch path). Ranks from a shape-only eval.
            sig = tuple(sorted(
                (n, a.shape, str(a.dtype)) for n, a in feeds.items()))
            fn = memo.get(sig)
            if fn is None:
                from ..monitor import fleet as _fleet_names

                # the skew probe's reserved feeds never enter the
                # program; its wait vector rides as one extra fetch
                # BEYOND the shape-evaluated ones (replicated by the
                # all_gather, so out-spec P() with no fetch-sync pmean)
                has_fleet = _fleet_names.FLEET_TS_SEC in feeds
                # feeds split over the data axis only: on a {dp,mp}
                # rule mesh each mp shard sees the whole dp-local batch
                ndev = (int(dp_mesh.shape["dp"]) if spmd
                        else dp_mesh.devices.size)
                local_feeds = {
                    n: jax.ShapeDtypeStruct(
                        (a.shape[0] // ndev,) + a.shape[1:], a.dtype)
                    for n, a in feeds.items()
                    if not n.startswith("__fleet_")
                }
                avals = jax.eval_shape(
                    plain_step,
                    {n: jax.ShapeDtypeStruct(np.shape(v),
                                             jnp.asarray(v).dtype)
                     for n, v in state.items()},
                    local_feeds, jax.ShapeDtypeStruct((2,), np.uint32))
                fetch_ranks = [len(f.shape) for f in avals[1]]

                def dp_step_shaped(state, feeds, key):
                    new_state, fetches = dp_step(state, feeds, key)
                    skew = None
                    if has_fleet:
                        skew = fetches[-1]
                        fetches = fetches[:-1]
                    with jax.named_scope("update/dp_fetch_sync_0"):
                        fetches = [f if r >= 1
                                   else jax.lax.pmean(f, "dp")
                                   for f, r in zip(fetches, fetch_ranks)]
                    if skew is not None:
                        fetches = fetches + [skew]
                    return new_state, fetches

                out_fetch_specs = [
                    P("dp") if r >= 1 else P() for r in fetch_ranks]
                if has_fleet:
                    # GSPMD tier: the probe returns its LOCAL wait row
                    # (no in-body AllGather — XLA's propagation drops
                    # it in partial-manual modules) and the out-spec
                    # boundary concatenates the [dp] vector instead
                    out_fetch_specs = out_fetch_specs + [
                        P("dp") if spmd else P()]
                # GSPMD tier: the model axes are AUTO — XLA propagates
                # the state placements + body pins and inserts the mp
                # collectives itself; the dp axis stays manual so the
                # bucketed grad sync / skew probe machinery runs as-is
                sm_kw = {"auto": model_axes} if spmd else {}
                fn = _mon().instrument_jit(
                    jax.jit(apply_precision_policy(shard_map(
                        dp_step_shaped, mesh=dp_mesh,
                        in_specs=(P(), P("dp"),
                                  P("dp") if spmd else P()),
                        out_specs=(P(), out_fetch_specs),
                        check_vma=False, **sm_kw), precision),
                        donate_argnums=(0,)),
                    key=telemetry_key + ":dp", var_info=var_info)
                memo[sig] = fn
            return fn(state, feeds, key)

        return compiled

    def _make_step_fn(self, ops, sections, fetch_names, persist_names, dp,
                      feed_casts=None, guard_on=False,
                      telemetry_key=None, pins=None, state_pins=None,
                      spmd=False):
        # optimizer-updated params: identical across dp replicas by
        # construction, so exempt from the SyncBN-style stats averaging
        param_names = set()
        for bs in sections:
            param_names.update(bs.param_names)
        feed_casts = feed_casts or {}
        # ProgramDesc provenance: every op's kernel emission is wrapped
        # in jax.named_scope at trace time (see run_op), so the lowered
        # HLO carries per-op attribution metadata at zero runtime cost
        scopes = {id(op): name
                  for op, name in zip(ops, op_scopes(ops, sections))}
        if guard_on:
            from ..resilience.guard import all_finite as _all_finite_tree

        def step(state, feeds, key):
            env = {}
            env.update(state)
            finite = jnp.asarray(True) if guard_on else None
            # fleet skew probe (ISSUE 10): the reserved timestamp feeds
            # never enter the program env — they feed the barrier-wait
            # collective emitted in the dp_grad_sync scope below
            fleet_ts = None
            if dp and "__fleet_ts_sec__" in feeds:
                fleet_ts = (feeds["__fleet_ts_sec__"],
                            feeds["__fleet_ts_usec__"])
            skew = None
            # device-resident feeds whose dtype mismatches the declared
            # var dtype are cast HERE, inside the compiled step — the
            # cast fuses into the step instead of costing the dispatch
            # path a separate per-call device computation
            for n, v in feeds.items():
                if n.startswith("__fleet_"):
                    continue
                env[n] = v.astype(feed_casts[n]) if n in feed_casts else v
            const_env = {}
            rng_box = _RngBox(key)
            pos = 0
            for sec_i, bs in enumerate(sections):
                seg = ops[pos:bs.pos]
                train_params = {
                    n: env[n] for n in bs.param_names if n in env
                }
                chunks = _checkpoint_chunks(seg, bs.checkpoint_names)

                def fwd(ps, _env=dict(env), _chunks=chunks,
                        _loss=bs.loss_name, _key=rng_box.key):
                    e = dict(_env)
                    e.update(ps)
                    box_key = _key
                    for chunk, remat in _chunks:
                        if remat:
                            # recompute segment (RecomputeOptimizer /
                            # backward.py:623 parity) via jax.checkpoint
                            def run_chunk(e_in, k, _c=chunk):
                                e2 = dict(e_in)
                                b = _RngBox(k)
                                interpret(_c, e2, b, const_env, scopes,
                                          allow_sampling=False,
                                          pins=pins)
                                return e2, b.key

                            e, box_key = jax.checkpoint(run_chunk)(e, box_key)
                        else:
                            b = _RngBox(box_key)
                            interpret(chunk, e, b, const_env, scopes,
                                      allow_sampling=False, pins=pins)
                            box_key = b.key
                    loss = e[_loss]
                    return jnp.sum(loss), (e, box_key)

                (loss_val, (env, new_key)), grads = jax.value_and_grad(
                    fwd, has_aux=True
                )(train_params)
                rng_box = _RngBox(new_key)
                if guard_on:
                    # anomaly guard: ONE fused reduction per section over
                    # the loss and the raw (pre-sync, still scaled under
                    # AMP — exactly where update_loss_scaling samples)
                    # gradients; folded into the compiled step so the
                    # check costs no extra dispatch
                    finite = finite & jnp.isfinite(loss_val) \
                        & _all_finite_tree(grads)
                # DP gradient sync — the one collective the reference
                # inserts as allreduce op-handles
                # (multi_devices_graph_pass.cc:446), coalesced here by
                # transpiler.collective.sync_gradients into flattened
                # fixed-capacity buckets (FLAGS_dp_bucket_bytes; the
                # fuse_all_reduce_op_pass analogue — bitwise-identical
                # to per-gradient psums).  Framework-inserted (no
                # ProgramDesc op to blame), so it keeps its OWN
                # attribution scope: on a dp mesh the allreduce is real
                # device time and must not land in the unattributed
                # residual.
                with jax.named_scope(f"fwd{sec_i}/dp_grad_sync_{sec_i}"):
                    if dp:
                        from ..transpiler import collective as _coll

                        # keyed per program so the pass ledger keeps
                        # one bucketing record PER dp program instead
                        # of newest-wins under one shared key
                        synced = _coll.sync_gradients(
                            grads, "dp", key=telemetry_key)
                        if fleet_ts is not None and skew is None:
                            # the straggler probe rides the SAME scope
                            # as the bucketed grad collectives: one
                            # extra scalar pair per step, attributed to
                            # dp_grad_sync like the psums it measures
                            skew = _coll.emit_skew_probe(
                                fleet_ts[0], fleet_ts[1], "dp",
                                gather=not spmd)
                    else:
                        synced = grads
                    for n, g in synced.items():
                        env[n + "@GRAD"] = g
                pos = bs.pos
            interpret(ops[pos:], env, rng_box, const_env, scopes,
                      allow_sampling=False, pins=pins)
            fetches = [env[n] for n in fetch_names]
            new_state = {n: env[n] for n in persist_names if n in env}
            if dp:
                # params were updated identically (grads pmean'd) and need
                # no second collective; non-param float stats buffers
                # (batch-norm running stats) diverge with the local shard
                # -> average, SyncBN-style. Integer state (counters) is
                # identical across devices and must NOT go through pmean
                # (true division would float-ify it).  Scoped like the
                # grad sync: framework collective, own attribution row.
                with jax.named_scope("update/dp_state_sync_0"):
                    new_state = {
                        n: (jax.lax.pmean(v, "dp")
                            if n not in param_names and jnp.issubdtype(
                                jnp.asarray(v).dtype, jnp.floating)
                            else v)
                        for n, v in new_state.items()}
            if guard_on:
                with jax.named_scope("update/guard_check_0"):
                    # the flag travels as float32 so the dp fetch pmean
                    # averages it: ANY shard's anomaly pulls it below 1.0
                    flag = finite.astype(jnp.float32)
                    if dp:
                        flag = jax.lax.pmean(flag, "dp")
                    ok = flag >= 1.0
                    # an anomalous step commits NOTHING: select the old
                    # state on device (same contract as the AMP scaler's
                    # skip-on-overflow).  XLA copies where donation would
                    # alias — correctness first, the guard is opt-in.
                    new_state = {
                        n: (jnp.where(ok, jnp.asarray(v),
                                      jnp.asarray(state[n]))
                           if n in state else v)
                        for n, v in new_state.items()}
                fetches = fetches + [flag]
            if state_pins:
                # pin each donated state output to its INPUT layout:
                # without this XLA is free to infer a different output
                # sharding for the updated state, which both breaks
                # donation aliasing and retraces the step next call
                # with the drifted placement
                with jax.named_scope("update/spmd_state_pin_0"):
                    new_state = {
                        n: (jax.lax.with_sharding_constraint(
                                v, state_pins[n])
                            if n in state_pins else v)
                        for n, v in new_state.items()}
            if fleet_ts is not None:
                if skew is None:
                    # no backward section carried the probe (eval / dp
                    # inference program): emit it with the state-sync
                    # framework collectives instead
                    from ..transpiler import collective as _coll

                    with jax.named_scope("update/dp_grad_sync_fleet"):
                        skew = _coll.emit_skew_probe(
                            fleet_ts[0], fleet_ts[1], "dp",
                            gather=not spmd)
                # the wait vector is the VERY last fetch — the executor
                # pops it before the guard flag's own pop
                fetches = fetches + [skew]
            return new_state, fetches

        return step

    # ------------------------------------------------------------------
    def _run_eager(self, program, feed_arrays, fetch_names, scope, key,
                   return_numpy):
        """Op-by-op interpretation without jit (FLAGS_eager_executor), with
        per-op NaN/Inf checking when FLAGS_check_nan_inf is set (parity:
        operator.cc:1032 + nan_inf_utils_detail.cc)."""
        check = flags.flag("check_nan_inf")
        env = {}
        for n, v in scope.vars.items():
            if v is not None:
                env[n] = v
        env.update(feed_arrays)
        rng_box = _RngBox(key)
        ops = self._live_ops(program, fetch_names)
        sections = [] if program._is_test else list(program.backward_sections)
        scopes = {id(op): name
                  for op, name in zip(ops, op_scopes(ops, sections))}
        pos = 0
        persist = {v.name for v in program.list_vars() if v.persistable}

        def run_seg(seg):
            if not check:
                # the sampling-aware loop: per-op timing when a
                # monitor.op_profile sampler is active
                interpret(seg, env, rng_box, None, scopes)
                return
            for op in seg:
                run_op(op, env, rng_box, None, scopes.get(id(op)))
                for slot, names in op.outputs.items():
                    for n in names:
                        if n in env and jnp.issubdtype(
                            jnp.asarray(env[n]).dtype, jnp.floating
                        ):
                            if not bool(jnp.all(jnp.isfinite(env[n]))):
                                raise FloatingPointError(
                                    f"op '{op.type}' output '{n}' "
                                    f"contains NaN/Inf"
                                )

        for bs in sections:
            seg = ops[pos:bs.pos]
            train_params = {n: env[n] for n in bs.param_names if n in env}

            def fwd(ps, _env=dict(env), _seg=seg, _key=rng_box.key):
                e = dict(_env)
                e.update(ps)
                box = _RngBox(_key)
                interpret(_seg, e, box, None, scopes)
                return jnp.sum(e[bs.loss_name]), (e, box.key)

            (loss_val, (env, new_key)), grads = jax.value_and_grad(
                fwd, has_aux=True
            )(train_params)
            rng_box = _RngBox(new_key)
            if check:
                for n, g in grads.items():
                    if not bool(jnp.all(jnp.isfinite(g))):
                        raise FloatingPointError(f"gradient of '{n}' has NaN/Inf")
            for n, g in grads.items():
                env[n + "@GRAD"] = g
            pos = bs.pos
        run_seg(ops[pos:])

        for n in persist:
            if n in env:
                scope.set_var(n, env[n])
        fetches = [env[n] for n in fetch_names]
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches
