from .program import (
    Program,
    Block,
    Variable,
    Parameter,
    Operator,
    BackwardSection,
    default_main_program,
    default_startup_program,
    program_guard,
    name_scope,
    data,
)
from .executor import Executor, Scope, global_scope, scope_guard
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from .backward import append_backward, gradients
from .param_attr import ParamAttr
from . import initializer, unique_name

__all__ = [
    "Program", "Block", "Variable", "Parameter", "Operator",
    "BackwardSection", "default_main_program", "default_startup_program",
    "program_guard", "name_scope", "data", "Executor", "Scope",
    "CompiledProgram", "BuildStrategy", "ExecutionStrategy",
    "global_scope", "scope_guard", "append_backward", "gradients",
    "ParamAttr", "initializer", "unique_name",
]
