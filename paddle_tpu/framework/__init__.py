from .program import (
    Program,
    Block,
    Variable,
    Parameter,
    Operator,
    BackwardSection,
    default_main_program,
    default_startup_program,
    program_guard,
    name_scope,
    data,
)
from .executor import Executor, Scope, global_scope, scope_guard
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy
from .backward import append_backward, gradients
from .param_attr import ParamAttr
from . import initializer, unique_name

__all__ = [
    "Program", "Block", "Variable", "Parameter", "Operator",
    "BackwardSection", "default_main_program", "default_startup_program",
    "program_guard", "name_scope", "data", "Executor", "Scope",
    "CompiledProgram", "BuildStrategy", "ExecutionStrategy",
    "global_scope", "scope_guard", "append_backward", "gradients",
    "ParamAttr", "initializer", "unique_name",
]


# ---- device/place helpers + version/dygraph introspection ----------------
# (reference framework.py: cuda_places :318, cpu_places :368,
#  cuda_pinned_places :399, in_dygraph_mode :222, is_compiled_with_cuda
#  :342, load_op_library :..., require_version :129, device_guard :5461)

def cpu_places(device_count=None):
    """List of CPUPlace; count defaults to CPU_NUM (reference) or 1."""
    import os

    from ..core.place import CPUPlace

    if device_count is None:
        device_count = int(os.environ.get("CPU_NUM", "1"))
    return [CPUPlace() for _ in range(device_count)]


def cuda_places(device_ids=None):
    """Reference lists CUDA devices; here the accelerator set is the
    jax device list (TPU chips), exposed as TPUPlace — a 1.x script's
    `places=fluid.cuda_places()` keeps meaning "all accelerators"."""
    import jax

    from ..core.place import TPUPlace

    devs = [d for d in jax.devices() if d.platform != "cpu"]
    if device_ids is not None:
        return [TPUPlace(i) for i in device_ids]
    if not devs:
        return cpu_places()
    return [TPUPlace(d.id) for d in devs]


def cuda_pinned_places(device_count=None):
    """Pinned host memory has no XLA-level control; returns CPU places
    (honest shim, same count semantics as the reference)."""
    return cpu_places(device_count)


def in_dygraph_mode():
    """True inside dygraph.guard() (reference: tracer active)."""
    from .. import dygraph

    return dygraph._guard_depth > 0


def is_compiled_with_cuda():
    """Always False: this build targets TPU via XLA, never CUDA."""
    return False


def load_op_library(lib_path):
    """The reference dlopens a custom-op .so and re-generates layer
    wrappers.  Custom native ops here are Pallas kernels registered via
    ops.registry; there is no compatible binary ABI to load, so this
    raises with the migration pointer instead of silently ignoring."""
    raise NotImplementedError(
        f"load_op_library({lib_path!r}): CUDA/C++ custom-op libraries "
        "have no TPU ABI; register a JAX/Pallas kernel via "
        "paddle_tpu.ops.registry.register_op instead")


def require_version(min_version, max_version=None):
    """Version gate (reference framework.py:129): validates THIS
    package's version against [min_version, max_version]."""
    from ..version import full_version

    def parse(v):
        parts = []
        for p in str(v).split("."):
            parts.append(int(p) if p.isdigit() else 0)
        return (parts + [0, 0, 0, 0])[:4]

    if not isinstance(min_version, str) or (
            max_version is not None and not isinstance(max_version, str)):
        raise TypeError("require_version: version args must be str")
    cur = parse(full_version)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {full_version} < required min "
            f"{min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {full_version} > allowed max "
            f"{max_version}")


class _DeviceGuard:
    def __init__(self, device):
        self.device = device

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def device_guard(device=None):
    """Reference pins ops in the block to a device (framework.py:5461).
    Under XLA, placement inside one program is the compiler's decision,
    so the context is an honest no-op kept for script parity."""
    if device not in (None, "cpu", "gpu", "tpu") and not str(
            device).startswith(("gpu:", "tpu:")):
        raise ValueError(f"device_guard: unknown device {device!r}")
    return _DeviceGuard(device)


__all__ += ["cpu_places", "cuda_places", "cuda_pinned_places",
            "in_dygraph_mode", "is_compiled_with_cuda",
            "load_op_library", "require_version", "device_guard"]
