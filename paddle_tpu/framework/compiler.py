"""CompiledProgram — multi-device execution of a static-graph Program.

Parity: /root/reference/python/paddle/fluid/compiler.py:87 (CompiledProgram)
and :296 (_compile_data_parallel -> core.ParallelExecutor). The reference
clones the graph per device, inserts allreduce op-handles, and drains an
SSA graph with a thread pool (framework/parallel_executor.cc:443,
details/threaded_ssa_graph_executor.cc:150). Here the SAME recorded Program
is lowered to ONE SPMD train step over the mesh's "dp" axis: state
(persistables) replicated, feed batches sharded on their leading dim,
gradients pmean'd between the backward and the optimizer ops. XLA compiles
the collectives; there are no op-handles, rings, or thread pools to manage.

Fetch semantics mirror ParallelExecutor: a fetched tensor of rank >= 1
comes back concatenated over the dp axis (the reference merges per-device
LoDTensors, pybind fetch path), so a [1]-shaped loss fetched over 8
devices is returned as shape [8] — average it like reference users do.
"""

import numpy as np

__all__ = ["CompiledProgram", "BuildStrategy", "ExecutionStrategy",
           "resolve_precision", "apply_precision_policy"]


# ---------------------------------------------------------------------------
# Precision policy — the explicit bf16 conv/matmul knob on compiled steps
# ---------------------------------------------------------------------------

def resolve_precision(program=None):
    """Precision for a compiled step: the program's own override
    (CompiledProgram.with_precision) wins, else FLAGS_conv_matmul_precision,
    else None (jax's default).  Values: "bfloat16" (pin every dot/conv to
    the bf16 MXU path — the precision lever of the ResNet-50 A/B grid),
    "tensorfloat32", "float32"/"highest" (full-precision passes)."""
    p = getattr(program, "_precision", None) if program is not None else None
    if p is None:
        from .. import flags

        p = flags.flag("conv_matmul_precision") or None
    return p


def apply_precision_policy(fn, precision):
    """Wrap a step callable so `jax.default_matmul_precision(precision)`
    is active while jit TRACES it — every dot_general / conv the step
    stages inherits the policy.  No-op for a falsy precision."""
    if not precision:
        return fn
    import functools

    import jax

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.default_matmul_precision(precision):
            return fn(*args, **kwargs)

    return wrapped


class BuildStrategy:
    """Parity shim for details/build_strategy.h:37. Only the knobs with a
    TPU meaning survive; graph-surgery options (fuse passes, memory
    optimize) are XLA's job and are accepted-and-ignored for script
    compatibility."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = 0
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_reduce_ops = None
        self.fuse_elewise_add_act_ops = None
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    """Parity shim for ExecutionStrategy (pybind'd struct): thread counts
    are meaningless under one compiled program; kept for script parity."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.num_iteration_per_run = 1


class CompiledProgram:
    """Wrap a Program for (optionally multi-device) execution.

        compiled = CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        exe.run(compiled, feed=..., fetch_list=[loss])

    Without with_data_parallel, running a CompiledProgram is identical to
    running the raw Program (the reference's single-device CompiledProgram
    applies build passes; ours are XLA's problem).
    """

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = None
        self._is_data_parallel = False
        self._dp_places = None
        self._loss_name = None
        self._precision = None
        self._telemetry_label = None
        self._dp_mesh_cache = None   # (ndev, Mesh) — see _dp_mesh
        self._dp_key_cache = None    # (Mesh, key) — see _dp_mesh_key
        self._is_spmd = False        # with_sharding_rules(execute=True)
        self._spmd_rules = None      # PartitionRules driving execution
        self._spmd_places = None     # explicit device list (elastic)
        self._spmd_mesh_cache = None  # (fingerprint, Mesh)

    def with_precision(self, precision):
        """Pin the matmul/conv precision this program compiles with
        ("bfloat16" | "tensorfloat32" | "float32" | "highest"); overrides
        FLAGS_conv_matmul_precision for this program only."""
        self._precision = precision
        return self

    def with_telemetry(self, label):
        """Name this program in the telemetry compile ledger: while
        `monitor.is_enabled()`, its compile events, cost-analysis FLOPs
        and memory bytes are keyed by `label` (default: an opaque
        program-identity key), so `monitor.mfu(step_time, key=label)`
        and the per-program ledger read naturally."""
        self._telemetry_label = label
        return self

    def with_sharding_rules(self, rules, mesh=None, data_axis="dp",
                            execute=False, places=None):
        """Attach a partition-rule set for the static sharding
        analyzer (ISSUE 12): under ``FLAGS_static_check`` the verifier
        lints the program against these rules (PT301-PT306 — rule
        misses, replicated giants, hot-edge reshards, divisibility,
        conflicting joins, unresolved psums) before any trace.

        ``rules`` is an ``analysis.sharding.PartitionRules``, a
        ``{"mesh": ..., "rules": ...}`` dict (the rule-file format), or
        a plain ``[(regex, dims), ...]`` list with ``mesh`` given
        separately.  Attachment is analysis metadata, not a graph
        mutation: the program version does not bump, and the lint
        cache keys on the rule fingerprint.

        ``execute=True`` is the GSPMD runtime tier (ISSUE 16): the
        executor LOWERS these rules — params and donated optimizer
        state placed per-leaf on the rule mesh, activation edges pinned
        with ``with_sharding_constraint``, feeds batch-sharded over the
        data axis, model axes handed to XLA as GSPMD auto axes.
        ``places`` pins an explicit device list (elastic contract);
        re-attaching a different rule set retraces (the compiled-step
        cache keys on the rule fingerprint + mesh device identity)."""
        from ..analysis import sharding as _sh

        if isinstance(rules, dict):
            rules = _sh.PartitionRules.from_dict(rules)
        elif not isinstance(rules, _sh.PartitionRules):
            if mesh is None:
                raise ValueError(
                    "with_sharding_rules(list_of_rules) needs mesh=")
            rules = _sh.PartitionRules(rules, mesh,
                                       data_axis=data_axis)
        _sh.attach(self._program, rules)
        if execute:
            self._is_spmd = True
            self._spmd_rules = rules
            if places is not None:
                self._spmd_places = places
            self._spmd_mesh_cache = None
        return self

    # -- reference API ---------------------------------------------------
    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        """compiler.py:296 parity. places defaults to every local device;
        pass an int to cap the dp width, a list of Places, or a list of
        jax Devices (the elastic runtime retargets a survivor onto
        exactly its local devices this way)."""
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._exec_strategy = exec_strategy
        self._dp_places = places
        self._dp_mesh_cache = None
        return self

    def retarget_dp(self, places):
        """Elastic hook (ISSUE 11): re-point the dp mesh at a new
        device set after a topology change — same contract as the
        places= of with_data_parallel, but callable mid-run.  The mesh
        memo is invalidated here; the executor's compiled-step cache
        keys on the mesh's device identity, so the next run retraces
        on the new world instead of serving the stale executable."""
        if not self._is_data_parallel:
            raise ValueError("retarget_dp needs with_data_parallel first")
        self._dp_places = places
        self._dp_mesh_cache = None
        return self

    # -- executor integration -------------------------------------------
    def _get_executable_program(self):
        return self._program

    def _dp_device_count(self):
        import jax

        places = self._dp_places
        if places is None:
            return len(jax.devices())
        if isinstance(places, int):
            return places
        return len(places)

    def _dp_mesh(self):
        """Mesh over the dp devices, memoized per device count: the
        executor asks for it on EVERY run, and rebuilding a Mesh per
        step is host dispatch overhead (plus a fresh object identity
        for jit to hash).  Invalidates itself if with_data_parallel /
        retarget_dp re-targets a different place set.

        places as a list of jax Devices pins the mesh to EXACTLY those
        devices (the elastic shrink path: a survivor's local devices
        only, so no collective can touch a dead peer's channel);
        otherwise the first `n` global devices as before."""
        import jax
        from jax.sharding import Mesh

        places = self._dp_places
        explicit = (isinstance(places, (list, tuple)) and places
                    and all(hasattr(p, "id") and hasattr(p, "platform")
                            for p in places))
        if explicit:
            devs = list(places)
            n = len(devs)
        else:
            n = self._dp_device_count()
            devs = None
        cached = self._dp_mesh_cache
        if cached is not None and cached[0] == n:
            return cached[1]
        if devs is None:
            devs = jax.devices()[:n]
        mesh = Mesh(np.array(devs), ("dp",))
        self._dp_mesh_cache = (n, mesh)
        from .. import monitor

        if monitor.is_enabled():
            monitor.gauge("dp_devices").set(n)
        return mesh

    def _dp_mesh_key(self):
        """Device-identity cache key of the current dp mesh, via the
        shared :func:`distributed.mesh.mesh_layout` cache (ISSUE 16
        satellite — the same layout object serves the fleet timestamp
        feeds and the skew probe).  Memoized with the mesh itself, so
        the executor's per-dispatch key build stays O(1) — and a
        retarget_dp onto a SAME-SIZED different device set still
        retraces instead of serving the old world's executable."""
        mesh = self._dp_mesh()
        cached = self._dp_key_cache
        if cached is not None and cached[0] is mesh:
            return cached[1]
        from ..distributed.mesh import mesh_layout

        key = mesh_layout(mesh, "dp").key
        self._dp_key_cache = (mesh, key)
        return key

    # -- GSPMD runtime tier (ISSUE 16) ----------------------------------
    def _spmd_mesh(self):
        """Mesh for the attached rule set's ``{axis: size}`` spec
        (``build_rule_mesh`` — analyzer axis names become jax mesh axes
        verbatim), memoized per rule fingerprint.  ``places`` given to
        ``with_sharding_rules(execute=True)`` pins the device list."""
        rules = self._spmd_rules
        if rules is None:
            raise ValueError(
                "no executable rules: with_sharding_rules(..., "
                "execute=True) first")
        from ..distributed.mesh import build_rule_mesh

        fp = rules.fingerprint()
        cached = self._spmd_mesh_cache
        if cached is not None and cached[0] == fp:
            return cached[1]
        places = self._spmd_places
        devices = None
        if isinstance(places, (list, tuple)) and places:
            devices = list(places)
        mesh = build_rule_mesh(rules.mesh, devices=devices)
        self._spmd_mesh_cache = (fp, mesh)
        from .. import monitor

        if monitor.is_enabled():
            monitor.gauge("spmd_devices").set(int(mesh.devices.size))
        return mesh

    def _spmd_layout(self):
        """Shared MeshLayout for the spmd mesh, keyed on (device
        identity, rule fingerprint) in the distributed.mesh cache."""
        rules = self._spmd_rules
        mesh = self._spmd_mesh()
        from ..distributed.mesh import mesh_layout

        return mesh_layout(mesh, data_axis=rules.data_axis,
                           fingerprint=rules.fingerprint())

    def _spmd_key(self):
        """Compiled-step cache key of the spmd tier: rule fingerprint +
        mesh device identity — re-attaching rules OR retargeting the
        mesh retraces instead of serving a stale layout."""
        layout = self._spmd_layout()
        return (layout.key, layout.fingerprint)
