"""Unique name generator.

Parity: /root/reference/python/paddle/fluid/unique_name.py — per-prefix
counters with guard support for reproducible naming.
"""

import contextlib


class UniqueNameGenerator:
    def __init__(self):
        self.ids = {}

    def __call__(self, key):
        if key not in self.ids:
            self.ids[key] = 0
        n = self.ids[key]
        self.ids[key] += 1
        return f"{key}_{n}"


generator = UniqueNameGenerator()


def generate(key):
    return generator(key)


@contextlib.contextmanager
def guard(new_generator=None):
    global generator
    old = generator
    generator = new_generator or UniqueNameGenerator()
    try:
        yield
    finally:
        generator = old
