"""Unique name generator.

Parity: /root/reference/python/paddle/fluid/unique_name.py — per-prefix
counters with guard support for reproducible naming.
"""

import contextlib


class UniqueNameGenerator:
    def __init__(self):
        self.ids = {}

    def __call__(self, key):
        if key not in self.ids:
            self.ids[key] = 0
        n = self.ids[key]
        self.ids[key] += 1
        return f"{key}_{n}"


generator = UniqueNameGenerator()


def generate(key):
    return generator(key)


def generate_with_ignorable_key(key):
    """unique_name.py:123 parity — same counter space; the reference's
    "ignorable" prefix only matters to its dygraph name checker."""
    return generator(key)


def switch(new_generator=None):
    """unique_name.py:131 parity — swap the global generator, returning
    the previous one so callers can restore it."""
    global generator
    old = generator
    generator = new_generator or UniqueNameGenerator()
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    old = switch(new_generator)
    try:
        yield
    finally:
        switch(old)
