"""Program / Block / Variable / Operator graph builder.

TPU-native replacement for the reference's ProgramDesc stack — both the C++
proto IR (/root/reference/paddle/fluid/framework/framework.proto:42-216) and
the Python mirror (python/paddle/fluid/framework.py: Variable:806,
Operator:1706, Block:2176, Program:3602).

Design inversion vs the reference: a Program here is a lightweight recorded
op list that the Executor lowers to ONE jitted jax function.  There is no
graph-IR pass framework (framework/ir/) — fusion, memory planning, and
multi-device partitioning are XLA's job.  What is kept is the *user-facing*
graph-builder API (append_op / vars / parameters / clone / serialization)
because that is the reference's programming model.
"""

import contextlib
import copy
import json
import os
import sys

import numpy as np

from ..core.dtype import convert_dtype
from . import unique_name

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _user_callsite():
    """First stack frame outside the paddle_tpu package — where the user
    built this op (reference op_call_stack.cc attaches the Python stack
    to op errors). Walks raw frames via sys._getframe — unlike
    traceback.extract_stack this never touches source files, so per-op
    graph-build overhead stays negligible even for large programs."""
    fr = sys._getframe(1)
    depth = 0
    while fr is not None and depth < 24:
        code = fr.f_code
        if not code.co_filename.startswith(_PKG_DIR):
            return f"{code.co_filename}:{fr.f_lineno} ({code.co_name})"
        fr = fr.f_back
        depth += 1
    return None


def did_you_mean(name, candidates, n=3, cutoff=0.6):
    """Difflib close-match suggestion text (" — did you mean ...?") or
    "" when nothing is close.  The ONE fuzzy-suggestion rule: Block.var
    uses it for typo'd var names and the sharding rule engine for rule
    regexes that match zero vars — a typo'd rule gets the same
    treatment a typo'd fetch does."""
    import difflib

    close = difflib.get_close_matches(name, list(candidates), n=n,
                                      cutoff=cutoff)
    if not close:
        return ""
    return " — did you mean " + " or ".join(
        f"'{c}'" for c in close) + "?"


class Variable:
    """A named slot in a Block. Parity: framework.py:806."""

    def __init__(
        self,
        block,
        name=None,
        shape=None,
        dtype="float32",
        persistable=False,
        stop_gradient=False,
        is_data=False,
        lod_level=0,
    ):
        self.block = block
        self.name = name or unique_name.generate("_generated_var")
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = convert_dtype(dtype) if dtype is not None else None
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.lod_level = lod_level

    @property
    def persistable(self):
        return self._persistable

    @persistable.setter
    def persistable(self, value):
        # layers toggle persistability on existing vars (plain attribute
        # write); the flip changes the executor's persist-name analysis,
        # so it must bump the program version like any other mutation or
        # a cached run-plan would keep serving the stale persist set
        value = bool(value)
        old = getattr(self, "_persistable", None)
        self._persistable = value
        if old is not None and old != value:
            self.block.program._bump()

    @property
    def is_parameter(self):
        return isinstance(self, Parameter)

    def astype(self, dtype):
        from ..layers import cast

        return cast(self, dtype)

    # Python operator sugar (parity: layers/math_op_patch.py)
    def _elementwise(self, other, op_type, reverse=False):
        from ..layers import elementwise_op_with_scalar

        return elementwise_op_with_scalar(self, other, op_type, reverse)

    def __add__(self, other):
        return self._elementwise(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._elementwise(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._elementwise(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._elementwise(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._elementwise(other, "elementwise_div")

    def __matmul__(self, other):
        from ..layers import matmul

        return matmul(self, other)

    def __neg__(self):
        from ..layers import scale

        return scale(self, scale=-1.0)

    def __repr__(self):
        kind = "Parameter" if self.is_parameter else "Variable"
        return f"{kind}(name={self.name}, shape={self.shape}, dtype={self.dtype})"

    def to_dict(self):
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "is_parameter": self.is_parameter,
            "lod_level": self.lod_level,
        }


class Parameter(Variable):
    """Trainable persistable variable. Parity: framework.py:4631."""

    def __init__(self, block, name=None, shape=None, dtype="float32",
                 trainable=True, regularizer=None, **kwargs):
        super().__init__(
            block, name=name, shape=shape, dtype=dtype,
            persistable=True, stop_gradient=not trainable,
        )
        self.trainable = trainable
        self.regularizer = regularizer
        self.initializer = kwargs.get("initializer")


class Operator:
    """One recorded op. Parity: framework.py:1706 / OpDesc (framework.proto:42).

    inputs/outputs map slot name -> list of variable names (strings), like
    OpDesc.Var in the proto.
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs = {}
        self.outputs = {}
        self.attrs = dict(attrs or {})
        for slot, vs in (inputs or {}).items():
            self.inputs[slot] = [v.name if isinstance(v, Variable) else v
                                 for v in _as_list(vs)]
        for slot, vs in (outputs or {}).items():
            self.outputs[slot] = [v.name if isinstance(v, Variable) else v
                                  for v in _as_list(vs)]
        # creation site for error decoration (op_call_stack.cc parity):
        # first caller frame outside paddle_tpu
        self.callsite = _user_callsite()
        # provenance for graph-optimizer rewrites: scope names of the
        # source ops this op absorbed (paddle_tpu.passes sets it), so
        # per-op attribution of an optimized program maps fused/folded
        # ops back to what the user built
        self.folded_from = ()

    def input_names(self):
        return [n for vs in self.inputs.values() for n in vs]

    def output_names(self):
        return [n for vs in self.outputs.values() for n in vs]

    def __repr__(self):
        return f"Op({self.type}: {self.inputs} -> {self.outputs})"

    def to_dict(self):
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": _jsonable_attrs(self.attrs),
        }


def _as_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _jsonable_attrs(attrs):
    out = {}
    for k, v in attrs.items():
        if k.startswith("_") and k != "_amp_inserted":
            # underscore attrs are runtime-only (rng keys etc.) —
            # except the AMP pin tag, a plain bool the numerics
            # analyzer must still see on a reloaded program (an
            # untagged identity pin would lint as PT403 churn)
            continue
        if isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        elif isinstance(v, Block):
            out[k] = {"__block__": v.idx}
        else:
            out[k] = v
    return out


class Block:
    """Op list + var scope. Parity: framework.py:2176 / BlockDesc."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}  # name -> Variable
        self.ops = []

    # -- vars ---------------------------------------------------------------

    def create_var(self, name=None, **kwargs):
        var = Variable(self, name=name, **kwargs)
        self.vars[var.name] = var
        self.program._bump()
        return var

    def create_parameter(self, name=None, shape=None, dtype="float32",
                         trainable=True, regularizer=None, initializer=None):
        p = Parameter(self, name=name, shape=shape, dtype=dtype,
                      trainable=trainable, regularizer=regularizer,
                      initializer=initializer)
        # creation provenance, like ops: the sharding lints (PT301/302)
        # blame a parameter, not an op — the callsite names where the
        # layer that made it was called
        p.callsite = _user_callsite()
        self.vars[p.name] = p
        self.program._bump()
        return p

    def var(self, name):
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError(
                f"variable '{name}' not found in block {self.idx}"
                + self._did_you_mean(name))
        return v

    def _did_you_mean(self, name):
        """Close-match suggestions over this block + its ancestors —
        a typo'd fetch/feed name gets candidates instead of a bare
        name error (op_call_stack-style ergonomics for the graph
        API).  Shares the module-level did_you_mean rule with the
        sharding rule engine's zero-match reporting."""
        candidates = set()
        b = self
        while True:
            candidates.update(b.vars)
            if b.parent_idx < 0:
                break
            b = self.program.blocks[b.parent_idx]
        return did_you_mean(name, candidates)

    def has_var(self, name):
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name):
        b = self
        while True:
            if name in b.vars:
                return b.vars[name]
            if b.parent_idx < 0:
                return None
            b = self.program.blocks[b.parent_idx]

    def all_parameters(self):
        return [v for v in self.vars.values() if v.is_parameter]

    # -- ops ----------------------------------------------------------------

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        self.program._bump()
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self.program._bump()
        return op

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": {n: v.to_dict() for n, v in self.vars.items()},
            "ops": [op.to_dict() for op in self.ops],
        }


class BackwardSection:
    """Marker recorded by append_backward: 'at op position `pos`, compute
    grads of `loss` w.r.t. `params` into <name>@GRAD vars'.  The executor
    realizes it with jax.value_and_grad over the preceding op segment —
    the TPU-native analogue of the grad-op chain appended by
    python/paddle/fluid/backward.py:1145."""

    def __init__(self, pos, loss_name, param_names, no_grad_set=None,
                 checkpoint_names=None):
        self.pos = pos
        self.loss_name = loss_name
        self.param_names = list(param_names)
        self.no_grad_set = set(no_grad_set or ())
        # recompute segments (RecomputeOptimizer parity): activation names
        # marked as checkpoints; executor wraps segments in jax.checkpoint.
        self.checkpoint_names = list(checkpoint_names or ())


class Program:
    """Parity: framework.py:3602 / ProgramDesc (framework.proto:211)."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self.random_seed = None
        self._version = 0
        self.backward_sections = []
        self._is_test = False
        # amp state set by amp.decorate; consulted by the executor
        self.amp_enabled = False
        # executor run-plan (executor._RunPlan): the steady-state
        # dispatch analysis cached per (program, _version).  Lives on
        # the Program — not in an id()-keyed executor dict — so a
        # recycled address after GC can never serve a stale plan.
        self._run_plan_cache = None
        # graph-optimizer state: optimize-time-evaluated constants
        # ({name: ndarray} — the executor seeds scopes from it) and the
        # cache of optimized substitute programs keyed by
        # (version, fetches, pass config)
        self._folded_constants = None
        self._opt_cache = None

    # -- structure ----------------------------------------------------------

    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[self.current_block_idx]

    def create_block(self, parent_idx=None):
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        return b

    def rollback(self):
        self.current_block_idx = self.blocks[self.current_block_idx].parent_idx

    def _bump(self):
        # every graph mutation lands here.  The version bump re-keys
        # the compiled-step cache; the derived caches living ON the
        # program (run-plan, lint results, optimized substitutes) are
        # dropped in the same call so no consumer can observe a window
        # where the version moved but a stale artifact still answers.
        self._version += 1
        self._run_plan_cache = None
        cache = getattr(self, "_lint_cache", None)
        if cache:
            cache.clear()
        self._opt_cache = None

    def all_parameters(self):
        return [p for b in self.blocks for p in b.all_parameters()]

    def list_vars(self):
        return [v for b in self.blocks for v in b.vars.values()]

    def num_ops(self):
        return sum(len(b.ops) for b in self.blocks)

    # -- clone / prune ------------------------------------------------------

    def clone(self, for_test=False):
        """Deep-copy the program. for_test=True marks test mode: executor
        runs batch_norm/dropout in inference mode and skips backward
        sections (parity: Program.clone framework.py:3806)."""
        p = Program()
        p.blocks = []
        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            for name, v in b.vars.items():
                cls = Parameter if v.is_parameter else Variable
                nv = cls.__new__(cls)
                nv.__dict__.update({k: copy.copy(val) for k, val in v.__dict__.items()
                                    if k != "block"})
                nv.block = nb
                nb.vars[name] = nv
            for op in b.ops:
                no = Operator(nb, op.type)
                no.inputs = {k: list(v) for k, v in op.inputs.items()}
                no.outputs = {k: list(v) for k, v in op.outputs.items()}
                no.attrs = dict(op.attrs)
                no.folded_from = getattr(op, "folded_from", ())
                if for_test and "is_test" in _TEST_MODE_OPS.get(op.type, ()):
                    no.attrs["is_test"] = True
                nb.ops.append(no)
            p.blocks.append(nb)
        p.current_block_idx = 0
        p.random_seed = self.random_seed
        p._is_test = for_test
        p.amp_enabled = self.amp_enabled
        if self._folded_constants:
            p._folded_constants = dict(self._folded_constants)
        # sharding-rule attachment (analysis metadata) rides along:
        # the for_test eval twin must lint PT3xx like its parent
        rules = getattr(self, "_sharding_rules", None)
        if rules is not None:
            p._sharding_rules = rules
        if for_test:
            # prune backward + optimize ops (parity: Program.clone's test
            # mode, framework.py:3806 — everything appended after the first
            # backward marker is training-only)
            if self.backward_sections:
                cutoff = min(s.pos for s in self.backward_sections)
                p.global_block().ops = p.global_block().ops[:cutoff]
        else:
            p.backward_sections = [copy.deepcopy(s) for s in self.backward_sections]
        return p

    def _prune(self, fetch_names):
        """Keep only ops needed to produce fetch_names (parity:
        Program._prune, used by save_inference_model)."""
        needed = set(fetch_names)
        keep_idx = set()
        ops = self.global_block().ops
        for i in range(len(ops) - 1, -1, -1):
            if set(ops[i].output_names()) & needed:
                keep_idx.add(i)
                needed |= set(ops[i].input_names())
        pruned = self.clone(for_test=True)
        pruned.global_block().ops = [
            op for i, op in enumerate(pruned.global_block().ops) if i in keep_idx
        ]
        return pruned

    # -- serialization ------------------------------------------------------

    def to_json(self):
        doc = {
            "version": 1,
            "blocks": [b.to_dict() for b in self.blocks],
            "backward_sections": [
                {"pos": s.pos, "loss": s.loss_name, "params": s.param_names,
                 "checkpoints": s.checkpoint_names}
                for s in self.backward_sections
            ],
            "is_test": self._is_test,
            # an AMP-rewritten program must round-trip as rewritten:
            # a reloaded substitute fed back through rewrite_train_
            # program (e.g. tools/program_lint.py --amp) would
            # otherwise be double-cast
            "amp_enabled": self.amp_enabled,
        }
        if self._folded_constants:
            doc["folded_constants"] = {
                n: {"__ndarray__": np.asarray(v).tolist(),
                    "dtype": str(np.asarray(v).dtype)}
                for n, v in self._folded_constants.items()
            }
        return json.dumps(doc)

    @staticmethod
    def from_json(text):
        data = json.loads(text)
        p = Program()
        p.blocks = []
        for bd in data["blocks"]:
            b = Block(p, bd["idx"], bd["parent_idx"])
            for name, vd in bd["vars"].items():
                cls = Parameter if vd.get("is_parameter") else Variable
                if cls is Parameter:
                    v = Parameter(b, name=name, shape=vd["shape"], dtype=vd["dtype"],
                                  trainable=not vd["stop_gradient"])
                else:
                    v = Variable(b, name=name, shape=vd["shape"], dtype=vd["dtype"],
                                 persistable=vd["persistable"],
                                 stop_gradient=vd["stop_gradient"],
                                 is_data=vd.get("is_data", False))
                b.vars[name] = v
            for od in bd["ops"]:
                op = Operator(b, od["type"])
                op.inputs = od["inputs"]
                op.outputs = od["outputs"]
                op.attrs = _attrs_from_json(od["attrs"])
                b.ops.append(op)
            p.blocks.append(b)
        for sd in data.get("backward_sections", []):
            p.backward_sections.append(
                BackwardSection(sd["pos"], sd["loss"], sd["params"],
                                checkpoint_names=sd.get("checkpoints")))
        p._is_test = data.get("is_test", False)
        p.amp_enabled = data.get(
            "amp_enabled",
            # pre-amp_enabled serializations: the AMP rewrite's tagged
            # cast pins are the durable evidence it already ran
            any(op.get("attrs", {}).get("_amp_inserted")
                for bd in data["blocks"] for op in bd["ops"]))
        fc = data.get("folded_constants")
        if fc:
            p._folded_constants = {
                n: np.array(v["__ndarray__"], dtype=v["dtype"])
                for n, v in fc.items()
            }
        return p

    def to_string(self, throw_on_error=False):
        lines = []
        for b in self.blocks:
            lines.append(f"block {b.idx} (parent {b.parent_idx}):")
            for v in b.vars.values():
                lines.append(f"  {v!r}")
            for op in b.ops:
                lines.append(f"  {op!r}")
        return "\n".join(lines)

    __str__ = to_string


def _attrs_from_json(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, dict) and "__ndarray__" in v:
            out[k] = np.array(v["__ndarray__"], dtype=v["dtype"])
        else:
            out[k] = v
    return out


# ops whose behavior flips in test mode (clone(for_test=True))
_TEST_MODE_OPS = {
    "dropout": ("is_test",),
    "batch_norm": ("is_test",),
}


# ---------------------------------------------------------------------------
# Default programs + guards (parity: framework.py:4879 default_main_program)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program():
    return _main_program


def default_startup_program():
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    old_main, old_startup = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program = old_main
        _startup_program = old_startup


def data(name, shape, dtype="float32", lod_level=0):
    """Declare a feed variable (parity: fluid.data)."""
    block = default_main_program().global_block()
    return block.create_var(
        name=name, shape=shape, dtype=dtype, is_data=True,
        stop_gradient=True, lod_level=lod_level,
    )


@contextlib.contextmanager
def name_scope(prefix):
    """Cosmetic op namespace (parity: fluid.name_scope)."""
    with unique_name.guard(unique_name.generator):
        yield
