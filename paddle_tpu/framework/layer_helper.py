"""LayerHelper — shared plumbing for layer functions.

Parity: /root/reference/python/paddle/fluid/layer_helper.py — creates
temporary output vars, creates parameters in BOTH the main program (as
Parameter) and the startup program (with their init op), and appends
activations.
"""

from . import unique_name
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr
from .program import default_main_program, default_startup_program


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs
        self.name = kwargs.get("name") or unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    def create_variable_for_type_inference(self, dtype, shape=None):
        return self.block.create_var(
            name=unique_name.generate(self.name + ".tmp"),
            dtype=dtype,
            shape=shape,
        )

    def create_parameter(self, attr, shape, dtype="float32", is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        from .param_attr import WeightNormParamAttr

        if isinstance(attr, WeightNormParamAttr):
            # ANY parameter with this attr reparameterizes, bias
            # included (layer_helper_base.py:327)
            return self._create_weight_norm_parameter(
                attr, shape, dtype, default_initializer)
        suffix = "b" if is_bias else "w"
        name = attr.name or unique_name.generate(f"{self.name}.{suffix}")
        init = attr.initializer or default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias else XavierInitializer()

        param = self.main_program.global_block().create_parameter(
            name=name, shape=shape, dtype=dtype,
            trainable=attr.trainable, regularizer=attr.regularizer,
            initializer=init,
        )
        param.optimize_attr = {"learning_rate": attr.learning_rate}
        # mirror into startup program with its init op
        sb = self.startup_program.global_block()
        if name not in sb.vars:
            sp = sb.create_parameter(
                name=name, shape=shape, dtype=dtype, trainable=attr.trainable,
            )
            init(sp, sb)
        return param

    def _create_weight_norm_parameter(self, attr, shape, dtype,
                                      default_initializer=None):
        """WeightNormParamAttr reparameterization (layer_helper_base.py
        create_parameter with a WeightNormParamAttr): the layer's weight
        becomes w = g * v / ||v||, with g/v the trainable parameters and
        the norm taken over every axis EXCEPT attr.dim (dim=None -> full
        tensor norm), recomputed inside the program each step.  g is
        initialized to ||v|| in the startup program, so w == v at step 0
        exactly like the reference."""
        from .param_attr import ParamAttr as _PA

        base = attr.name or unique_name.generate(f"{self.name}.w")
        inner = _PA(name=base + "_v", initializer=attr.initializer,
                    learning_rate=attr.learning_rate,
                    regularizer=attr.regularizer, trainable=attr.trainable)
        v = self.create_parameter(inner, shape, dtype=dtype,
                                  default_initializer=default_initializer)
        dim = attr.dim
        if dim is not None:
            dim = dim % len(shape)          # negative dims normalize
        # g keeps the weight's rank: shape[dim] on dim, 1 elsewhere
        # (layer_helper_base.py:232-234) — checkpoints match by shape
        g_shape = [1] * len(shape)
        if dim is not None:
            g_shape[dim] = shape[dim]
        g_attr = _PA(name=base + "_g", learning_rate=attr.learning_rate,
                     regularizer=attr.regularizer,
                     trainable=attr.trainable,
                     initializer=ConstantInitializer(1.0))
        g = self.create_parameter(g_attr, g_shape, dtype=dtype)

        axes = ([a for a in range(len(shape)) if a != dim]
                if dim is not None else list(range(len(shape))))

        def norm_ops(block, v_name, out_name, keep_dim):
            sq = unique_name.generate(base + ".wn_sq")
            block.create_var(name=sq, dtype=dtype)
            block.append_op("square", {"X": [v_name]}, {"Out": [sq]}, {})
            ssum = unique_name.generate(base + ".wn_ss")
            block.create_var(name=ssum, dtype=dtype)
            block.append_op("reduce_sum", {"X": [sq]}, {"Out": [ssum]},
                            {"dim": axes, "keep_dim": keep_dim})
            block.append_op("sqrt", {"X": [ssum]}, {"Out": [out_name]},
                            {})

        # startup: g = ||v||, making the initial effective weight equal v
        sb = self.startup_program.global_block()
        raw = unique_name.generate(base + ".wn_g0")
        sb.create_var(name=raw, dtype=dtype)
        norm_ops(sb, v.name, raw, keep_dim=True)
        sb.append_op("reshape2", {"X": [raw]}, {"Out": [g.name]},
                     {"shape": list(g_shape)})

        # main program: w = g * v / ||v|| recomputed per step; g is
        # rank-preserved so plain -1 broadcasting applies throughout
        norm = self.create_variable_for_type_inference(dtype)
        norm_ops(self.main_program.global_block(), v.name, norm.name,
                 keep_dim=True)
        unit = self.create_variable_for_type_inference(dtype)
        self.append_op("elementwise_div", {"X": v, "Y": norm},
                       {"Out": unit}, {"axis": -1})
        w = self.create_variable_for_type_inference(dtype)
        self.append_op("elementwise_mul", {"X": unit, "Y": g},
                       {"Out": w}, {"axis": -1})
        w.shape = list(shape)
        return w

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        return self.block.append_op(type, inputs, outputs, attrs)

    def append_activation(self, out, act):
        if act is None:
            return out
        tmp = self.create_variable_for_type_inference(out.dtype,
                                                      shape=out.shape)
        self.append_op(act, inputs={"X": out}, outputs={"Out": tmp})
        return tmp

    def input_dtype(self, var):
        return var.dtype or "float32"
