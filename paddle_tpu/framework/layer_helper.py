"""LayerHelper — shared plumbing for layer functions.

Parity: /root/reference/python/paddle/fluid/layer_helper.py — creates
temporary output vars, creates parameters in BOTH the main program (as
Parameter) and the startup program (with their init op), and appends
activations.
"""

from . import unique_name
from .initializer import ConstantInitializer, XavierInitializer
from .param_attr import ParamAttr
from .program import default_main_program, default_startup_program


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.layer_type = layer_type
        self.kwargs = kwargs
        self.name = kwargs.get("name") or unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    def create_variable_for_type_inference(self, dtype, shape=None):
        return self.block.create_var(
            name=unique_name.generate(self.name + ".tmp"),
            dtype=dtype,
            shape=shape,
        )

    def create_parameter(self, attr, shape, dtype="float32", is_bias=False,
                         default_initializer=None):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        suffix = "b" if is_bias else "w"
        name = attr.name or unique_name.generate(f"{self.name}.{suffix}")
        init = attr.initializer or default_initializer
        if init is None:
            init = ConstantInitializer(0.0) if is_bias else XavierInitializer()

        param = self.main_program.global_block().create_parameter(
            name=name, shape=shape, dtype=dtype,
            trainable=attr.trainable, regularizer=attr.regularizer,
            initializer=init,
        )
        param.optimize_attr = {"learning_rate": attr.learning_rate}
        # mirror into startup program with its init op
        sb = self.startup_program.global_block()
        if name not in sb.vars:
            sp = sb.create_parameter(
                name=name, shape=shape, dtype=dtype, trainable=attr.trainable,
            )
            init(sp, sb)
        return param

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        return self.block.append_op(type, inputs, outputs, attrs)

    def append_activation(self, out, act):
        if act is None:
            return out
        tmp = self.create_variable_for_type_inference(out.dtype,
                                                      shape=out.shape)
        self.append_op(act, inputs={"X": out}, outputs={"Out": tmp})
        return tmp

    def input_dtype(self, var):
        return var.dtype or "float32"
