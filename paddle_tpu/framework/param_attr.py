"""ParamAttr — parameter configuration.

Parity: /root/reference/python/paddle/fluid/param_attr.py (ParamAttr,
WeightNormParamAttr is deferred).
"""


class ParamAttr:
    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if arg is False:
            return False
        from .initializer import Initializer

        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")


class WeightNormParamAttr(ParamAttr):
    """param_attr.py:187 — triggers the w = g * v / ||v||
    reparameterization in LayerHelper.create_parameter
    (layer_helper_base.py:87 parity): parameters become name_v / name_g
    (g rank-preserved, size shape[dim] on `dim`, singletons elsewhere),
    with g initialized to ||v|| so the step-0 weight equals v."""

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.dim = dim
