"""Static-graph autodiff API.

Parity: /root/reference/python/paddle/fluid/backward.py — `append_backward`
(:1145), `gradients` (:1678), recompute checkpoints (:623).

The reference walks forward ops in reverse querying C++ grad-op makers and
appends explicit grad ops.  Here gradients come from JAX: append_backward
records a BackwardSection marker; the Executor realizes it with
jax.value_and_grad over the forward segment (one fused XLA computation
instead of a grad-op chain).  `<name>@GRAD` variables are still materialized
in the block so downstream ops (optimizers, clipping, collectives) compose
exactly like the reference.
"""

from .program import BackwardSection, Parameter


def _grad_name(name):
    return name + "@GRAD"


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    checkpoints=None):
    """Mark backward computation for `loss`; returns [(param, grad_var)].

    checkpoints: list of Variables/names marking recompute boundaries
    (parity with RecomputeOptimizer / _append_backward_ops_with_checkpoints_).
    """
    program = loss.block.program
    block = program.global_block()
    no_grad = {v.name if hasattr(v, "name") else v for v in (no_grad_set or ())}

    if parameter_list is not None:
        params = [p.name if hasattr(p, "name") else p for p in parameter_list]
    else:
        params = [
            p.name for p in program.all_parameters()
            if getattr(p, "trainable", True)
        ]
    params = [p for p in params if p not in no_grad]

    ckpt_names = [c.name if hasattr(c, "name") else c
                  for c in (checkpoints or ())]

    pos = len(block.ops)
    program.backward_sections.append(
        BackwardSection(pos, loss.name, params, no_grad, ckpt_names)
    )
    # a backward section changes the compiled step even when every @GRAD
    # var already exists, so the run-plan/compiled caches must see it
    program._bump()

    result = []
    for pname in params:
        pv = block.var(pname)
        gname = _grad_name(pname)
        if gname not in block.vars:
            g = block.create_var(name=gname, shape=pv.shape, dtype=pv.dtype,
                                 stop_gradient=True)
        else:
            g = block.vars[gname]
        result.append((pv, g))
    return result


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Grads of targets w.r.t. inputs (parity: backward.py:1678
    calc_gradient): d(sum_i <targets[i], target_gradients[i] or 1>)/d(inputs).

    inputs must be variables live *before* the backward position (feed data
    or parameters) — intermediate activations inside the differentiated
    segment are not addressable, mirroring the jax functional model.
    """
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is not None and not isinstance(
            target_gradients, (list, tuple)):
        target_gradients = [target_gradients]
    program = targets[0].block.program
    block = program.global_block()
    if len(targets) > 1 or target_gradients is not None:
        # reference calc_gradient semantics: d(sum_i <target_i, tg_i>)/d(x)
        # with tg defaulting to ones.  Synthesize the weighted-sum scalar in
        # the block so ONE BackwardSection covers all targets (XLA fuses the
        # whole reverse sweep either way).
        parts = []
        for i, tgt in enumerate(targets):
            term = tgt
            tg = (target_gradients[i]
                  if target_gradients and i < len(target_gradients) else None)
            if tg is not None:
                mul = block.create_var(
                    name=f"{tgt.name}@weighted_{i}", shape=tgt.shape,
                    dtype=tgt.dtype, stop_gradient=False)
                block.append_op("elementwise_mul",
                                inputs={"X": tgt, "Y": tg},
                                outputs={"Out": mul}, attrs={"axis": -1})
                term = mul
            red = block.create_var(
                name=f"{tgt.name}@grad_sum_{i}", shape=[1],
                dtype=tgt.dtype, stop_gradient=False)
            block.append_op("reduce_sum", inputs={"X": term},
                            outputs={"Out": red},
                            attrs={"reduce_all": True, "keep_dim": False})
            parts.append(red)
        if len(parts) > 1:
            loss = block.create_var(
                name=f"{targets[0].name}@combined_target", shape=[1],
                dtype=targets[0].dtype, stop_gradient=False)
            block.append_op("sum", inputs={"X": parts},
                            outputs={"Out": loss})
        else:
            loss = parts[0]
    else:
        loss = targets[0]
    names = [v.name if hasattr(v, "name") else v for v in inputs]
    pos = len(block.ops)
    program.backward_sections.append(
        BackwardSection(pos, loss.name, names, no_grad_set)
    )
    program._bump()
    grads = []
    for n in names:
        v = block.var(n)
        g = block.create_var(name=_grad_name(n), shape=v.shape, dtype=v.dtype,
                             stop_gradient=True)
        grads.append(g)
    return grads
