"""Parameter initializers.

Parity: /root/reference/python/paddle/fluid/initializer.py — each
initializer appends an init op for a parameter into the *startup program*
(ConstantInitializer, UniformInitializer, NormalInitializer,
TruncatedNormalInitializer, XavierInitializer, MSRAInitializer,
NumpyArrayInitializer).
"""

import math

import numpy as np


class Initializer:
    def __call__(self, param, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, param, block):
        block.append_op(
            "fill_constant",
            outputs={"Out": param.name},
            attrs={"shape": list(param.shape), "dtype": param.dtype,
                   "value": float(self.value)},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, param, block):
        block.append_op(
            "uniform_random",
            outputs={"Out": param.name},
            attrs={"shape": list(param.shape), "dtype": param.dtype,
                   "min": self.low, "max": self.high, "seed": self.seed},
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, param, block):
        block.append_op(
            "gaussian_random",
            outputs={"Out": param.name},
            attrs={"shape": list(param.shape), "dtype": param.dtype,
                   "mean": self.loc, "std": self.scale, "seed": self.seed},
        )


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, param, block):
        block.append_op(
            "truncated_gaussian_random",
            outputs={"Out": param.name},
            attrs={"shape": list(param.shape), "dtype": param.dtype,
                   "mean": self.loc, "std": self.scale, "seed": self.seed},
        )


def _fan_in_out(shape):
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
    fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
    return fan_in, fan_out


class XavierInitializer(Initializer):
    """Glorot init (initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform = uniform
        self.fan_in, self.fan_out = fan_in, fan_out
        self.seed = seed

    def __call__(self, param, block):
        fi, fo = _fan_in_out(param.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(param, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(param, block)


class MSRAInitializer(Initializer):
    """Kaiming/He init (initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform = uniform
        self.fan_in = fan_in
        self.seed = seed

    def __call__(self, param, block):
        fi, _ = _fan_in_out(param.shape)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(param, block)
        else:
            std = math.sqrt(2.0 / fi)
            NormalInitializer(0.0, std, self.seed)(param, block)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, param, block):
        block.append_op(
            "assign_value",
            outputs={"Out": param.name},
            attrs={"shape": list(self.value.shape), "dtype": param.dtype,
                   "fp32_values": self.value.astype(np.float32).flatten().tolist()},
        )


class BilinearInitializer(Initializer):
    """Bilinear-upsample kernel init for transposed conv weights
    (parity: reference initializer.py BilinearInitializer :766-775) —
    a Conv2DTranspose with this weight, stride s, kernel 2s-s%2 and
    groups=C performs bilinear interpolation.  Weight shape must be
    [C_in, f_out, H, W] with H == W."""

    def __call__(self, param, block):
        shape = list(param.shape)
        if len(shape) != 4:
            raise ValueError(
                f"BilinearInitializer needs a 4-D weight, got {shape}")
        if shape[2] != shape[3]:
            raise ValueError(
                f"BilinearInitializer needs a square kernel, got {shape}")
        k = shape[3]
        # exactly the reference's formula: f = ceil(k/2),
        # c = (2f - 1 - f%2) / (2f); the center's half-pixel shift keys
        # on the parity of f, NOT of k (review catch — they differ for
        # k % 4 in {2, 3})
        f = (k + 1) // 2
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        og = np.ogrid[:k, :k]
        filt = ((1 - np.abs(og[0] / f - c))
                * (1 - np.abs(og[1] / f - c)))
        # each (in-channel, out-filter) slot gets the same bilinear
        # kernel; emission delegates to NumpyArrayInitializer so the
        # assign_value encoding lives once
        weight = np.broadcast_to(filt, shape).astype(np.float32)
        NumpyArrayInitializer(weight)(param, block)


# Reference-compatible aliases
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
Bilinear = BilinearInitializer
MSRA = MSRAInitializer
