"""`fluid.data_feeder` import-path compatibility.

Parity: python/paddle/fluid/data_feeder.py — implementation in
reader/__init__.py.
"""

from .reader import DataFeeder  # noqa: F401

__all__ = ["DataFeeder"]
