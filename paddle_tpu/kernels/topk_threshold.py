"""Pallas TPU top-k threshold kernel for DGC gradient sparsification.

Parity target: the reference's DGC sparse-allreduce path
(/root/reference/paddle/fluid/framework/details/sparse_all_reduce_op_handle.cc
+ the external dgc library's CUDA top-k). A full sort (lax.top_k) is
O(N log N) and HBM-heavy at gradient sizes; DGC itself only needs a
THRESHOLD approximating the kth largest |g| (the paper samples gradients
to estimate it). This kernel computes a cumulative histogram of |x|
against 256 linear edges in one streaming pass — each grid step loads a
tile into VMEM and emits per-tile counts of |x| >= edge on the VPU; XLA
sums the [tiles, 256] partials and the threshold is the largest edge
keeping >= k elements. Guarantees kept_count >= k (conservative: the
bin containing the true kth value is kept whole), with one data pass
instead of a sort.

On non-TPU backends the kernel runs in interpret mode (numerics tests).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NUM_EDGES = 256
DEFAULT_BLOCK = 64 * 1024


def _interpret():
    from .backend import is_tpu_backend

    return not is_tpu_backend()


def _count_ge_kernel(x_ref, edges_ref, out_ref):
    # input is already |x|; padding is -1 so it never crosses an edge
    a = x_ref[...].astype(jnp.float32)                   # [block]
    edges = edges_ref[...]                               # [NUM_EDGES]
    # cumulative histogram: count of |x| >= edge, per edge
    ge = (a[:, None] >= edges[None, :]).astype(jnp.float32)
    out_ref[...] = jnp.sum(ge, axis=0)[None, :]          # [1, NUM_EDGES]


@functools.partial(jax.jit, static_argnames=("block",))
def count_ge_histogram(flat_abs, edges, block=DEFAULT_BLOCK):
    """[N] |values| + [NUM_EDGES] edges -> [NUM_EDGES] counts of
    |x| >= edge, via a tiled one-pass Pallas reduction."""
    n = flat_abs.shape[0]
    pad = (-n) % block
    x = jnp.pad(flat_abs, (0, pad), constant_values=-1.0)  # pads count 0
    tiles = x.shape[0] // block
    partials = pl.pallas_call(
        _count_ge_kernel,
        grid=(tiles,),
        in_specs=[
            pl.BlockSpec((block,), lambda i: (i,)),
            pl.BlockSpec((NUM_EDGES,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((1, NUM_EDGES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((tiles, NUM_EDGES), jnp.float32),
        interpret=_interpret(),
    )(x, edges)
    return partials.sum(axis=0)


def topk_threshold(v, k, block=DEFAULT_BLOCK):
    """Approximate kth-largest |v|: the largest histogram edge that keeps
    at least k elements. mask = |v| >= threshold keeps >= k elements
    (within one 1/256 bin of exactly k)."""
    flat = jnp.abs(v.reshape(-1)).astype(jnp.float32)
    vmax = jnp.max(flat)
    edges = jnp.linspace(0.0, 1.0, NUM_EDGES, dtype=jnp.float32) \
        * jnp.maximum(vmax, 1e-30)
    counts = count_ge_histogram(flat, edges, block=block)
    keep_ok = counts >= k                                 # monotone in -edge
    # the largest edge index still keeping >= k elements
    idx = jnp.max(jnp.where(keep_ok, jnp.arange(NUM_EDGES), 0))
    return edges[idx]


def dgc_topk_mask_pallas(v, sparsity, block=DEFAULT_BLOCK):
    """DGC keep-mask via the streaming threshold kernel: keeps the
    largest ~(1-sparsity) fraction of |v| (always >= the exact k)."""
    n = v.size
    k = max(1, int(round(n * (1.0 - sparsity))))
    t = topk_threshold(v, k, block=block)
    return (jnp.abs(v) >= t).astype(v.dtype)
