"""TPU kernels: Pallas implementations of the hot fused ops.

The native-kernel tier of the framework — the analogue of the reference's
hand-written CUDA fused ops (/root/reference/paddle/fluid/operators/fused/)
and math library (operators/math/), rebuilt as Pallas/Mosaic kernels with
XLA fallbacks.
"""

from . import attention  # noqa: F401
