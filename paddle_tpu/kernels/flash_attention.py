"""Pallas TPU flash attention (forward + custom-VJP backward).

The native-kernel tier of the attention stack: replaces the reference's
hand-fused CUDA attention (/root/reference/paddle/fluid/operators/fused/
multihead_matmul_op.cu, operators/math/bert_encoder_functor.cu) with an
online-softmax tiled kernel that never materialises the [S, S] score
matrix in HBM.

Structure (canonical TPU pipelining shape): the grid is
(batch*heads, q blocks, k blocks) with the k axis innermost and marked
"arbitrary" so Mosaic double-buffers the k/v block DMAs against compute.
Softmax statistics (running max m, running sum l) and the output
accumulator live in VMEM scratch that persists across the k steps of one
q block; the causal triangle prunes dead (qi, ki) tiles with pl.when.
Matmuls run in the input dtype (bf16 → full-rate MXU) accumulating f32
via preferred_element_type.

Backward recomputes scores blockwise from the saved logsumexp (no S×S
residual): one kernel for dq (grid k-innermost) and one for dk/dv (grid
q-innermost) — the flash-attention-2 decomposition.

On non-TPU backends the same kernels run in interpret mode, which is how
tests/test_flash_attention.py checks numerics vs the XLA composition.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific pieces; absent on CPU-only builds
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

# 1024x1024 tiles: measured fastest on v5e (r4 flash_block_ab2,
# b8 h16 s2048 d64 fwd+bwd chained): 512x512 17.48ms, 1024x512 16.62,
# 2048x512 17.07, 1024x1024 14.80 (2048x1024 fails to compile).  The
# f32 score block is 4 MB — fits Mosaic's default 16MB scoped budget
# (this file sets no vmem_limit_bytes, unlike fused_bottleneck); shorter
# k loops beat the extra DMA overlap the 512 tiling bought.  Override
# per-call via flash_attention(block_q=..., block_k=...) or globally
# via PADDLE_TPU_FLASH_BLOCK=<q>x<k> for on-chip A/B runs.
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024
_LANES = 128
NEG_INF = -1e30


def _env_blocks():
    import os

    v = os.environ.get("PADDLE_TPU_FLASH_BLOCK")
    if not v:
        return None
    try:
        bq, _, bk = v.partition("x")
        return int(bq), int(bk or bq)
    except ValueError:
        raise ValueError(
            f"PADDLE_TPU_FLASH_BLOCK={v!r} is malformed; expected "
            f"'<block_q>x<block_k>' (e.g. 512x512) or a single size"
        ) from None


def _vmem_spec(*args):
    if _VMEM is None:
        return pl.BlockSpec(*args)
    return pl.BlockSpec(*args, memory_space=_VMEM)


def _scratch(shape, dtype=jnp.float32):
    if pltpu is not None:
        return pltpu.VMEM(shape, dtype)
    raise RuntimeError("pallas TPU backend unavailable")  # pragma: no cover


def _compiler_params():
    if pltpu is None:  # pragma: no cover
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


def _interpret():
    from .backend import is_tpu_backend

    return not is_tpu_backend()


def _causal_mask(s, qi, ki, block_q, block_k):
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, sm_scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _tile(masked):
        q = q_ref[0]                                      # [bq, d] native
        k_blk = k_ref[0]                                  # [bk, d]
        v_blk = v_ref[0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [bq, bk] f32
        if masked:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        m_prev = m_ref[:, 0]                              # [bq]
        l_prev = l_ref[:, 0]
        m_cur = jnp.maximum(m_prev, s.max(axis=-1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_cur = l_prev * alpha + p.sum(axis=-1)
        acc_ref[:] = acc_ref[:] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_cur[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_cur[:, None], l_ref.shape)

    if causal:
        # only tiles straddling the diagonal pay the iota/mask passes;
        # tiles fully below it run the unmasked fast path
        live = (qi + 1) * block_q > ki * block_k
        full = qi * block_q >= (ki + 1) * block_k

        @pl.when(live & full)
        def _fast():
            _tile(masked=False)

        @pl.when(live & jnp.logical_not(full))
        def _diag():
            _tile(masked=True)
    else:
        _tile(masked=False)

    @pl.when(ki == num_k - 1)
    def _finalize():
        l = l_ref[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe[:, None]).astype(o_ref.dtype)
        # stats get a trailing singleton axis: TPU block shapes need the
        # last two dims (8,128)-aligned or equal to the array dims
        lse_ref[0] = (m_ref[:, 0] + jnp.log(l_safe))[:, None]


def _fwd(q, k, v, sm_scale, causal, block_q, block_k):
    b, h, s, d = q.shape
    grid = (b * h, s // block_q, s // block_k)
    q3 = q.reshape(b * h, s, d)
    k3 = k.reshape(b * h, s, d)
    v3 = v.reshape(b * h, s, d)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=grid,
        in_specs=[
            _vmem_spec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            _vmem_spec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            _vmem_spec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        ],
        out_specs=[
            _vmem_spec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            _vmem_spec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, s, 1), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, d)),
            _scratch((block_q, _LANES)),
            _scratch((block_q, _LANES)),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q3, k3, v3)
    return out.reshape(b, h, s, d), lse.reshape(b, h, s)


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc_ref, *, sm_scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    live = ((qi + 1) * block_q > ki * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[0]                                      # [bq, d]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]                            # [bq]
        delta = delta_ref[0][:, 0]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        s = sm_scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse[:, None])                     # [bq, bk]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dq_acc_ref[:] = dq_acc_ref[:] + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k - 1)
    def _finalize():
        dq_ref[0] = dq_acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                    *, sm_scale, causal, block_q, block_k):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    num_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    live = ((qi + 1) * block_q > ki * block_k) if causal else True

    @pl.when(live)
    def _compute():
        k_blk = k_ref[0]                                  # [bk, d]
        v_blk = v_ref[0]
        q = q_ref[0]                                      # [bq, d]
        do = do_ref[0]
        lse = lse_ref[0][:, 0]
        delta = delta_ref[0][:, 0]
        s = sm_scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        if causal:
            s = _causal_mask(s, qi, ki, block_q, block_k)
        p = jnp.exp(s - lse[:, None])
        dv_acc_ref[:] = dv_acc_ref[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * sm_scale
        dk_acc_ref[:] = dk_acc_ref[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _bwd(sm_scale, causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    do = g
    # delta = rowsum(dO * O), [b,h,s] — plain XLA, fuses into one pass
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    return _bwd_core(sm_scale, causal, block_q, block_k, q, k, v, do,
                     lse, delta)


def _bwd_core(sm_scale, causal, block_q, block_k, q, k, v, do, lse,
              delta):
    """Shared FA-2 backward given a precomputed delta row vector.

    The (out, lse)-output variant folds its lse cotangent in here:
    ds = p*(dp - delta + dlse) = p*(dp - (delta - dlse)), so the caller
    just passes delta - dlse and the kernels stay byte-identical."""
    b, h, s, d = q.shape
    q3 = q.reshape(b * h, s, d)
    k3 = k.reshape(b * h, s, d)
    v3 = v.reshape(b * h, s, d)
    do3 = do.reshape(b * h, s, d)
    lse3 = lse.reshape(b * h, s, 1)
    delta3 = delta.reshape(b * h, s, 1)

    grid_dq = (b * h, s // block_q, s // block_k)
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=grid_dq,
        in_specs=[
            _vmem_spec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            _vmem_spec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            _vmem_spec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
            _vmem_spec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
            _vmem_spec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
            _vmem_spec((1, block_q, 1), lambda bh, qi, ki: (bh, qi, 0)),
        ],
        out_specs=_vmem_spec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, s, d), q.dtype),
        scratch_shapes=[_scratch((block_q, d))],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse3, delta3)

    grid_kv = (b * h, s // block_k, s // block_q)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=grid_kv,
        in_specs=[
            _vmem_spec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)),
            _vmem_spec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            _vmem_spec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            _vmem_spec((1, block_q, d), lambda bh, ki, qi: (bh, qi, 0)),
            _vmem_spec((1, block_q, 1), lambda bh, ki, qi: (bh, qi, 0)),
            _vmem_spec((1, block_q, 1), lambda bh, ki, qi: (bh, qi, 0)),
        ],
        out_specs=[
            _vmem_spec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
            _vmem_spec((1, block_k, d), lambda bh, ki, qi: (bh, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, s, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, s, d), v.dtype),
        ],
        scratch_shapes=[_scratch((block_k, d)), _scratch((block_k, d))],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(q3, k3, v3, do3, lse3, delta3)

    return (dq.reshape(b, h, s, d), dk.reshape(b, h, s, d),
            dv.reshape(b, h, s, d))


# --------------------------------------------------------------------------
# single-query decode forward (ISSUE 17)
# --------------------------------------------------------------------------

def _decode_compiler_params():
    if pltpu is None:  # pragma: no cover
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary"))


def _smem_spec(*args):
    if pltpu is None:  # pragma: no cover
        return pl.BlockSpec(*args)
    return pl.BlockSpec(*args, memory_space=pltpu.SMEM)


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref,
                   l_ref, *, sm_scale, block_k):
    ki = pl.program_id(1)
    num_k = pl.num_programs(1)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = len_ref[0, 0]

    # k blocks entirely past the live prefix contribute nothing; skip
    # their DMA'd compute outright (the ragged-length win: a slot at
    # pos 40 in a 2048-deep cache touches 1 block, not 16)
    @pl.when(ki * block_k < length)
    def _tile():
        q = q_ref[0]                                      # [1, d]
        k_blk = k_ref[0]                                  # [bk, d]
        v_blk = v_ref[0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale  # [1, bk] f32
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(k_pos < length, s, NEG_INF)
        m_prev = m_ref[0, 0]
        l_prev = l_ref[0, 0]
        m_cur = jnp.maximum(m_prev, s.max())
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)
        l_ref[:] = jnp.broadcast_to(l_prev * alpha + p.sum(), l_ref.shape)
        acc_ref[0:1] = acc_ref[0:1] * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_cur, m_ref.shape)

    @pl.when(ki == num_k - 1)
    def _finalize():
        l = l_ref[0, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[0:1] / l_safe).astype(o_ref.dtype)


def flash_decode(q, k, v, lengths, sm_scale=None, block_k=None):
    """Single-query flash attention for the decode phase.

    q: [B, H, 1, D] (one new token per row), k/v: [B, H, T, D] (the KV
    cache), lengths: int32 [B] or scalar — live prefix length per row
    (pos + 1); cache positions >= length are masked out.  The grid is
    (B*H, T//block_k) with the k axis "arbitrary" so the running
    (m, l, acc) online-softmax state persists across k blocks, and
    blocks past the live prefix are pruned with pl.when — cost scales
    with the ragged lengths, not the cache depth.  T must be divisible
    by block_k (auto-shrunk power of two <= 512)."""
    b, h, q_len, d = q.shape
    if q_len != 1:
        raise ValueError(f"flash_decode needs q_len == 1, got {q_len}")
    t = k.shape[-2]
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(d)
    if block_k is None:
        cand = 512
        while cand > 64 and (cand > t or t % cand):
            cand //= 2
        block_k = cand if (cand <= t and t % cand == 0) else t
    if t % block_k:
        raise ValueError(
            f"cache depth {t} must be divisible by block_k {block_k}")
    lengths = jnp.broadcast_to(
        jnp.asarray(lengths, jnp.int32).reshape(-1), (b,))
    len2 = jnp.repeat(lengths, h).reshape(b * h, 1)
    q3 = q.reshape(b * h, 1, d)
    k3 = k.reshape(b * h, t, d)
    v3 = v.reshape(b * h, t, d)
    grid = (b * h, t // block_k)
    out = pl.pallas_call(
        functools.partial(_decode_kernel, sm_scale=float(sm_scale),
                          block_k=block_k),
        grid=grid,
        in_specs=[
            _smem_spec((1, 1), lambda bh, ki: (bh, 0)),
            _vmem_spec((1, 1, d), lambda bh, ki: (bh, 0, 0)),
            _vmem_spec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            _vmem_spec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_specs=_vmem_spec((1, 1, d), lambda bh, ki: (bh, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, 1, d), q.dtype),
        scratch_shapes=[
            # 8-row scratch (f32 sublane tile) though only row 0 is
            # used: sub-tile scratch shapes are not portable on TPU
            _scratch((8, d)),
            _scratch((8, _LANES)),
            _scratch((8, _LANES)),
        ],
        compiler_params=_decode_compiler_params(),
        interpret=_interpret(),
    )(len2, q3, k3, v3)
    return out.reshape(b, h, 1, d)


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, sm_scale, causal, block_q, block_k):
    out, _ = _fwd(q, k, v, sm_scale, causal, block_q, block_k)
    return out


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k)
    return out, (q, k, v, out, lse)


_flash.defvjp(_flash_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_lse(q, k, v, sm_scale, causal, block_q, block_k):
    return _fwd(q, k, v, sm_scale, causal, block_q, block_k)


def _flash_lse_fwd(q, k, v, sm_scale, causal, block_q, block_k):
    out, lse = _fwd(q, k, v, sm_scale, causal, block_q, block_k)
    return (out, lse), (q, k, v, out, lse)


def _flash_lse_bwd(sm_scale, causal, block_q, block_k, res, g):
    q, k, v, out, lse = res
    do, dlse = g
    # dlse rides the same kernels: ds gains +p*dlse, i.e. delta -> delta
    # - dlse (see _bwd_core)
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1) - dlse.astype(jnp.float32)
    return _bwd_core(sm_scale, causal, block_q, block_k, q, k, v, do,
                     lse, delta)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def _resolve(q, sm_scale, block_q, block_k):
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    s = q.shape[-2]

    def _auto_block(default):
        # largest power-of-two tile <= default that divides seq, so any
        # 128-multiple seq (1920, 2176, ...) gets a valid tiling; the
        # ladder always descends to 64 regardless of where the default
        # starts (raising the default to 1024 must not lift the floor —
        # a seq divisible by 64 but not 128 would otherwise fall back
        # to one full-seq tile and blow the score block's VMEM)
        cand = default
        while cand >= 64:
            if cand <= s and s % cand == 0:
                return cand
            cand //= 2
        return s

    env = _env_blocks()
    dq, dk = env if env else (DEFAULT_BLOCK_Q, DEFAULT_BLOCK_K)
    block_q = block_q or _auto_block(dq)
    block_k = block_k or _auto_block(dk)
    block_q, block_k = min(block_q, s), min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(
            f"seq {s} must be divisible by block sizes ({block_q},{block_k})")
    return float(sm_scale), block_q, block_k


def flash_attention(q, k, v, causal=False, sm_scale=None,
                    block_q=None, block_k=None):
    """Tiled attention over [batch, heads, seq, head_dim] inputs.

    seq must be a multiple of the block sizes (default DEFAULT_BLOCK_Q/
    DEFAULT_BLOCK_K = 1024, auto-shrunk to a power-of-two divisor of
    seq); head_dim should be an MXU-friendly 64/128/256. Returns the same
    shape/dtype as q.
    """
    sm_scale, block_q, block_k = _resolve(q, sm_scale, block_q, block_k)
    return _flash(q, k, v, sm_scale, bool(causal), block_q, block_k)


def flash_attention_with_lse(q, k, v, causal=False, sm_scale=None,
                             block_q=None, block_k=None):
    """flash_attention that ALSO returns the per-row logsumexp
    [batch, heads, seq] (f32), fully differentiable through both
    outputs — the building block for ring attention's (out, lse) block
    combine (distributed/ring_attention.py): partial attentions over kv
    shards merge exactly via softmax-weighted averaging of normalized
    outputs."""
    sm_scale, block_q, block_k = _resolve(q, sm_scale, block_q, block_k)
    return _flash_lse(q, k, v, sm_scale, bool(causal), block_q, block_k)
