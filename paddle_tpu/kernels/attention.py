"""Fused scaled-dot-product attention.

Replaces the reference's fused transformer attention
(/root/reference/paddle/fluid/operators/fused/multihead_matmul_op.cu and
math/bert_encoder_functor.cu) with a TPU-native path: a Pallas
flash-attention kernel (added in kernels/flash_attention.py) for large
sequence lengths, and an XLA-fused softmax(QK^T)V composition otherwise.
"""

import math
import warnings

import jax
import jax.numpy as jnp

NEG_INF = -1e9
# the decode path masks with -1e30 (flash_attention.py's NEG_INF), NOT
# this module's -1e9: models/generate.py's inline decode math always
# used -1e30, and the serving engine's token-exactness contract is that
# decode_attention reproduces it bitwise
DECODE_NEG_INF = -1e30


def _xla_attention(q, k, v, mask, scale, is_causal, dropout_p, training,
                   rng_key):
    # q,k,v: [B, H, S, D]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if is_causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        logits = jnp.where(causal, logits, NEG_INF)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, NEG_INF)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        if rng_key is None:
            from ..nn.parameter import default_rng

            rng_key = default_rng.next_key()
        keep = jax.random.bernoulli(rng_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def decode_attention(q, k, v, pos=None, mask=None, scale=None,
                     use_flash=None):
    """Single-query decode attention: q [B, H, 1, D] against a KV-cache
    prefix k/v [B, H, T, D] -> [B, H, 1, D].

    `pos` is the CURRENT token's cache position — scalar (whole batch at
    one position, models/generate.py's cohort decode) or [B] (per-slot
    ragged positions, the serving engine); cache columns > pos are
    masked.  Alternatively pass an explicit `mask` (bool keeps-where-
    true, else additive) when the live set is not a prefix (the fused-op
    path).  With neither, the full cache is attended (pos = T-1).

    Numerics contract: the XLA path is bitwise the inline decode math
    models/generate.py shipped with (f32 scores, -1e30 masked columns,
    f32 softmax, cast back to q.dtype) — masked columns underflow to
    exactly 0.0 in f32, so padded cache depth never perturbs the live
    sums and cached decode stays token-exact vs a full forward.  The
    flash path (TPU, deep caches) is the online-softmax Pallas kernel in
    flash_attention.py: same math re-associated, allclose not bitwise,
    so the serving engine pins one path per process."""
    import os

    head_dim = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(head_dim)
    t = k.shape[-2]
    if pos is not None and mask is not None:
        raise ValueError("pass pos or mask, not both")
    if pos is None and mask is None:
        pos = t - 1

    can_flash = (mask is None and q.shape[-2] == 1 and t % 128 == 0
                 and head_dim in (64, 128, 256))
    if use_flash is None:
        from .backend import is_tpu_backend

        env = os.environ.get("PADDLE_TPU_FORCE_FLASH_DECODE", "")
        if env:
            use_flash = env.lower() in ("1", "true", "yes")
        else:
            use_flash = is_tpu_backend() and t >= 1024
    if use_flash and can_flash:
        from .flash_attention import flash_decode

        return flash_decode(q, k, v, jnp.asarray(pos, jnp.int32) + 1,
                            sm_scale=scale)

    if mask is None:
        pos_arr = jnp.asarray(pos, jnp.int32)
        idx = jnp.arange(t, dtype=jnp.int32)
        if pos_arr.ndim == 0:
            live = (idx <= pos_arr)[None, None, None, :]
        else:                                   # [B] per-row positions
            live = (idx[None, :] <= pos_arr[:, None])[:, None, None, :]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k.astype(q.dtype)) * scale
    if mask is None:
        s = jnp.where(live, s.astype(jnp.float32), DECODE_NEG_INF)
    elif mask.dtype == jnp.bool_:
        s = jnp.where(mask, s.astype(jnp.float32), DECODE_NEG_INF)
    else:
        s = s.astype(jnp.float32) + mask
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(q.dtype))


def dot_product_attention(q, k, v, mask=None, dropout_p=0.0, is_causal=False,
                          scale=None, training=True, rng_key=None,
                          use_flash=None):
    """q/k/v: [batch, heads, seq, head_dim] -> [batch, heads, seq, head_dim]."""
    head_dim = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(head_dim)

    # the flash kernel supports neither arbitrary masks nor in-kernel
    # dropout, and needs self-attention shapes with block-aligned seq;
    # anything else must take the XLA path even if the caller forced
    # use_flash=True (silent wrong numerics otherwise)
    seq = q.shape[-2]
    can_flash = (
        (dropout_p == 0.0 or not training)
        and mask is None
        and q.shape[-2] == k.shape[-2]
        and seq % 128 == 0
        and head_dim in (64, 128, 256)
    )
    forced_flash = use_flash is True
    if use_flash is None:
        # Below ~1k tokens XLA's fused softmax(QK^T)V is faster on-chip
        # (the S^2 matrix still fits cache-friendly tiles); flash wins
        # once the S^2 materialisation starts thrashing HBM (measured
        # crossover on v5e: 512 -> XLA, 2048 -> flash by ~20%).
        # PADDLE_TPU_FORCE_FLASH=0/1 overrides the heuristic for
        # on-chip A/B runs (same role as PADDLE_TPU_FLASH_BLOCK).
        import os

        from .backend import is_tpu_backend

        env = os.environ.get("PADDLE_TPU_FORCE_FLASH", "")
        if env:
            use_flash = env.lower() in ("1", "true", "yes")
        else:
            use_flash = (is_tpu_backend() and seq >= 1024)
    if forced_flash and not can_flash:
        warnings.warn(
            "use_flash=True requested but the flash kernel cannot serve this "
            f"call (mask={mask is not None}, dropout={dropout_p}, seq={seq}, "
            f"head_dim={head_dim}; needs no mask, no train-dropout, "
            "self-attention, seq%128==0, head_dim in 64/128/256) — "
            "falling back to the XLA path", stacklevel=2)
    if use_flash and can_flash:
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=is_causal, sm_scale=scale)
    return _xla_attention(q, k, v, mask, scale, is_causal, dropout_p,
                          training, rng_key)
