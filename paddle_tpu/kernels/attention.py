"""Fused scaled-dot-product attention.

Replaces the reference's fused transformer attention
(/root/reference/paddle/fluid/operators/fused/multihead_matmul_op.cu and
math/bert_encoder_functor.cu) with a TPU-native path: a Pallas
flash-attention kernel (added in kernels/flash_attention.py) for large
sequence lengths, and an XLA-fused softmax(QK^T)V composition otherwise.
"""

import math
import warnings

import jax
import jax.numpy as jnp

NEG_INF = -1e9


def _xla_attention(q, k, v, mask, scale, is_causal, dropout_p, training,
                   rng_key):
    # q,k,v: [B, H, S, D]
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if is_causal:
        s_q, s_k = logits.shape[-2], logits.shape[-1]
        causal = jnp.tril(jnp.ones((s_q, s_k), dtype=bool), k=s_k - s_q)
        logits = jnp.where(causal, logits, NEG_INF)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, NEG_INF)
        else:
            logits = logits + mask
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        if rng_key is None:
            from ..nn.parameter import default_rng

            rng_key = default_rng.next_key()
        keep = jax.random.bernoulli(rng_key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def dot_product_attention(q, k, v, mask=None, dropout_p=0.0, is_causal=False,
                          scale=None, training=True, rng_key=None,
                          use_flash=None):
    """q/k/v: [batch, heads, seq, head_dim] -> [batch, heads, seq, head_dim]."""
    head_dim = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(head_dim)

    # the flash kernel supports neither arbitrary masks nor in-kernel
    # dropout, and needs self-attention shapes with block-aligned seq;
    # anything else must take the XLA path even if the caller forced
    # use_flash=True (silent wrong numerics otherwise)
    seq = q.shape[-2]
    can_flash = (
        (dropout_p == 0.0 or not training)
        and mask is None
        and q.shape[-2] == k.shape[-2]
        and seq % 128 == 0
        and head_dim in (64, 128, 256)
    )
    forced_flash = use_flash is True
    if use_flash is None:
        # Below ~1k tokens XLA's fused softmax(QK^T)V is faster on-chip
        # (the S^2 matrix still fits cache-friendly tiles); flash wins
        # once the S^2 materialisation starts thrashing HBM (measured
        # crossover on v5e: 512 -> XLA, 2048 -> flash by ~20%).
        # PADDLE_TPU_FORCE_FLASH=0/1 overrides the heuristic for
        # on-chip A/B runs (same role as PADDLE_TPU_FLASH_BLOCK).
        import os

        from .backend import is_tpu_backend

        env = os.environ.get("PADDLE_TPU_FORCE_FLASH", "")
        if env:
            use_flash = env.lower() in ("1", "true", "yes")
        else:
            use_flash = (is_tpu_backend() and seq >= 1024)
    if forced_flash and not can_flash:
        warnings.warn(
            "use_flash=True requested but the flash kernel cannot serve this "
            f"call (mask={mask is not None}, dropout={dropout_p}, seq={seq}, "
            f"head_dim={head_dim}; needs no mask, no train-dropout, "
            "self-attention, seq%128==0, head_dim in 64/128/256) — "
            "falling back to the XLA path", stacklevel=2)
    if use_flash and can_flash:
        from .flash_attention import flash_attention

        return flash_attention(q, k, v, causal=is_causal, sm_scale=scale)
    return _xla_attention(q, k, v, mask, scale, is_causal, dropout_p,
                          training, rng_key)
