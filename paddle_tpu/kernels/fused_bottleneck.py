"""Pallas TPU fused ResNet bottleneck block (forward + custom-VJP backward).

The conv-net analogue of flash attention: the on-chip roofline of the
ResNet-50 train step (tools/resnet50_ablate.py, r4) showed the step
running at ~100% of v5e HBM bandwidth — 46.7GB of traffic, dominated by
the per-conv materialisation of every intermediate activation of every
bottleneck block.  This kernel computes a whole stride-1 bottleneck
block

    y = relu(a3 * conv1x1(h1, w3) + b3 + shortcut)
    h1 = relu(a2 * conv3x3(h0, w2) + b2)
    h0 = relu(a1 * conv1x1(x, w1) + b1)
    shortcut = x                      (identity variant)
             | a4 * conv1x1(x, w4) + b4   (projection variant)

in one VMEM residency per batch tile: HBM sees one read of x and one
write of y in the forward, and one read of (x, dy) and one write of dx
(plus the tiny weight grads) in the backward, which recomputes
h0/h1/conv outputs on-tile flash-style instead of saving them.

Batch-norm enters as a per-channel affine (a, b): training batch stats
(ghost-batch subsampled, see models/resnet.py) are computed OUTSIDE the
kernel from a small sample slice, so the kernel stays a pure function
of (x, weights, affines) and autodiff composes the stats path for free.

Tiling: the grid is 1-D over batch tiles; each tile carries the FULL
H x W spatial plane so the 3x3 conv needs no halo exchange — the pad
lives in a VMEM scratch.  The 3x3 conv itself is nine shifted
[T*H*W, Cm] x [Cm, Cm] matmuls (all MXU), accumulated in f32 via
preferred_element_type.  Weight/affine grads accumulate in f32 output
blocks revisited by every grid step (index_map -> 0, the standard
matmul-k-loop accumulator pattern; TPU grid steps are sequential).

Replaces the traffic role of the reference's fused conv blocks
(/root/reference/paddle/fluid/operators/fused/conv_fusion_op.cu,
fusion_conv_inception_op.cu) with a design shaped by VMEM/HBM rather
than cuDNN fusion enums.

On non-TPU backends the kernels run in interpret mode;
tests/test_fused_bottleneck.py checks fwd+grad numerics against the
unfused composition.

MEASURED STATUS (honest, r4->r5).  Every kernel variant passes on-chip
fwd+bwd smoke at every ResNet-50 geometry (ONCHIP_QUEUE.log
fused_kernel_smoke3), but the path has NOT yet beaten XLA end-to-end:

- the only full-model fused config measured on chip, the 12-block
  identity subset, was SLOWER than unfused (0.1133 vs 0.1493 MFU at
  b128 ss16, r4 13:04) — hypothesis: the recompute backward trades
  ~2x conv FLOPs for traffic, a good trade only where the block is
  deep in the bandwidth-bound regime (large-spatial stages 1-2), while
  the tiny-spatial stage-3/4 tiles (7^2/14^2 x 1-2k channels) have the
  least im2col reuse and likely pay more compute than they save; the
  r5 `id_early` subset + onchip_queue `resnet_fused_subset_ab`
  experiment tests exactly this split;
- the FULL 16-block program cannot currently be measured at all: the
  axon remote-compile service routes programs with many Mosaic custom
  calls to an AOT helper that dies server-side on a broken
  TPU_WORKER_HOSTNAMES env (three r4 captures lost) — an
  infrastructure ceiling, not a kernel property;
- a full-fused FORWARD compiled in 382.6s (r4 12:55), so compile cost
  alone makes the full path impractical behind the tunnel until the
  persistent cache is warm.

Until a measured config BEATS unfused, the headline bench reports the
XLA path and the fused path stays opt-in (PADDLE_TPU_FUSED_SUBSET,
bench resnet_fused side row).  If id_early also loses, the honest
conclusion is that XLA's conv stack + ghost-BN is already within the
roofline's reach and these kernels are a capability demonstration, not
a perf win.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_VMEM = pltpu.VMEM


def _interpret():
    from .backend import is_tpu_backend

    return not is_tpu_backend()


def _vmem_spec(*args):
    return pl.BlockSpec(*args, memory_space=_VMEM)


def _compiler_params():
    # vmem_limit_bytes raises Mosaic's default 16MB scoped-VMEM budget:
    # at the ResNet-50 stage geometries the kernels' live f32
    # intermediates measure 16.0-28.3MB of scoped allocation (v5e,
    # jax 0.9 — see FUSED_PROBE.log), well under the chip's 128MB VMEM
    # but over the default compiler cap.
    return pltpu.CompilerParams(dimension_semantics=("arbitrary",),
                                vmem_limit_bytes=100 * 1024 * 1024)


def _full_spec(shape):
    """Whole-array block revisited by every grid step."""
    return _vmem_spec(shape, lambda n: (0,) * len(shape))


def default_batch_tile(n, h, w, c, rows_target=12544):
    """Largest divisor of n with t*h*w <= rows_target (~4*56*56 rows:
    VMEM fits the f32 intermediates at stage-1 channel counts and the
    MXU still sees long matmuls)."""
    t = max(1, min(n, rows_target // max(h * w, 1)))
    while n % t:
        t -= 1
    return t


# Mosaic's scoped-VMEM demand is ~(live f32 intermediates) = rows x
# max-channel x 4B x live-count, so a fixed row target that fits
# stage 1 (c=256) wedges the compiler at stage 2+ (c=512..2048): the
# on-chip bisect (FUSED_PROBE.log / ONCHIP_QUEUE.log r4) measured
# s1 compiling in ~20s at rows x c = 12544*256 (fwd) / 6272*256 (bwd)
# while s2's bwd at 6272*512 searched >420s.  Budget row-units
# instead: rows_target = UNITS / max(cin, cout), anchored at the
# proven stage-1 points.
_FWD_ROW_UNITS = 12544 * 256
_BWD_ROW_UNITS = 6272 * 256


def _rows_for(cin, cout, units):
    return max(256, units // max(cin, cout, 1))


def _dot(a, b, dims):
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32)


# The 3x3 conv inside the kernels has two formulations:
#   taps:   nine shifted [R, Cm] x [Cm, Cmo] matmuls — each contracts
#           only Cm (64-512) of the MXU's 128-deep systolic array, so
#           stage-1/2 run the MXU at <=50% depth
#   im2col: ONE [R, 9*Cm] x [9*Cm, Cmo] matmul over a lane-concatenated
#           patch matrix built in VMEM — full MXU depth at every stage,
#           at the cost of a 9x wider VMEM intermediate
# PADDLE_TPU_FUSED_CONV=taps restores the original formulation for
# on-chip A/Bs.  The env var is read at TRACE time and is not part of
# any jit cache key, so it is process-start-only: flipping it after a
# shape has compiled keeps serving the cached executable (A/B drivers
# run each mode in its own process).
def _conv_mode():
    import os

    return os.environ.get("PADDLE_TPU_FUSED_CONV", "im2col")


def _im2col(h0_pad, t, h, wid, cm):
    """Lane-concatenated 3x3 patches of a padded [T, H+2, W+2, Cm]
    tile -> [T*H*W, 9*Cm]."""
    taps = [h0_pad[:, dy:dy + h, dx:dx + wid, :].reshape(t * h * wid, cm)
            for dy in range(3) for dx in range(3)]
    return jnp.concatenate(taps, axis=1)


def _conv3x3(h0_pad, w2, t, h, wid, cm):
    """3x3 conv over a padded [T, H+2, W+2, Cm] tile -> f32
    [T*H*W, Cmo]."""
    if _conv_mode() == "im2col":
        p = _im2col(h0_pad, t, h, wid, cm)
        return _dot(p, w2.reshape(9 * cm, w2.shape[-1]), ((1,), (0,)))
    acc = jnp.zeros((t * h * wid, w2.shape[-1]), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            sl = h0_pad[:, dy:dy + h, dx:dx + wid, :]
            acc += _dot(sl.reshape(t * h * wid, cm), w2[dy, dx],
                        ((1,), (0,)))
    return acc


def _fwd_kernel(x_ref, w1_ref, w2_ref, w3_ref, w4_ref, aff_ref, o_ref,
                h0p_ref, *, t, h, w, cin, cm, cout, proj):
    dt = x_ref.dtype
    x = x_ref[...]                                       # [T,H,W,Cin]
    xm = x.reshape(t * h * w, cin)
    a1, b1 = aff_ref[0, :cm], aff_ref[1, :cm]
    a2, b2 = aff_ref[2, :cm], aff_ref[3, :cm]
    a3, b3 = aff_ref[4, :cout], aff_ref[5, :cout]

    c0 = _dot(xm, w1_ref[...], ((1,), (0,)))
    h0 = jnp.maximum(c0 * a1 + b1, 0.0).astype(dt)       # [R, Cm]
    h0p_ref[...] = jnp.zeros(h0p_ref.shape, h0p_ref.dtype)
    h0p_ref[:, 1:h + 1, 1:w + 1, :] = h0.reshape(t, h, w, cm)
    c1 = _conv3x3(h0p_ref[...], w2_ref[...], t, h, w, cm)
    h1 = jnp.maximum(c1 * a2 + b2, 0.0).astype(dt)
    c2 = _dot(h1, w3_ref[...], ((1,), (0,)))
    if proj:
        a4, b4 = aff_ref[6, :cout], aff_ref[7, :cout]
        s = _dot(xm, w4_ref[...], ((1,), (0,))) * a4 + b4
    else:
        s = xm.astype(jnp.float32)
    pre = c2 * a3 + b3 + s
    o_ref[...] = jnp.maximum(pre, 0.0).astype(dt).reshape(t, h, w, cout)


def _bwd_kernel(x_ref, dy_ref, w1_ref, w2_ref, w3_ref, w4_ref, aff_ref,
                dx_ref, dw1_ref, dw2_ref, dw3_ref, dw4_ref, daff_ref,
                h0p_ref, dc1p_ref, *, t, h, w, cin, cm, cout, proj):
    dt = x_ref.dtype
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw1_ref[...] = jnp.zeros_like(dw1_ref)
        dw2_ref[...] = jnp.zeros_like(dw2_ref)
        dw3_ref[...] = jnp.zeros_like(dw3_ref)
        dw4_ref[...] = jnp.zeros_like(dw4_ref)
        daff_ref[...] = jnp.zeros_like(daff_ref)

    x = x_ref[...]
    xm = x.reshape(t * h * w, cin)
    a1, b1 = aff_ref[0, :cm], aff_ref[1, :cm]
    a2, b2 = aff_ref[2, :cm], aff_ref[3, :cm]
    a3, b3 = aff_ref[4, :cout], aff_ref[5, :cout]
    w1, w2, w3 = w1_ref[...], w2_ref[...], w3_ref[...]

    # ---- recompute forward (flash-style; nothing saved in HBM) ----
    c0 = _dot(xm, w1, ((1,), (0,)))
    u0 = c0 * a1 + b1
    h0 = jnp.maximum(u0, 0.0).astype(dt)
    c0 = c0.astype(dt)                    # residency: f32 copy freed
    h0p_ref[...] = jnp.zeros(h0p_ref.shape, h0p_ref.dtype)
    h0p_ref[:, 1:h + 1, 1:w + 1, :] = h0.reshape(t, h, w, cm)
    im2col = _conv_mode() == "im2col"
    if im2col:
        # build the patch matrix ONCE: the recompute's conv and the
        # dW2 matmul below both consume it (review catch — Mosaic is
        # not guaranteed to CSE it across separate ref reads)
        p = _im2col(h0p_ref[...], t, h, w, cm)
        c1 = _dot(p, w2.reshape(9 * cm, cm), ((1,), (0,)))
    else:
        c1 = _conv3x3(h0p_ref[...], w2, t, h, w, cm)
    u1 = c1 * a2 + b2
    h1 = jnp.maximum(u1, 0.0).astype(dt)
    c1 = c1.astype(dt)
    c2 = _dot(h1, w3, ((1,), (0,)))
    if proj:
        a4, b4 = aff_ref[6, :cout], aff_ref[7, :cout]
        w4 = w4_ref[...]
        c4 = _dot(xm, w4, ((1,), (0,)))
        s = c4 * a4 + b4
        c4 = c4.astype(dt)
    else:
        s = xm.astype(jnp.float32)
    pre = c2 * a3 + b3 + s
    c2 = c2.astype(dt)

    # ---- backward chain ----
    dy = dy_ref[...].reshape(t * h * w, cout).astype(jnp.float32)
    dz3 = jnp.where(pre > 0.0, dy, 0.0)                  # f32 [R,Cout]
    daff_ref[4, :cout] += jnp.sum(dz3 * c2.astype(jnp.float32), axis=0)
    daff_ref[5, :cout] += jnp.sum(dz3, axis=0)
    dc2 = (dz3 * a3).astype(dt)
    dw3_ref[...] += _dot(h1, dc2, ((0,), (0,)))
    dh1 = _dot(dc2, w3, ((1,), (1,)))
    du1 = jnp.where(u1 > 0.0, dh1, 0.0)
    daff_ref[2, :cm] += jnp.sum(du1 * c1.astype(jnp.float32), axis=0)
    daff_ref[3, :cm] += jnp.sum(du1, axis=0)
    dc1 = (du1 * a2).astype(dt)

    # dW2[dy,dx] += shift(h0_pad)^T @ dc1 ; dh0 via transposed taps
    dc1p_ref[...] = jnp.zeros(dc1p_ref.shape, dc1p_ref.dtype)
    dc1p_ref[:, 1:h + 1, 1:w + 1, :] = dc1.reshape(t, h, w, cm)
    if im2col:
        # dW2 = P^T @ dc1 as ONE [9Cm, R] x [R, Cm] matmul (full MXU
        # depth over the big R contraction), reusing the recompute's
        # patch matrix; dh0 is the transposed conv = im2col(dc1p)
        # against the spatially FLIPPED transposed weights
        dw2_ref[...] += _dot(p, dc1, ((0,), (0,))).reshape(dw2_ref.shape)
        pr = _im2col(dc1p_ref[...], t, h, w, cm)
        w2t = jnp.transpose(w2[::-1, ::-1], (0, 1, 3, 2)).reshape(
            9 * cm, cm)
        dh0 = _dot(pr, w2t, ((1,), (0,)))
    else:
        dh0 = jnp.zeros((t * h * w, cm), jnp.float32)
        for dy_ in range(3):
            for dx_ in range(3):
                tap = h0p_ref[:, dy_:dy_ + h, dx_:dx_ + w, :]
                dw2_ref[dy_, dx_] += _dot(tap.reshape(t * h * w, cm), dc1,
                                          ((0,), (0,)))
                # transposed conv: dh0 gathers dc1 at the opposite shift
                rtap = dc1p_ref[:, 2 - dy_:2 - dy_ + h,
                                2 - dx_:2 - dx_ + w, :]
                dh0 += _dot(rtap.reshape(t * h * w, cm), w2[dy_, dx_],
                            ((1,), (1,)))
    du0 = jnp.where(u0 > 0.0, dh0, 0.0)
    daff_ref[0, :cm] += jnp.sum(du0 * c0.astype(jnp.float32), axis=0)
    daff_ref[1, :cm] += jnp.sum(du0, axis=0)
    dc0 = (du0 * a1).astype(dt)
    dw1_ref[...] += _dot(xm, dc0, ((0,), (0,)))
    dx_main = _dot(dc0, w1, ((1,), (1,)))
    if proj:
        daff_ref[6, :cout] += jnp.sum(dz3 * c4.astype(jnp.float32),
                                      axis=0)
        daff_ref[7, :cout] += jnp.sum(dz3, axis=0)
        dc4 = (dz3 * a4).astype(dt)
        dw4_ref[...] += _dot(xm, dc4, ((0,), (0,)))
        dx_res = _dot(dc4, w4, ((1,), (1,)))
    else:
        dx_res = dz3
    dx_ref[...] = (dx_main + dx_res).astype(dt).reshape(t, h, w, cin)


def _pack_affines(affs, width):
    """[8, width] f32 row-packed affine table (rows padded to width;
    rows 6-7 are the projection-shortcut affine, zero for identity)."""
    rows = []
    for v in affs:
        v = v.astype(jnp.float32)
        rows.append(jnp.pad(v, (0, width - v.shape[0]))
                    if v.shape[0] < width else v)
    while len(rows) < 8:
        rows.append(jnp.zeros(width, jnp.float32))
    return jnp.stack(rows)


def _specs(x, dy_shape, w1, w2, w3, w4, aff, t, h, w):
    tile = lambda shape: _vmem_spec(shape, lambda i: (i, 0, 0, 0))
    return ([tile((t, h, w, x.shape[-1]))]
            + ([tile((t, h, w, dy_shape[-1]))] if dy_shape else [])
            + [_full_spec(w1.shape), _full_spec(w2.shape),
               _full_spec(w3.shape), _full_spec(w4.shape),
               _full_spec(aff.shape)])


def _fwd(x, w1, w2, w3, w4, aff, batch_tile, proj):
    n, h, w, cin = x.shape
    cm, cout = w1.shape[1], w3.shape[1]
    t = batch_tile or default_batch_tile(
        n, h, w, max(cin, cout),
        rows_target=_rows_for(cin, cout, _FWD_ROW_UNITS))
    if n % t:
        raise ValueError(f"batch_tile={t} does not divide batch {n}")
    kernel = functools.partial(_fwd_kernel, t=t, h=h, w=w, cin=cin,
                               cm=cm, cout=cout, proj=proj)
    return pl.pallas_call(
        kernel,
        grid=(n // t,),
        in_specs=_specs(x, None, w1, w2, w3, w4, aff, t, h, w),
        out_specs=_vmem_spec((t, h, w, cout), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h, w, cout), x.dtype),
        scratch_shapes=[pltpu.VMEM((t, h + 2, w + 2, cm), x.dtype)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(x, w1, w2, w3, w4, aff)


def _bwd(x, dy, w1, w2, w3, w4, aff, batch_tile, proj):
    n, h, w, cin = x.shape
    cm, cout = w1.shape[1], w3.shape[1]
    # backward holds ~2x the forward's f32 residents; halve the row
    # budget relative to the forward tile
    t = batch_tile or default_batch_tile(
        n, h, w, max(cin, cout),
        rows_target=_rows_for(cin, cout, _BWD_ROW_UNITS))
    if n % t:
        raise ValueError(f"batch_tile={t} does not divide batch {n}")
    kernel = functools.partial(_bwd_kernel, t=t, h=h, w=w, cin=cin,
                               cm=cm, cout=cout, proj=proj)
    scratch = [pltpu.VMEM((t, h + 2, w + 2, cm), x.dtype),
               pltpu.VMEM((t, h + 2, w + 2, cm), x.dtype)]
    tile = lambda c: _vmem_spec((t, h, w, c), lambda i: (i, 0, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(n // t,),
        in_specs=_specs(x, dy.shape, w1, w2, w3, w4, aff, t, h, w),
        out_specs=[tile(cin), _full_spec(w1.shape), _full_spec(w2.shape),
                   _full_spec(w3.shape), _full_spec(w4.shape),
                   _full_spec(aff.shape)],
        out_shape=[
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            jax.ShapeDtypeStruct(w1.shape, jnp.float32),
            jax.ShapeDtypeStruct(w2.shape, jnp.float32),
            jax.ShapeDtypeStruct(w3.shape, jnp.float32),
            jax.ShapeDtypeStruct(w4.shape, jnp.float32),
            jax.ShapeDtypeStruct(aff.shape, jnp.float32),
        ],
        scratch_shapes=scratch,
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(x, dy, w1, w2, w3, w4, aff)


def _dummy_w4(x):
    # identity variant: w4 is never read; minimal aligned placeholder
    return jnp.zeros((8, 128), x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(10,))
def fused_bottleneck(x, w1, w2, w3, a1, b1, a2, b2, a3, b3,
                     batch_tile=None):
    """Identity-shortcut stride-1 bottleneck block, one HBM round-trip.

    x: [N, H, W, Cin] (NHWC); w1: [Cin, Cm]; w2: [3, 3, Cm, Cm];
    w3: [Cm, Cin]; a*/b*: per-channel affines (batch-norm resolved to
    scale/shift by the caller — see models/resnet.py ghost-stats path).
    """
    aff = _pack_affines((a1, b1, a2, b2, a3, b3), x.shape[-1])
    return _fwd(x, w1, w2, w3, _dummy_w4(x), aff, batch_tile, False)


def _vjp_fwd(x, w1, w2, w3, a1, b1, a2, b2, a3, b3, batch_tile):
    aff = _pack_affines((a1, b1, a2, b2, a3, b3), x.shape[-1])
    y = _fwd(x, w1, w2, w3, _dummy_w4(x), aff, batch_tile, False)
    return y, (x, w1, w2, w3, aff, jnp.zeros((0,), a1.dtype))


def _vjp_bwd(batch_tile, res, dy):
    x, w1, w2, w3, aff, atok = res
    cm = w1.shape[1]
    dx, dw1, dw2, dw3, _, daff = _bwd(x, dy, w1, w2, w3, _dummy_w4(x),
                                      aff, batch_tile, False)
    cast = lambda g, ref: g.astype(ref.dtype)
    # daff rows must come back in the primal affine dtype (bf16 models
    # pass bf16 affines; JAX only tolerates the f32 mismatch via a
    # deprecated exception)
    daff = daff.astype(atok.dtype)
    return (dx, cast(dw1, w1), cast(dw2, w2), cast(dw3, w3),
            daff[0, :cm], daff[1, :cm], daff[2, :cm], daff[3, :cm],
            daff[4], daff[5])


fused_bottleneck.defvjp(_vjp_fwd, _vjp_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(13,))
def fused_bottleneck_proj(x, w1, w2, w3, w4, a1, b1, a2, b2, a3, b3,
                          a4, b4, batch_tile=None):
    """Projection-shortcut stride-1 bottleneck block (e.g. ResNet-50
    stage-1 block 0: Cin 64 -> Cout 256 at 56x56, the single most
    traffic-heavy block).  shortcut = a4 * conv1x1(x, w4) + b4."""
    cout = w3.shape[1]
    aff = _pack_affines((a1, b1, a2, b2, a3, b3, a4, b4), cout)
    return _fwd(x, w1, w2, w3, w4, aff, batch_tile, True)


def _vjp_fwd_proj(x, w1, w2, w3, w4, a1, b1, a2, b2, a3, b3, a4, b4,
                  batch_tile):
    cout = w3.shape[1]
    aff = _pack_affines((a1, b1, a2, b2, a3, b3, a4, b4), cout)
    y = _fwd(x, w1, w2, w3, w4, aff, batch_tile, True)
    return y, (x, w1, w2, w3, w4, aff, jnp.zeros((0,), a1.dtype))


def _vjp_bwd_proj(batch_tile, res, dy):
    x, w1, w2, w3, w4, aff, atok = res
    cm = w1.shape[1]
    dx, dw1, dw2, dw3, dw4, daff = _bwd(x, dy, w1, w2, w3, w4, aff,
                                        batch_tile, True)
    cast = lambda g, ref: g.astype(ref.dtype)
    daff = daff.astype(atok.dtype)
    return (dx, cast(dw1, w1), cast(dw2, w2), cast(dw3, w3),
            cast(dw4, w4), daff[0, :cm], daff[1, :cm], daff[2, :cm],
            daff[3, :cm], daff[4], daff[5], daff[6], daff[7])


fused_bottleneck_proj.defvjp(_vjp_fwd_proj, _vjp_bwd_proj)


# ---------------------------------------------------------------------------
# stride-2 transition block (projection shortcut + downsampling conv1)
# ---------------------------------------------------------------------------
#
# All stride-2 access is expressed as parity decomposition — reshape
# [.., 2k, ..] -> [.., k, 2, ..] then static index — so the kernel needs
# no strided memory ops: tap (dy, dx) of the stride-2 3x3 conv reads
# rows dy, dy+2, ... which is parity (dy % 2) offset (dy // 2) of the
# padded plane, and the transposed conv scatters by stacking the four
# output phases and collapsing [Ho, 2] -> H in a plain reshape.


def _tap2(h0p6, dy, dx, ho, wo):
    """Stride-2 tap: h0_pad[:, dy:dy+2*ho:2, dx:dx+2*wo:2, :] via the
    parity-reshaped [T, (H+2)/2, 2, (W+2)/2, 2, Cm] view."""
    ro, pr = divmod(dy, 2)
    co, pc = divmod(dx, 2)
    return h0p6[:, ro:ro + ho, pr, co:co + wo, pc, :]


def _conv3x3_s2(h0p6, w2, t, ho, wo, cm):
    acc = jnp.zeros((t * ho * wo, w2.shape[-1]), jnp.float32)
    for dy in range(3):
        for dx in range(3):
            sl = _tap2(h0p6, dy, dx, ho, wo)
            acc += _dot(sl.reshape(t * ho * wo, cm), w2[dy, dx],
                        ((1,), (0,)))
    return acc


def _fwd_kernel_down(x_ref, w1_ref, w2_ref, w3_ref, w4_ref, aff_ref,
                     o_ref, h0p_ref, *, t, h, w, cin, cm, cout):
    dt = x_ref.dtype
    ho, wo = h // 2, w // 2
    x = x_ref[...]
    xm = x.reshape(t * h * w, cin)
    a1, b1 = aff_ref[0, :cm], aff_ref[1, :cm]
    a2, b2 = aff_ref[2, :cm], aff_ref[3, :cm]
    a3, b3 = aff_ref[4, :cout], aff_ref[5, :cout]
    a4, b4 = aff_ref[6, :cout], aff_ref[7, :cout]

    c0 = _dot(xm, w1_ref[...], ((1,), (0,)))
    h0 = jnp.maximum(c0 * a1 + b1, 0.0).astype(dt)
    h0p_ref[...] = jnp.zeros(h0p_ref.shape, h0p_ref.dtype)
    h0p_ref[:, 1:h + 1, 1:w + 1, :] = h0.reshape(t, h, w, cm)
    h0p6 = h0p_ref[...].reshape(t, (h + 2) // 2, 2, (w + 2) // 2, 2, cm)
    c1 = _conv3x3_s2(h0p6, w2_ref[...], t, ho, wo, cm)
    h1 = jnp.maximum(c1 * a2 + b2, 0.0).astype(dt)
    c2 = _dot(h1, w3_ref[...], ((1,), (0,)))
    # 1x1 stride-2 shortcut reads phase (0, 0) of x
    x6 = x.reshape(t, ho, 2, wo, 2, cin)
    xs2 = x6[:, :, 0, :, 0, :].reshape(t * ho * wo, cin)
    s = _dot(xs2, w4_ref[...], ((1,), (0,))) * a4 + b4
    pre = c2 * a3 + b3 + s
    o_ref[...] = jnp.maximum(pre, 0.0).astype(dt).reshape(t, ho, wo, cout)


def _bwd_kernel_down(x_ref, dy_ref, w1_ref, w2_ref, w3_ref, w4_ref,
                     aff_ref, dx_ref, dw1_ref, dw2_ref, dw3_ref, dw4_ref,
                     daff_ref, h0p_ref, dc1p_ref, *, t, h, w, cin, cm,
                     cout):
    dt = x_ref.dtype
    ho, wo = h // 2, w // 2
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dw1_ref[...] = jnp.zeros_like(dw1_ref)
        dw2_ref[...] = jnp.zeros_like(dw2_ref)
        dw3_ref[...] = jnp.zeros_like(dw3_ref)
        dw4_ref[...] = jnp.zeros_like(dw4_ref)
        daff_ref[...] = jnp.zeros_like(daff_ref)

    x = x_ref[...]
    xm = x.reshape(t * h * w, cin)
    a1, b1 = aff_ref[0, :cm], aff_ref[1, :cm]
    a2, b2 = aff_ref[2, :cm], aff_ref[3, :cm]
    a3, b3 = aff_ref[4, :cout], aff_ref[5, :cout]
    a4, b4 = aff_ref[6, :cout], aff_ref[7, :cout]
    w1, w2, w3, w4 = w1_ref[...], w2_ref[...], w3_ref[...], w4_ref[...]

    # ---- recompute ----
    c0 = _dot(xm, w1, ((1,), (0,)))
    u0 = c0 * a1 + b1
    h0 = jnp.maximum(u0, 0.0).astype(dt)
    c0 = c0.astype(dt)
    h0p_ref[...] = jnp.zeros(h0p_ref.shape, h0p_ref.dtype)
    h0p_ref[:, 1:h + 1, 1:w + 1, :] = h0.reshape(t, h, w, cm)
    h0p6 = h0p_ref[...].reshape(t, (h + 2) // 2, 2, (w + 2) // 2, 2, cm)
    c1 = _conv3x3_s2(h0p6, w2, t, ho, wo, cm)
    u1 = c1 * a2 + b2
    h1 = jnp.maximum(u1, 0.0).astype(dt)
    c1 = c1.astype(dt)
    c2 = _dot(h1, w3, ((1,), (0,)))
    x6 = x.reshape(t, ho, 2, wo, 2, cin)
    xs2 = x6[:, :, 0, :, 0, :].reshape(t * ho * wo, cin)
    c4 = _dot(xs2, w4, ((1,), (0,)))
    pre = c2 * a3 + b3 + (c4 * a4 + b4)
    c2 = c2.astype(dt)
    c4 = c4.astype(dt)

    # ---- backward ----
    dy = dy_ref[...].reshape(t * ho * wo, cout).astype(jnp.float32)
    dz3 = jnp.where(pre > 0.0, dy, 0.0)
    daff_ref[4, :cout] += jnp.sum(dz3 * c2.astype(jnp.float32), axis=0)
    daff_ref[5, :cout] += jnp.sum(dz3, axis=0)
    daff_ref[6, :cout] += jnp.sum(dz3 * c4.astype(jnp.float32), axis=0)
    daff_ref[7, :cout] += jnp.sum(dz3, axis=0)
    dc2 = (dz3 * a3).astype(dt)
    dw3_ref[...] += _dot(h1, dc2, ((0,), (0,)))
    dh1 = _dot(dc2, w3, ((1,), (1,)))
    du1 = jnp.where(u1 > 0.0, dh1, 0.0)
    daff_ref[2, :cm] += jnp.sum(du1 * c1.astype(jnp.float32), axis=0)
    daff_ref[3, :cm] += jnp.sum(du1, axis=0)
    dc1 = (du1 * a2).astype(dt)

    # shortcut grads; dx phase-(0,0) scatter built by phase stacking
    dc4 = (dz3 * a4).astype(dt)
    dw4_ref[...] += _dot(xs2, dc4, ((0,), (0,)))
    dxs = _dot(dc4, w4, ((1,), (1,))).reshape(t, ho, wo, cin)
    zero = jnp.zeros_like(dxs)
    dx_short = jnp.stack(
        [jnp.stack([dxs, zero], axis=3),
         jnp.stack([zero, zero], axis=3)],
        axis=2).reshape(t * h * w, cin)

    # dW2 taps + transposed stride-2 conv via output phases
    dc1p_ref[...] = jnp.zeros(dc1p_ref.shape, dc1p_ref.dtype)
    dc1p_ref[:, 1:ho + 1, 1:wo + 1, :] = dc1.reshape(t, ho, wo, cm)
    for dy_ in range(3):
        for dx_ in range(3):
            tap = _tap2(h0p6, dy_, dx_, ho, wo)
            dw2_ref[dy_, dx_] += _dot(tap.reshape(t * ho * wo, cm), dc1,
                                      ((0,), (0,)))
    # dh0 phase (pr, pc): a tap (dy, dx) contributes to rows of parity
    # pr iff (2i + pr + 1 - dy) is even, i.e. dy ≡ pr+1 (mod 2); row
    # offset in the padded dc1 = 1 + (pr + 1 - dy)//2 (zero-padding
    # absorbs the out-of-range boundary rows)
    phases = []
    for pr in (0, 1):
        rows = []
        for pc in (0, 1):
            acc = jnp.zeros((t * ho * wo, cm), jnp.float32)
            for dy_ in range(3):
                if (dy_ % 2) != (pr + 1) % 2:
                    continue
                for dx_ in range(3):
                    if (dx_ % 2) != (pc + 1) % 2:
                        continue
                    ro = 1 + (pr + 1 - dy_) // 2
                    co = 1 + (pc + 1 - dx_) // 2
                    sl = dc1p_ref[:, ro:ro + ho, co:co + wo, :]
                    acc += _dot(sl.reshape(t * ho * wo, cm),
                                w2[dy_, dx_], ((1,), (1,)))
            rows.append(acc.reshape(t, ho, wo, cm))
        phases.append(rows)
    dh0 = jnp.stack(
        [jnp.stack([phases[0][0], phases[0][1]], axis=3),
         jnp.stack([phases[1][0], phases[1][1]], axis=3)],
        axis=2).reshape(t * h * w, cm)

    du0 = jnp.where(u0 > 0.0, dh0, 0.0)
    daff_ref[0, :cm] += jnp.sum(du0 * c0.astype(jnp.float32), axis=0)
    daff_ref[1, :cm] += jnp.sum(du0, axis=0)
    dc0 = (du0 * a1).astype(dt)
    dw1_ref[...] += _dot(xm, dc0, ((0,), (0,)))
    dx_main = _dot(dc0, w1, ((1,), (1,)))
    dx_ref[...] = (dx_main + dx_short).astype(dt).reshape(t, h, w, cin)


def _fwd_down(x, w1, w2, w3, w4, aff, batch_tile):
    n, h, w, cin = x.shape
    cm, cout = w1.shape[1], w3.shape[1]
    t = batch_tile or default_batch_tile(
        n, h, w, max(cin, cout),
        rows_target=_rows_for(cin, cout, _FWD_ROW_UNITS))
    if n % t:
        raise ValueError(f"batch_tile={t} does not divide batch {n}")
    kernel = functools.partial(_fwd_kernel_down, t=t, h=h, w=w, cin=cin,
                               cm=cm, cout=cout)
    return pl.pallas_call(
        kernel,
        grid=(n // t,),
        in_specs=_specs(x, None, w1, w2, w3, w4, aff, t, h, w),
        out_specs=_vmem_spec((t, h // 2, w // 2, cout),
                             lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h // 2, w // 2, cout),
                                       x.dtype),
        scratch_shapes=[pltpu.VMEM((t, h + 2, w + 2, cm), x.dtype)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(x, w1, w2, w3, w4, aff)


def _bwd_down(x, dy, w1, w2, w3, w4, aff, batch_tile):
    n, h, w, cin = x.shape
    cm, cout = w1.shape[1], w3.shape[1]
    t = batch_tile or default_batch_tile(
        n, h, w, max(cin, cout),
        rows_target=_rows_for(cin, cout, _BWD_ROW_UNITS))
    if n % t:
        raise ValueError(f"batch_tile={t} does not divide batch {n}")
    kernel = functools.partial(_bwd_kernel_down, t=t, h=h, w=w, cin=cin,
                               cm=cm, cout=cout)
    scratch = [pltpu.VMEM((t, h + 2, w + 2, cm), x.dtype),
               pltpu.VMEM((t, h // 2 + 2, w // 2 + 2, cm), x.dtype)]
    tile = lambda hh, ww, c: _vmem_spec((t, hh, ww, c),
                                        lambda i: (i, 0, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(n // t,),
        in_specs=[tile(h, w, cin), tile(h // 2, w // 2, cout),
                  _full_spec(w1.shape), _full_spec(w2.shape),
                  _full_spec(w3.shape), _full_spec(w4.shape),
                  _full_spec(aff.shape)],
        out_specs=[tile(h, w, cin), _full_spec(w1.shape),
                   _full_spec(w2.shape), _full_spec(w3.shape),
                   _full_spec(w4.shape), _full_spec(aff.shape)],
        out_shape=[
            jax.ShapeDtypeStruct(x.shape, x.dtype),
            jax.ShapeDtypeStruct(w1.shape, jnp.float32),
            jax.ShapeDtypeStruct(w2.shape, jnp.float32),
            jax.ShapeDtypeStruct(w3.shape, jnp.float32),
            jax.ShapeDtypeStruct(w4.shape, jnp.float32),
            jax.ShapeDtypeStruct(aff.shape, jnp.float32),
        ],
        scratch_shapes=scratch,
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(x, dy, w1, w2, w3, w4, aff)


@functools.partial(jax.custom_vjp, nondiff_argnums=(13,))
def fused_bottleneck_down(x, w1, w2, w3, w4, a1, b1, a2, b2, a3, b3,
                          a4, b4, batch_tile=None):
    """Stride-2 transition bottleneck block (conv1 3x3 stride 2 +
    projection shortcut 1x1 stride 2): [N, H, W, Cin] ->
    [N, H/2, W/2, Cout], H and W even.  Completes fused coverage of
    all 16 ResNet-50 blocks."""
    cout = w3.shape[1]
    aff = _pack_affines((a1, b1, a2, b2, a3, b3, a4, b4), cout)
    return _fwd_down(x, w1, w2, w3, w4, aff, batch_tile)


def _vjp_fwd_down(x, w1, w2, w3, w4, a1, b1, a2, b2, a3, b3, a4, b4,
                  batch_tile):
    cout = w3.shape[1]
    aff = _pack_affines((a1, b1, a2, b2, a3, b3, a4, b4), cout)
    y = _fwd_down(x, w1, w2, w3, w4, aff, batch_tile)
    return y, (x, w1, w2, w3, w4, aff, jnp.zeros((0,), a1.dtype))


def _vjp_bwd_down(batch_tile, res, dy):
    x, w1, w2, w3, w4, aff, atok = res
    cm = w1.shape[1]
    dx, dw1, dw2, dw3, dw4, daff = _bwd_down(x, dy, w1, w2, w3, w4, aff,
                                             batch_tile)
    cast = lambda g, ref: g.astype(ref.dtype)
    daff = daff.astype(atok.dtype)
    return (dx, cast(dw1, w1), cast(dw2, w2), cast(dw3, w3),
            cast(dw4, w4), daff[0, :cm], daff[1, :cm], daff[2, :cm],
            daff[3, :cm], daff[4], daff[5], daff[6], daff[7])


fused_bottleneck_down.defvjp(_vjp_fwd_down, _vjp_bwd_down)


# ---------------------------------------------------------------------------
# stem tail: BN affine + relu + 3x3 stride-2 maxpool (pad 1)
# ---------------------------------------------------------------------------
#
# The ResNet stem's elementwise tail is pure HBM traffic on the XLA
# path (BN-affine fusion + pool fwd + a select-and-scatter backward,
# ~2ms of the on-chip step): this kernel does relu(c*a+b) and the
# stride-2 maxpool in one VMEM residency, and the backward recomputes
# on-tile and routes pool gradients by VALUE EQUALITY against the
# pooled max.  Equality routing differs from select-and-scatter only
# on exact ties: ties at 0 (the common case — relu floors) are killed
# by the relu mask in the same backward, and positive float ties are
# measure-zero for real activations (each tied element receives the
# full window gradient rather than first-wins).


def _pool_taps(hp6, ho, wo):
    acc = None
    for dy in range(3):
        for dx in range(3):
            sl = _tap2(hp6, dy, dx, ho, wo)
            acc = sl if acc is None else jnp.maximum(acc, sl)
    return acc


def _stem_fwd_kernel(c_ref, aff_ref, o_ref, hp_ref, *, t, h, w, cm):
    dt = c_ref.dtype
    ho, wo = h // 2, w // 2
    a, b = aff_ref[0], aff_ref[1]
    c = c_ref[...].reshape(t * h * w, cm)
    hh = jnp.maximum(c.astype(jnp.float32) * a + b, 0.0).astype(dt)
    # h >= 0 so 0-padding can never win a max over a window that
    # contains at least one real element (every window does)
    hp_ref[...] = jnp.zeros(hp_ref.shape, hp_ref.dtype)
    hp_ref[:, 1:h + 1, 1:w + 1, :] = hh.reshape(t, h, w, cm)
    hp6 = hp_ref[...].reshape(t, (h + 2) // 2, 2, (w + 2) // 2, 2, cm)
    o_ref[...] = _pool_taps(hp6, ho, wo)


def _stem_bwd_kernel(c_ref, dy_ref, aff_ref, dc_ref, daff_ref, hp_ref,
                     yp_ref, dyp_ref, *, t, h, w, cm):
    dt = c_ref.dtype
    ho, wo = h // 2, w // 2
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        daff_ref[...] = jnp.zeros_like(daff_ref)

    a, b = aff_ref[0], aff_ref[1]
    c = c_ref[...].reshape(t * h * w, cm)
    cf = c.astype(jnp.float32)
    u = cf * a + b
    hh = jnp.maximum(u, 0.0).astype(dt)
    hp_ref[...] = jnp.zeros(hp_ref.shape, hp_ref.dtype)
    hp_ref[:, 1:h + 1, 1:w + 1, :] = hh.reshape(t, h, w, cm)
    hp6 = hp_ref[...].reshape(t, (h + 2) // 2, 2, (w + 2) // 2, 2, cm)
    y = _pool_taps(hp6, ho, wo)                          # [T,Ho,Wo,Cm]

    # padded y and dy: a window out of range contributes dy = 0, so the
    # pad value of yp is irrelevant
    yp_ref[...] = jnp.zeros(yp_ref.shape, yp_ref.dtype)
    yp_ref[:, 1:ho + 1, 1:wo + 1, :] = y
    dyp_ref[...] = jnp.zeros(dyp_ref.shape, dyp_ref.dtype)
    dyp_ref[:, 1:ho + 1, 1:wo + 1, :] = dy_ref[...]

    # dh phase (pr, pc): windows (dy, dx) with dy ≡ pr+1, dx ≡ pc+1
    # (mod 2) cover that phase; padded-window offset 1 + (pr+1-dy)//2
    h6 = hh.reshape(t, ho, 2, wo, 2, cm)
    phases = []
    for pr in (0, 1):
        row = []
        for pc in (0, 1):
            h_ph = h6[:, :, pr, :, pc, :]
            acc = jnp.zeros((t, ho, wo, cm), jnp.float32)
            for dy_ in range(3):
                if (dy_ % 2) != (pr + 1) % 2:
                    continue
                for dx_ in range(3):
                    if (dx_ % 2) != (pc + 1) % 2:
                        continue
                    ro = 1 + (pr + 1 - dy_) // 2
                    co = 1 + (pc + 1 - dx_) // 2
                    ysl = yp_ref[:, ro:ro + ho, co:co + wo, :]
                    dsl = dyp_ref[:, ro:ro + ho, co:co + wo, :]
                    acc = acc + jnp.where(h_ph == ysl,
                                          dsl.astype(jnp.float32), 0.0)
            row.append(acc)
        phases.append(row)
    dh = jnp.stack(
        [jnp.stack([phases[0][0], phases[0][1]], axis=3),
         jnp.stack([phases[1][0], phases[1][1]], axis=3)],
        axis=2).reshape(t * h * w, cm)
    du = jnp.where(u > 0.0, dh, 0.0)
    daff_ref[0] += jnp.sum(du * cf, axis=0)
    daff_ref[1] += jnp.sum(du, axis=0)
    dc_ref[...] = (du * a).astype(dt).reshape(t, h, w, cm)


def _stem_fwd(c, aff, batch_tile):
    n, h, w, cm = c.shape
    t = batch_tile or default_batch_tile(n, h, w, cm)
    if n % t:
        raise ValueError(f"batch_tile={t} does not divide batch {n}")
    kernel = functools.partial(_stem_fwd_kernel, t=t, h=h, w=w, cm=cm)
    tile = _vmem_spec((t, h, w, cm), lambda i: (i, 0, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(n // t,),
        in_specs=[tile, _full_spec(aff.shape)],
        out_specs=_vmem_spec((t, h // 2, w // 2, cm),
                             lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h // 2, w // 2, cm), c.dtype),
        scratch_shapes=[pltpu.VMEM((t, h + 2, w + 2, cm), c.dtype)],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(c, aff)


def _stem_bwd(c, dy, aff, batch_tile):
    n, h, w, cm = c.shape
    t = batch_tile or default_batch_tile(n, h, w, cm, rows_target=6272)
    if n % t:
        raise ValueError(f"batch_tile={t} does not divide batch {n}")
    kernel = functools.partial(_stem_bwd_kernel, t=t, h=h, w=w, cm=cm)
    tile = _vmem_spec((t, h, w, cm), lambda i: (i, 0, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(n // t,),
        in_specs=[tile,
                  _vmem_spec((t, h // 2, w // 2, cm),
                             lambda i: (i, 0, 0, 0)),
                  _full_spec(aff.shape)],
        out_specs=[tile, _full_spec(aff.shape)],
        out_shape=[jax.ShapeDtypeStruct(c.shape, c.dtype),
                   jax.ShapeDtypeStruct(aff.shape, jnp.float32)],
        scratch_shapes=[
            pltpu.VMEM((t, h + 2, w + 2, cm), c.dtype),
            pltpu.VMEM((t, h // 2 + 2, w // 2 + 2, cm), c.dtype),
            pltpu.VMEM((t, h // 2 + 2, w // 2 + 2, cm), dy.dtype),
        ],
        compiler_params=_compiler_params(),
        interpret=_interpret(),
    )(c, dy, aff)


# Longest spatial side the stem kernel is PROVEN to compile at on v5e
# (jax 0.9): 112 (the ResNet-50 stem geometry), once _compiler_params
# raises Mosaic's default 16MB scoped-VMEM cap — under the default cap
# the phase-deinterleave reshape's scratch overflows (FUSED_PROBE.log).
# The scoped cost scales with the LANE-PADDED plane, so the guard keys
# on max(h, w); anything beyond the proven side dispatches to the XLA
# composition rather than gambling on an unproven Mosaic compile.
_STEM_SIDE_LIMIT = 112


def _stem_tail_xla(c, a, b):
    """XLA fallback with kernel-identical semantics: relu(c*a+b) ->
    3x3 stride-2 maxpool, pad 1."""
    hh = jnp.maximum(c.astype(jnp.float32) * a + b, 0.0).astype(c.dtype)
    return jax.lax.reduce_window(
        hh, jnp.asarray(-jnp.inf, hh.dtype), jax.lax.max,
        (1, 3, 3, 1), (1, 2, 2, 1),
        ((0, 0), (1, 1), (1, 1), (0, 0)))


def fused_stem_tail(c, a, b, batch_tile=None):
    """relu(c*a + b) -> 3x3 stride-2 maxpool (pad 1): the BN-affine +
    relu + pool tail of the ResNet stem in one HBM round-trip.
    c: [N, H, W, Cm] conv output (H, W even); a/b: per-channel affine.

    Above _STEM_SIDE_LIMIT the Pallas kernel is unproven (Mosaic
    scoped-vmem cost scales with the plane) and this dispatches to
    the XLA composition — the stem tail is ~1% of the ResNet-50 step's
    HBM traffic, so the fused win there was never material; the guard
    keeps the API total while the bottleneck kernels carry the perf.
    The dispatch lives OUTSIDE the custom_vjp: a guard inside the
    primal would be bypassed by the custom VJP rules under grad, and
    the XLA branch wants native autodiff anyway."""
    # keyed on the longer spatial side, not the h*w product: the OOM
    # scales with the lane-padded plane, so a tall-narrow [112, 28]
    # plane is as bad as [112, 112] (review catch)
    if max(c.shape[1], c.shape[2]) > _STEM_SIDE_LIMIT:
        return _stem_tail_xla(c, a, b)
    return _stem_tail_pallas(c, a, b, batch_tile)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _stem_tail_pallas(c, a, b, batch_tile=None):
    aff = jnp.stack([a.astype(jnp.float32), b.astype(jnp.float32)])
    return _stem_fwd(c, aff, batch_tile)


def _stem_vjp_fwd(c, a, b, batch_tile):
    aff = jnp.stack([a.astype(jnp.float32), b.astype(jnp.float32)])
    y = _stem_fwd(c, aff, batch_tile)
    return y, (c, aff, jnp.zeros((0,), a.dtype))


def _stem_vjp_bwd(batch_tile, res, dy):
    c, aff, atok = res
    dc, daff = _stem_bwd(c, dy, aff, batch_tile)
    daff = daff.astype(atok.dtype)
    return dc, daff[0], daff[1]


_stem_tail_pallas.defvjp(_stem_vjp_fwd, _stem_vjp_bwd)
