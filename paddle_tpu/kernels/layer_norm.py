"""Pallas TPU fused LayerNorm (forward + custom-VJP backward).

Parity target: the reference's fused layer-norm CUDA kernels
(/root/reference/paddle/fluid/operators/layer_norm_op.cu and the fused
variants in operators/fused/fused_fc_elementwise_layernorm_op.cc) — one
kernel that reads x once, computes mean/rstd in f32, and writes the
normalized output, instead of the unfused mean/var/normalize chain.

Kernel shape: grid over row blocks; each step loads a [block_rows, D]
tile into VMEM, reduces mean and variance along D in f32 on the VPU, and
writes y = (x - mean) * rstd * gamma + beta in the input dtype.  Mean and
rstd are saved for the backward, which fuses the three reference grad
terms (dx, dgamma partial, dbeta partial) into one data pass; the dgamma/
dbeta row-partials are reduced with a plain XLA sum outside the kernel
(a [rows, D] -> [D] reduction XLA already does at line rate).

On non-TPU backends the kernels run in interpret mode (numerics tests);
dispatch (ops/nn_ops.py layer_norm) only selects the Pallas path on TPU
for last-axis norms with D % 128 == 0 under FLAGS_use_pallas_layer_norm.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover - TPU-specific
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_ROWS = 256


def _interpret():
    from .backend import is_tpu_backend

    return not is_tpu_backend()


def _fwd_kernel(x_ref, g_ref, b_ref, y_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)                  # [R, D]
    mean = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = xc * rstd * g_ref[...].astype(jnp.float32)[None, :] \
        + b_ref[...].astype(jnp.float32)[None, :]
    y_ref[...] = y.astype(y_ref.dtype)
    # mean/rstd are NOT materialized: 1-D f32 outputs tile at T(1024)
    # and clash with row blocks (Mosaic layout-verify failure on chip);
    # the backward recomputes them from the x block it already holds
    # in VMEM — identical numerics, and the forward writes less HBM.


def _bwd_kernel(x_ref, g_ref, dy_ref,
                dx_ref, dg_acc_ref, db_acc_ref, *, rows, block, groups,
                eps):
    x = x_ref[...].astype(jnp.float32)                  # [R, D]
    dy = dy_ref[...].astype(jnp.float32)
    gamma = g_ref[...].astype(jnp.float32)[None, :]
    # recompute row stats from the block already in VMEM (see fwd)
    mean = jnp.mean(x, axis=1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    xhat = xc * rstd
    wdy = dy * gamma
    # dx = rstd * (wdy - mean(wdy) - xhat * mean(wdy * xhat))
    c1 = jnp.mean(wdy, axis=1, keepdims=True)
    c2 = jnp.mean(wdy * xhat, axis=1, keepdims=True)
    dx_ref[...] = (rstd * (wdy - c1 - xhat * c2)).astype(dx_ref.dtype)
    # a partial final block carries out-of-bounds padded rows: mask them
    # out of the cross-row partial sums (dx rows beyond `rows` are
    # discarded on write, but sums would absorb the garbage)
    row_idx = pl.program_id(0) * block \
        + jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], 1), 0)
    valid = row_idx < rows
    d = x.shape[1]
    # dgamma/dbeta partials: reduce the block's rows down to `groups`
    # rows (8 keeps the accumulator TPU-tileable — a (1, D) block
    # violates the (8, 128) minimum) and ACCUMULATE into one
    # VMEM-resident [groups, D] output shared by every grid step; the
    # final [groups, D] -> [D] sum happens outside in XLA.
    # jnp.where, not a multiply: padded rows may hold NaN (NaN * 0 = NaN)
    dgp = jnp.sum(jnp.where(valid, dy * xhat, 0.0)
                  .reshape(groups, -1, d), axis=1)
    dbp = jnp.sum(jnp.where(valid, dy, 0.0)
                  .reshape(groups, -1, d), axis=1)

    @pl.when(pl.program_id(0) == 0)
    def _init():
        dg_acc_ref[...] = jnp.zeros_like(dg_acc_ref)
        db_acc_ref[...] = jnp.zeros_like(db_acc_ref)

    dg_acc_ref[...] += dgp
    db_acc_ref[...] += dbp


def _fwd(x, gamma, beta, eps, block_rows):
    rows, d = x.shape
    block = min(block_rows, rows)
    grid = (pl.cdiv(rows, block),)
    y = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((d,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        interpret=_interpret(),
    )(x, gamma, beta)
    return y


def _bwd(x, gamma, dy, eps, block_rows):
    rows, d = x.shape
    block = min(block_rows, rows)
    nblocks = pl.cdiv(rows, block)
    groups = 8 if block % 8 == 0 else 1
    dx, dg_acc, db_acc = pl.pallas_call(
        functools.partial(_bwd_kernel, rows=rows, block=block,
                          groups=groups, eps=eps),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0)),
            pl.BlockSpec((d,), lambda i: (0,)),
            pl.BlockSpec((block, d), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, d), lambda i: (i, 0)),
            # every grid step maps the SAME full-array block: the
            # accumulator stays VMEM-resident across the whole grid
            pl.BlockSpec((groups, d), lambda i: (0, 0)),
            pl.BlockSpec((groups, d), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, d), x.dtype),
            jax.ShapeDtypeStruct((groups, d), jnp.float32),
            jax.ShapeDtypeStruct((groups, d), jnp.float32),
        ],
        interpret=_interpret(),
    )(x, gamma, dy)
    return dx, dg_acc.sum(axis=0), db_acc.sum(axis=0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_layer_norm(x, gamma, beta, eps=1e-5,
                     block_rows=DEFAULT_BLOCK_ROWS):
    """LayerNorm over the last axis of a 2-D [rows, D] input."""
    return _fwd(x, gamma, beta, eps, block_rows)


def _fused_ln_fwd(x, gamma, beta, eps, block_rows):
    return _fwd(x, gamma, beta, eps, block_rows), (x, gamma)


def _fused_ln_bwd(eps, block_rows, res, dy):
    x, gamma = res
    dx, dgamma, dbeta = _bwd(x, gamma, dy, eps, block_rows)
    return dx, dgamma.astype(gamma.dtype), dbeta.astype(gamma.dtype)


fused_layer_norm.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def layer_norm_pallas(x, gamma, beta, eps=1e-5):
    """Any-rank wrapper: normalizes over the last axis."""
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    y = fused_layer_norm(x2, gamma, beta, eps)
    return y.reshape(shape)
