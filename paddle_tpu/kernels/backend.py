"""Backend detection shared by the Pallas kernels.

The real chip in this environment registers as platform "axon" (a
tunneled TPU PJRT plugin), not "tpu" — `jax.default_backend()` checks
alone would leave every Pallas kernel permanently on the interpret/XLA
path on actual hardware.  Detection therefore also inspects the device
kind string ("TPU v5 lite", ...).
"""

import jax


def is_tpu_backend():
    if jax.default_backend() == "tpu":
        return True
    try:
        d = jax.devices()[0]
    except Exception:  # pragma: no cover - backend init failure
        return False
    kind = (getattr(d, "device_kind", "") or "").lower()
    return d.platform == "tpu" or "tpu" in kind
