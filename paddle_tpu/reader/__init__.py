"""Data pipeline.

Parity targets:
- DataLoader.from_generator (/root/reference/python/paddle/fluid/reader.py:179)
- reader decorators (python/paddle/reader/decorator.py: batch/shuffle/map/...)
- the C++ double-buffered device feed (operators/reader/buffered_reader.cc)
  becomes a background-thread prefetcher handing ready host batches to the
  jitted step (device transfer overlaps with compute via jax async dispatch).
"""

import itertools
import queue
import random as _random
import threading

import numpy as np

__all__ = ["DataLoader", "PyReader", "batch", "shuffle", "buffered", "map_readers",
           "chain", "compose", "firstn", "cache", "device_prefetch"]


# ---------------------------------------------------------------------------
# reader decorators (python/paddle/reader/decorator.py parity)
# ---------------------------------------------------------------------------

def batch(reader, batch_size, drop_last=False):
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def shuffle(reader, buf_size, seed=None):
    def shuffled():
        rng = _random.Random(seed)
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf

    return shuffled


def buffered(reader, size):
    """Background-thread prefetch (decorator.py buffered).  The
    consumer side is instrumented: buffer occupancy lands on the
    `reader.prefetch_depth` gauge at every get (starvation shows as a
    flatline at 0 on /metrics and the chrome counter track), and the
    blocking get itself is charged to the goodput ledger's data_wait
    bucket while one is active."""

    class _End:
        pass

    def buffered_reader():
        from .. import monitor
        from ..monitor import goodput

        depth = monitor.gauge("reader.prefetch_depth")
        q = queue.Queue(maxsize=size)

        def worker():
            try:
                for item in reader():
                    q.put(item)
            finally:
                q.put(_End)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            depth.set(q.qsize())
            gled = goodput.active()
            if gled is None:
                item = q.get()
            else:
                with gled.span("data_wait"):
                    item = q.get()
            if item is _End:
                break
            yield item

    return buffered_reader


def map_readers(func, *readers):
    def reader():
        its = [r() for r in readers]
        for items in zip(*its):
            yield func(*items)

    return reader


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers):
    def reader():
        for items in zip(*[r() for r in readers]):
            out = []
            for it in items:
                if isinstance(it, tuple):
                    out.extend(it)
                else:
                    out.append(it)
            yield tuple(out)

    return reader


def firstn(reader, n):
    def reader_n():
        yield from itertools.islice(reader(), n)

    return reader_n


def cache(reader):
    all_items = []
    filled = [False]

    def cached():
        if not filled[0]:
            for item in reader():
                all_items.append(item)
                yield item
            filled[0] = True
        else:
            yield from all_items

    return cached


def _transferable(leaf):
    """Array-like leaves get device_put; names/metadata pass through."""
    if isinstance(leaf, (np.ndarray, np.generic)):
        return True
    # jax.Array without importing jax at module scope
    return type(leaf).__module__.startswith(("jaxlib", "jax"))


def device_prefetch(batches, size=2, device=None):
    """Double-buffered host->device prefetch (buffered_reader.cc role,
    done the TPU way).

    Keeps `size` batches' transfers IN FLIGHT ahead of the consumer:
    `jax.device_put` is async dispatch, so batch N+1's host->device copy
    is issued before the consumer has finished step N — the copy rides
    the DMA while the step occupies the compute units, which is the
    entire win (measured as the prefetch lever of bench.py's
    resnet50_sweep).  size=2 is the classic double buffer; larger only
    helps if the producer is burstier than the consumer.

    Each array leaf of every yielded batch is a FRESH device buffer that
    the consumer exclusively owns, so donating it into a jitted step
    (donate_argnums) is safe — no buffer is ever yielded twice and the
    iterator keeps no reference once a batch is handed out.  Non-array
    leaves (names, metadata) pass through untouched.  Order is the
    source order: nothing is dropped, duplicated, or reordered.

    batches: iterable of pytrees (feed dicts, tuples of arrays, ...).
    device: target jax.Device (default: jax's default device).
    """
    import collections

    import jax

    if size < 1:
        raise ValueError(f"device_prefetch size must be >= 1, got {size}")

    def put_leaf(leaf):
        if not _transferable(leaf):
            return leaf
        if isinstance(leaf, jax.Array):
            # device_put on an already-on-device array ALIASES the same
            # buffer; copy so the fresh-buffer/donation guarantee holds
            # for every leaf, not just host ones
            import jax.numpy as jnp

            fresh = jnp.copy(leaf)
            return fresh if device is None \
                else jax.device_put(fresh, device)
        return jax.device_put(leaf, device)

    def put(item):
        return jax.tree_util.tree_map(put_leaf, item)

    from .. import monitor

    depth = monitor.gauge("reader.prefetch_depth")
    it = iter(batches)
    queue = collections.deque()

    def fill(n):
        for item in itertools.islice(it, n):
            queue.append(put(item))

    fill(size)
    while queue:
        # buffer occupancy AT each get: a healthy double buffer reads
        # `size`, a starved one flatlines at 1 (this batch only) — the
        # input-starvation signal on /metrics and the chrome track
        depth.set(len(queue))
        out = queue.popleft()
        # issue batch N+1's transfer BEFORE handing batch N to the
        # consumer: the copy overlaps the consumer's step
        fill(1)
        yield out


# ---------------------------------------------------------------------------
# DataLoader
# ---------------------------------------------------------------------------

def _stack_samples(samples, feed_names):
    """list of tuples -> dict of batched numpy arrays."""
    cols = list(zip(*samples))
    out = {}
    for name, col in zip(feed_names, cols):
        out[name] = np.stack([np.asarray(c) for c in col])
    return out


class DataLoader:
    """Feeds dict batches to Executor.run (reader.py:179 parity).

    Iterating yields dicts name->np.ndarray ready to pass as `feed`.
    """

    def __init__(self, feed_list=None, capacity=4, iterable=True,
                 use_multiprocess=False, num_workers=2):
        self._feed_names = [
            v.name if hasattr(v, "name") else v for v in (feed_list or [])
        ]
        self._capacity = capacity
        self._batch_reader = None
        self._use_multiprocess = use_multiprocess
        self._num_workers = num_workers

    @staticmethod
    def from_generator(feed_list=None, capacity=4, iterable=True,
                       return_list=False, use_double_buffer=True,
                       use_multiprocess=False, num_workers=2):
        """use_multiprocess=True engages worker processes + shared-memory
        transport (reader.py:469 DygraphGeneratorLoader parity) instead
        of the background thread — the GIL-free path for CPU-bound
        python readers."""
        return DataLoader(feed_list, capacity, iterable,
                          use_multiprocess=use_multiprocess,
                          num_workers=num_workers)

    def set_batch_generator(self, reader, places=None):
        self._batch_reader = reader
        return self

    def set_sample_list_generator(self, reader, places=None):
        def batched():
            for samples in reader():
                yield _stack_samples(samples, self._feed_names)

        self._batch_reader = batched
        return self

    def set_sample_generator(self, reader, batch_size, drop_last=True,
                             places=None):
        return self.set_sample_list_generator(
            batch(reader, batch_size, drop_last=drop_last), places)

    def __iter__(self):
        if self._batch_reader is None:
            raise RuntimeError("no generator set on DataLoader")
        if self._use_multiprocess:
            from .shm import ShmBatchLoader

            def sharded(worker_id, num_workers):
                return self._gen_feed_dicts(worker_id, num_workers)

            return iter(ShmBatchLoader(sharded,
                                       num_workers=self._num_workers,
                                       capacity=self._capacity))
        prefetched = buffered(self._gen_feed_dicts, self._capacity)
        return iter(prefetched())

    def _gen_feed_dicts(self, worker_id=None, num_workers=None):
        import itertools

        reader = self._batch_reader
        if worker_id is None:
            items = reader()
        else:
            # multiprocess path: pass the shard through when the user's
            # reader is shard-aware, else round-robin islice (order
            # preserved; see ShmBatchLoader doc for the cost model)
            from .shm import is_shard_aware

            items = (reader(worker_id, num_workers)
                     if is_shard_aware(reader)
                     else itertools.islice(reader(), worker_id, None,
                                           num_workers))
        for item in items:
            if isinstance(item, dict):
                yield item
            elif isinstance(item, (list, tuple)) and self._feed_names:
                yield {n: np.asarray(v)
                       for n, v in zip(self._feed_names, item)}
            else:
                yield item


class DataFeeder:
    """Parity: fluid.DataFeeder (data_feeder.py) — converts sample lists
    to feed dicts."""

    def __init__(self, feed_list, place=None):
        self._feed_names = [v.name if hasattr(v, "name") else v
                            for v in feed_list]

    def feed(self, samples):
        return _stack_samples(samples, self._feed_names)


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel-map a reader with a thread pool (parity:
    python/paddle/reader/decorator.py:364 xmap_readers — the reference
    uses threads too). order=True preserves sample order."""
    import queue as _q
    import threading as _t

    def xreader():
        in_q = _q.Queue(buffer_size)
        out_q = _q.Queue(buffer_size)
        END = object()

        errors = []

        def feeder():
            try:
                for i, sample in enumerate(reader()):
                    in_q.put((i, sample))
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)
            finally:
                # guarantee every worker sees an END even if the source
                # reader raised (missing sentinels deadlock the consumer)
                for _ in range(process_num):
                    in_q.put(END)

        def worker():
            try:
                while True:
                    item = in_q.get()
                    if item is END:
                        return
                    i, sample = item
                    out_q.put((i, mapper(sample)))
            except BaseException as e:  # noqa: BLE001
                errors.append(e)
            finally:
                out_q.put(END)

        threads = [_t.Thread(target=feeder, daemon=True)]
        threads += [_t.Thread(target=worker, daemon=True)
                    for _ in range(process_num)]
        for t in threads:
            t.start()

        finished = 0
        if order:
            import heapq
            heap, want = [], 0
            while finished < process_num:
                item = out_q.get()
                if item is END:
                    finished += 1
                    continue
                heapq.heappush(heap, item)
                while heap and heap[0][0] == want:
                    yield heapq.heappop(heap)[1]
                    want += 1
            # on error some indices never arrive; drain what's complete
            while heap and not errors:
                yield heapq.heappop(heap)[1]
        else:
            while finished < process_num:
                item = out_q.get()
                if item is END:
                    finished += 1
                    continue
                yield item[1]
        if errors:
            raise errors[0]

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """Interleave multiple readers, each drained on its own thread
    (parity: decorator.py:457 — the reference forks processes; readers
    here are python generators feeding a jit pipeline, so threads give
    the same overlap without fork hazards under JAX)."""
    import queue as _q
    import threading as _t

    def mreader():
        out_q = _q.Queue(queue_size)
        END = object()

        errors = []

        def drain(r):
            try:
                for sample in r():
                    out_q.put(sample)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)
            finally:
                out_q.put(END)  # guaranteed sentinel, even on error

        threads = [_t.Thread(target=drain, args=(r,), daemon=True)
                   for r in readers]
        for t in threads:
            t.start()
        finished = 0
        while finished < len(readers):
            item = out_q.get()
            if item is END:
                finished += 1
                continue
            yield item
        if errors:
            raise errors[0]

    return mreader


class PyReader(DataLoader):
    """`fluid.io.PyReader` parity (reference reader.py:441): the 1.x
    name for the generator-fed loader.  decorate_* methods map onto the
    DataLoader setters; start()/reset() exist for the non-iterable
    protocol (iteration here is always the iterable protocol, so they
    are no-ops kept for script parity)."""

    def __init__(self, feed_list=None, capacity=4, use_double_buffer=True,
                 iterable=True, return_list=False):
        super().__init__(feed_list=feed_list, capacity=capacity,
                         iterable=iterable)

    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        return self.set_sample_generator(sample_generator, batch_size,
                                         drop_last=drop_last, places=places)

    def decorate_sample_list_generator(self, reader, places=None):
        return self.set_sample_list_generator(reader, places=places)

    def decorate_batch_generator(self, reader, places=None):
        return self.set_batch_generator(reader, places=places)

    def start(self):
        return None

    def reset(self):
        return None
