"""Multiprocess shared-memory data loading.

Parity: /root/reference/python/paddle/fluid/reader.py:469
DygraphGeneratorLoader (use_multiprocess=True) over
memory/allocation/mmap_allocator.cc — worker PROCESSES prepare batches
and hand them to the trainer through shared memory, sidestepping both
the GIL (thread loaders serialize CPU-bound python readers) and pickle
(arrays move as raw bytes in a SharedMemory segment).

Design: worker i round-robins the batch stream (batches i, i+N,
i+2N, ...), writes each batch's arrays back-to-back into one
SharedMemory segment, and queues (segment name, per-array metadata).
The consumer reads queues round-robin so batch ORDER MATCHES the serial
reader, copies the arrays out (one memcpy — the same cost the
reference's LoDTensor shared-mem copy pays), and unlinks the segment
immediately, so segment lifetime is one batch.

Cleanup mirrors the reference's signal-handler story
(reader.py:469 _set_process_signal_handler): workers install
terminate-on-SIGTERM handlers, the parent tracks live segment names and
unlinks them on iterator close/GC/atexit, and python's own
resource_tracker backstops anything that leaks.
"""

import atexit
import itertools
import multiprocessing as mp
import signal
import traceback
from multiprocessing import shared_memory

import numpy as np

__all__ = ["ShmBatchLoader", "ProducerDeadError"]

_END = "__end__"
_ERR = "__err__"


class ProducerDeadError(ConnectionError):
    """A shm worker PROCESS died without reporting (OOM killer,
    segfault, SIGKILL) while the consumer was blocked on its queue.
    Subclasses ConnectionError so the resilience taxonomy classifies
    it TRANSIENT by type — a re-launched loader epoch is the recovery,
    exactly like the reference fleet re-launching a dead worker —
    instead of the consumer hanging forever on a queue nobody will
    ever feed again."""

# segment names handed to the parent but not yet unlinked; one process-
# wide registry + atexit hook (per-instance hooks would pin loaders)
_LIVE_SEGMENTS = set()


def _cleanup_segments():
    for name in list(_LIVE_SEGMENTS):
        try:
            seg = shared_memory.SharedMemory(name=name)
            seg.close()
            seg.unlink()
        except FileNotFoundError:
            pass
        _LIVE_SEGMENTS.discard(name)


atexit.register(_cleanup_segments)


def is_shard_aware(reader):
    """A reader opts into N-way sharding by REQUIRING at least two
    positional parameters — (worker_id, num_workers) — with any further
    parameters defaulted.  Zero required params = plain generator
    (defaulted params like `def r(batch_size=32)` must NOT receive
    worker indices).  Exactly one required param is ambiguous and
    rejected loudly rather than silently mis-called."""
    import inspect

    try:
        params = list(inspect.signature(reader).parameters.values())
    except (TypeError, ValueError):
        return False
    required = [p for p in params
                if p.default is inspect.Parameter.empty
                and p.kind in (p.POSITIONAL_ONLY,
                               p.POSITIONAL_OR_KEYWORD)]
    if len(required) == 2:
        return True
    if required:
        raise TypeError(
            f"reader {reader!r} requires {len(required)} positional "
            f"parameters — a multiprocess reader must require either "
            f"zero (plain generator) or exactly two "
            f"(worker_id, num_workers); further parameters must be "
            f"defaulted")
    return False


def _worker_main(batch_reader, worker_id, num_workers, sharded, q,
                 capacity_sem):
    signal.signal(signal.SIGTERM, lambda *a: exit(0))
    try:
        # fault-injection hook (inherited by fork): an armed
        # crash_point("shm.worker") kills THIS process without a
        # sentinel — the SIGKILL/OOM-killer shape the consumer's
        # producer-death guard must detect (see except InjectedCrash)
        from ..resilience import faultinject as _fi
    except Exception:
        _fi = None
    try:
        if sharded:
            # shard-aware reader: each worker generates ONLY its batches
            it = batch_reader(worker_id, num_workers)
        else:
            # plain generator: islice re-evaluates skipped batches, so
            # >1 worker on an expensive plain reader does duplicate
            # work — callers wanting real parallel speedup pass a
            # (worker_id, num_workers) factory (see ShmBatchLoader doc)
            it = itertools.islice(batch_reader(), worker_id, None,
                                  num_workers)
        for batch in it:
            if _fi is not None:
                _fi.crash_point("shm.worker")
            arrays = _normalize(batch)
            total = sum(a.nbytes for _, a in arrays)
            capacity_sem.acquire()      # bound in-flight shared memory
            seg = shared_memory.SharedMemory(create=True,
                                             size=max(total, 1))
            meta = []
            off = 0
            for name, a in arrays:
                seg.buf[off:off + a.nbytes] = a.tobytes()
                meta.append((name, str(a.dtype), a.shape, off))
                off += a.nbytes
            q.put((seg.name, meta))
            seg.close()                 # parent unlinks after copying
            try:
                # ownership moves to the parent: stop this process's
                # resource tracker from warning about (or double-
                # unlinking) the segment at exit
                from multiprocessing import resource_tracker

                resource_tracker.unregister("/" + seg.name,
                                            "shared_memory")
            except Exception:
                pass
        q.put((_END, worker_id))
    except BaseException as e:
        if _fi is not None and isinstance(e, _fi.InjectedCrash):
            # model a SIGKILL faithfully: no sentinel, no cleanup —
            # the process just stops existing.  (q.put'ing _ERR here
            # would be a dying process politely reporting its own
            # murder, which is exactly what the producer-death guard
            # exists to NOT rely on.)
            import os

            os._exit(1)
        q.put((_ERR, traceback.format_exc()))


def _normalize(batch):
    if isinstance(batch, dict):
        return [(k, np.ascontiguousarray(v)) for k, v in batch.items()]
    if isinstance(batch, (list, tuple)):
        return [(str(i), np.ascontiguousarray(v))
                for i, v in enumerate(batch)]
    return [("0", np.ascontiguousarray(batch))]


class ShmBatchLoader:
    """Iterate a batch reader with `num_workers` worker processes and
    shared-memory transport.  Yields whatever shape the reader yields
    (dict -> dict, tuple/list -> list), batches in serial order.

    Two reader forms:
      reader()                      -> plain generator.  One worker
        decouples reader CPU time from the train loop (the reference's
        DygraphGeneratorLoader shape); more workers preserve order via
        round-robin islice but re-run the generator per worker, so they
        only help when per-batch cost is in the YIELDED work.
      reader(worker_id, num_workers) -> shard-aware factory.  Each
        worker generates only batches worker_id, worker_id+N, ... —
        N-way parallel CPU speedup with order still guaranteed.
    """

    def __init__(self, batch_reader, num_workers=2, capacity=4,
                 mp_context=None, death_poll_s=1.0):
        assert num_workers >= 1
        self._reader = batch_reader
        self._sharded = is_shard_aware(batch_reader)
        self._num_workers = num_workers
        self._capacity = capacity
        # producer-death guard poll: how long one blocking queue read
        # waits before re-checking the worker process is still alive
        self._death_poll_s = death_poll_s
        # fork: generators/closures pass to children for free (the
        # reference's loader forks too); children only touch numpy
        self._ctx = mp.get_context(mp_context or "fork")
        # module-level registry + one atexit hook: per-instance
        # registration would pin every epoch's loader alive forever
        self._live_segments = _LIVE_SEGMENTS

    def _cleanup_segments(self):
        _cleanup_segments()

    def __iter__(self):
        n = self._num_workers
        queues = [self._ctx.Queue() for _ in range(n)]
        sems = [self._ctx.Semaphore(max(1, self._capacity // n))
                for _ in range(n)]
        procs = [
            self._ctx.Process(
                target=_worker_main,
                args=(self._reader, i, n, self._sharded, queues[i],
                      sems[i]),
                daemon=True)
            for i in range(n)
        ]
        for p in procs:
            p.start()
        try:
            # round-robin keeps serial order for round-robin-sharded
            # streams; a finished worker leaves the rotation so uneven
            # shard-aware readers (e.g. sharded by file) still drain
            # every batch instead of truncating at the first END
            active = list(range(n))
            pos = 0
            while active:
                i = active[pos % len(active)]
                while True:
                    try:
                        item = queues[i].get(timeout=self._death_poll_s)
                        break
                    except Exception:
                        # producer-death guard: a worker killed without
                        # a sentinel (OOM killer, segfault, SIGKILL)
                        # would leave this get() blocked FOREVER —
                        # poll-check liveness and raise a CLASSIFIED
                        # error instead (ProducerDeadError is transient
                        # in the resilience taxonomy: re-running the
                        # loader is the recovery)
                        p = procs[i]
                        if not p.is_alive():
                            try:
                                # the dying worker's queue feeder may
                                # have flushed a final batch: drain it
                                # before declaring starvation
                                item = queues[i].get_nowait()
                                break
                            except Exception:
                                pass
                            raise ProducerDeadError(
                                f"multiprocess DataLoader worker {i} "
                                f"died (exitcode {p.exitcode}) without "
                                f"reporting — likely killed (OOM?); "
                                f"consumer unblocked instead of "
                                f"hanging")
                if item[0] == _END:
                    active.remove(i)
                    continue
                if item[0] == _ERR:
                    raise RuntimeError(
                        f"multiprocess DataLoader worker failed:\n"
                        f"{item[1]}")
                seg_name, meta = item
                self._live_segments.add(seg_name)
                yield self._materialize(seg_name, meta)
                sems[i].release()
                pos += 1
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(timeout=5)
            # drain queues so no segment leaks
            for q in queues:
                try:
                    while True:
                        item = q.get_nowait()
                        if item and item[0] not in (_END, _ERR):
                            self._live_segments.add(item[0])
                except Exception:
                    pass
            self._cleanup_segments()

    def _materialize(self, seg_name, meta):
        seg = shared_memory.SharedMemory(name=seg_name)
        try:
            out = {}
            for name, dtype, shape, off in meta:
                nbytes = int(np.prod(shape, dtype=np.int64)) \
                    * np.dtype(dtype).itemsize
                # bytes() copies without exporting a live view that
                # would pin the segment open at close(); .copy() makes
                # the final array WRITABLE (frombuffer views over bytes
                # are read-only, unlike the threaded loader's output)
                raw = bytes(seg.buf[off:off + nbytes])
                out[name] = np.frombuffer(
                    raw, dtype=dtype).reshape(shape).copy()
            keys = list(out)
            if keys == [str(i) for i in range(len(keys))]:
                return [out[k] for k in keys]   # tuple/list reader
            return out
        finally:
            seg.close()
            try:
                seg.unlink()
            except FileNotFoundError:
                pass
            self._live_segments.discard(seg_name)
