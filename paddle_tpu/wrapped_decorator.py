"""`fluid.wrapped_decorator` import-path compatibility.

Parity: python/paddle/fluid/wrapped_decorator.py (wrap_decorator :21,
signature_safe_contextmanager :31).  The reference leans on the
third-party `decorator` package to preserve signatures; functools in
the stdlib is enough here.
"""

import contextlib
import functools

__all__ = ["wrap_decorator", "signature_safe_contextmanager"]


def wrap_decorator(decorator_func):
    def __impl__(func):
        wrapped = decorator_func(func)
        return functools.wraps(func)(wrapped)

    return __impl__


signature_safe_contextmanager = wrap_decorator(contextlib.contextmanager)
