"""Continuous-batching decode serving — slot-based KV-cache engine
(ISSUE 17 tentpole).

The PR-8 runtime batches single-shot predictors; this engine serves
`models/generate.py`'s GPT family autoregressively, with the
iteration-level scheduling of Orca (OSDI '22) and the slot-resident
KV cache of vLLM (SOSP '23):

- ONE compiled decode step owns the whole serving state: a fixed
  `[layers, slots, heads, max_len, head_dim]` ring-buffer KV cache plus
  per-slot `pos/active/token/stop/eos/temp/key` vectors, passed as
  **donated** executor state (the PR-16 donation idiom — the cache
  never copies, the step updates it in place on device).
- Requests **join and leave mid-decode**: a finished slot is released
  and refilled by the next queued request's prefill WITHOUT retracing —
  prefill runs at the PR-8 bucket shapes (prompt padded to a
  power-of-two bucket, causally masked so padding is exactly inert) and
  writes K/V straight into the slot's cache region; slot index, true
  prompt length and stop position are traced scalars.  Steady state
  therefore compiles exactly (1 decode step + 1 prefill per bucket),
  asserted through the compile ledger by the decode_serving_smoke row.
- Every decode step runs the full slot width; inactive slots compute
  harmlessly masked garbage (their writes land clamped inside their own
  slot's region and are overwritten by the next tenant's prefill or by
  the step that first attends the position — see _decode_step_impl).

Token-exactness: decode attention is the SAME code generate() uses
(kernels/attention.py decode_attention), prefill is the same layer math
at bucket shape with MoE routed drop-free (cap = cohort size), and
padded/causally-dead columns underflow to exact f32 zeros — so a
request decoded through slots, including one that joins mid-stream
into a previously-released slot, emits token-for-token what
generate() emits (greedy; asserted dense + MoE in
tests/test_decode_serving.py).

Hardening is the PR-8 stack rewired for token granularity: per-TOKEN
deadline budgets (TTFT included) feeding the outcome ledger
(requests == sum(outcomes) stays the invariant), the circuit breaker
around both dispatch kinds, the hang watchdog tracking each in-flight
step (a wedged decode step gets a flight-recorder post-mortem and its
requests fail classified — the donated state is inside the wedged
call, so the engine marks itself broken rather than pretend the cache
survived), and DecodeStats publishing tokens/s, TTFT and inter-token
percentiles (exact nearest-rank), slot occupancy and the
prefill/decode split to /metrics and the telemetry stream.

`continuous=False` turns the SAME engine into the pad-to-bucket
baseline (admit a cohort, decode until every member finishes, only
then admit again) — the bench's control arm, isolating iteration-level
scheduling as the measured lever.
"""

import functools
import threading
import time
from collections import deque

import numpy as np

from .. import flags
from ..resilience import faultinject
from ..resilience.breaker import CircuitBreaker
from ..resilience.retry import RetryPolicy, call_with_retry
from ..resilience.taxonomy import DeadlineExceeded
from .runtime import QueueFullError, ServingClosedError, ServingFuture
from .stats import DecodeStats
from .watchdog import HangWatchdog, WatchdogStall

__all__ = ["DecodeEngine", "DecodeConfig", "EngineBrokenError",
           "default_prompt_buckets", "QueueFullError",
           "ServingClosedError", "WatchdogStall", "DeadlineExceeded"]

_DEFAULT_RETRY = object()


def _fr():
    from ..monitor import flight_recorder

    return flight_recorder


def _mon():
    from .. import monitor

    return monitor


def _tracing():
    from ..monitor import tracing

    return tracing


def default_prompt_buckets(max_len):
    """Power-of-two prompt buckets 16..max_len (PR-8 bucketing shape):
    one prefill program per bucket, compiled once."""
    out = []
    b = 16
    while b <= max_len:
        out.append(b)
        b *= 2
    return tuple(out) or (int(max_len),)


class DecodeConfig:
    """Knobs for one decode engine; flag-backed like ServingConfig."""

    def __init__(self, slots=None, max_len=None, buckets=None,
                 max_queue_depth=None, default_token_budget_s=None,
                 retry_policy=_DEFAULT_RETRY, breaker_threshold=5,
                 breaker_cooldown_s=5.0, watchdog_stall_s=None,
                 watchdog_poll_s=None, continuous=True, prewarm=True,
                 label="decode", clock=time.monotonic):
        self.slots = int(slots if slots is not None
                         else flags.flag("decode_slots"))
        self.max_len = int(max_len if max_len is not None
                           else flags.flag("decode_max_len"))
        if self.slots < 1 or self.max_len < 2:
            raise ValueError("need slots >= 1 and max_len >= 2")
        self.buckets = tuple(sorted(set(
            int(b) for b in (buckets
                             or default_prompt_buckets(self.max_len)))))
        if any(b < 1 or b > self.max_len for b in self.buckets):
            raise ValueError(
                f"buckets {self.buckets} must lie in [1, max_len="
                f"{self.max_len}]")
        self.max_queue_depth = int(
            max_queue_depth if max_queue_depth is not None
            else flags.flag("serving_queue_depth"))
        if default_token_budget_s is None:
            default_token_budget_s = \
                flags.flag("decode_token_budget_s") or None
        self.default_token_budget_s = default_token_budget_s
        if retry_policy is _DEFAULT_RETRY:
            retry_policy = RetryPolicy(max_retries=2, base_delay=0.02,
                                       max_delay=0.5, seed=0)
        self.retry_policy = retry_policy          # None disables retry
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.watchdog_stall_s = float(
            watchdog_stall_s if watchdog_stall_s is not None
            else flags.flag("serving_watchdog_stall_s"))
        self.watchdog_poll_s = watchdog_poll_s
        self.continuous = bool(continuous)
        self.prewarm = bool(prewarm)
        self.label = label
        self.clock = clock


class _DecodeRequest:
    __slots__ = ("prompt", "max_new", "eos_id", "temperature",
                 "token_budget_s", "rid", "future", "tokens",
                 "enqueue_t", "last_token_t", "first_token_t", "slot",
                 "bucket", "kill", "key", "trace", "qspan", "dspan")

    def __init__(self, prompt, max_new, eos_id, temperature,
                 token_budget_s, rid, bucket, key):
        self.prompt = prompt              # np.int32 [len]
        self.max_new = max_new
        self.eos_id = eos_id              # int or None
        self.temperature = temperature
        self.token_budget_s = token_budget_s
        self.rid = rid
        self.bucket = bucket
        self.key = key                    # np.uint32 [2]
        self.future = ServingFuture()
        self.tokens = []
        self.enqueue_t = None
        self.last_token_t = None          # engine clock of newest token
        self.first_token_t = None
        self.slot = None
        self.kill = False                 # expired while slot-resident
        # request-scoped trace context (monitor/tracing.py); None when
        # FLAGS_request_tracing is off
        self.trace = None
        self.qspan = None                 # queue-wait span
        self.dspan = None                 # slot-resident decode span

    def next_deadline(self):
        """Per-token budget: the NEXT token (the first included — TTFT
        counts queue wait) must land within budget of the previous."""
        if self.token_budget_s is None:
            return None
        anchor = self.last_token_t if self.last_token_t is not None \
            else self.enqueue_t
        return anchor + self.token_budget_s

    def expired(self, now):
        d = self.next_deadline()
        return d is not None and now >= d


class EngineBrokenError(RuntimeError):
    """The engine lost its donated device state (a wedged or failed
    decode step) and cannot continue; submit() fails fast."""


# ---------------------------------------------------------------------------
# device programs (module-level so each engine jits exactly two shapes)
# ---------------------------------------------------------------------------

def _decode_step_impl(state, trees, kill, cfg):
    """One full-width decode step over every slot.

    Inactive (or host-killed) slots still flow through the math — their
    writes land at their stale position CLAMPED inside their own slot's
    cache region, which is safe: a position is only ever attended on or
    after the step that first writes it (the live mask is `col <= pos`
    and the write at `pos` happens before the attend), and a refilling
    prefill overwrites the prompt region wholesale."""
    import jax
    import jax.numpy as jnp

    from ..kernels.attention import decode_attention
    from ..models import generate as G
    from ..nn import functional as F

    params = G.DecodeParams(*trees, cfg)
    n_slots = state["pos"].shape[0]
    max_len = state["k"].shape[3]
    scale = 1.0 / (cfg.hidden_size // cfg.num_heads) ** 0.5
    active = jnp.logical_and(state["active"], jnp.logical_not(kill))
    pos = state["pos"]
    tok = state["token"]
    x = jnp.take(params.emb["wte.weight"], tok[:, None], axis=0) \
        + jnp.take(params.emb["wpe.weight"], pos, axis=0)[:, None, :]
    posw = jnp.minimum(pos, max_len - 1)
    sl = jnp.arange(n_slots)

    def layer(x, xs):
        bp, k_cache, v_cache = xs          # caches [S, H, T, D]
        hn = F.layer_norm(x, [cfg.hidden_size], bp["norm1.weight"],
                          bp["norm1.bias"])
        q, k, v = G._qkv(hn, bp, cfg.num_heads)      # [S, H, 1, D]
        k_cache = k_cache.at[sl, :, posw, :].set(
            k[:, :, 0, :].astype(k_cache.dtype))
        v_cache = v_cache.at[sl, :, posw, :].set(
            v[:, :, 0, :].astype(v_cache.dtype))
        # per-slot ragged positions through the SAME single-query
        # kernel generate() decodes with — the token-exactness hinge
        o = decode_attention(q, k_cache, v_cache, pos=pos, scale=scale)
        return G._block_tail(x, G._merge_heads(o), bp, cfg,
                             decode=True), (k_cache, v_cache)

    x, (ks, vs) = jax.lax.scan(
        layer, x, (params.blocks, state["k"], state["v"]))
    x = F.layer_norm(x, [cfg.hidden_size], params.head["norm_f.weight"],
                     params.head["norm_f.bias"])
    logits = jnp.einsum("bh,vh->bv", x[:, -1],
                        params.emb["wte.weight"])
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    temp = state["temp"]
    scaled = logits.astype(jnp.float32) \
        / jnp.maximum(temp, 1e-6)[:, None]
    sampled = jax.vmap(
        lambda kk, p, lg: jax.random.categorical(
            jax.random.fold_in(kk, p), lg))(
        state["key"], pos, scaled).astype(jnp.int32)
    nxt = jnp.where(temp > 0.0, sampled, greedy)
    new_pos = pos + 1
    done = jnp.logical_or(
        jnp.logical_and(state["eos"] >= 0, nxt == state["eos"]),
        new_pos >= state["stop"])
    still = jnp.logical_and(active, jnp.logical_not(done))
    out = dict(state)
    out.update(
        k=ks, v=vs,
        pos=jnp.where(active, new_pos, pos),
        token=jnp.where(active, nxt, tok),
        active=still)
    return out, nxt, active, still


def _prefill_impl(state, trees, prompt, true_len, slot, stop, eos,
                  temp, key, cfg):
    """Prefill one request into one slot at a static bucket shape.

    `prompt` is [1, bucket] zero-padded; causal masking makes the pad
    columns exactly inert for the real positions (masked scores
    underflow to f32 zero), and MoE routes DROP-FREE (cap = cohort
    size) so pad tokens cannot displace real ones — the first emitted
    token is bitwise what generate()'s unpadded prefill emits.
    true_len/slot/stop are traced scalars: refilling any slot with any
    prompt length inside the bucket reuses this one program."""
    import jax
    import jax.numpy as jnp

    from ..models import generate as G
    from ..nn import functional as F

    params = G.DecodeParams(*trees, cfg)
    bucket = prompt.shape[1]
    pos = jnp.arange(bucket, dtype=jnp.int32)[None, :]
    x = jnp.take(params.emb["wte.weight"], prompt, axis=0) \
        + jnp.take(params.emb["wpe.weight"], pos, axis=0)

    def layer(x, bp):
        hn = F.layer_norm(x, [cfg.hidden_size], bp["norm1.weight"],
                          bp["norm1.bias"])
        q, k, v = G._qkv(hn, bp, cfg.num_heads)
        o = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                           training=False)
        return G._block_tail(x, G._merge_heads(o), bp, cfg,
                             decode=True), (k, v)

    x, (ks, vs) = jax.lax.scan(layer, x, params.blocks)
    # ks: [L, 1, H, bucket, D] -> this slot's cache region [:, slot]
    k_cache = jax.lax.dynamic_update_slice(
        state["k"], ks.astype(state["k"].dtype), (0, slot, 0, 0, 0))
    v_cache = jax.lax.dynamic_update_slice(
        state["v"], vs.astype(state["v"].dtype), (0, slot, 0, 0, 0))
    x = F.layer_norm(x, [cfg.hidden_size], params.head["norm_f.weight"],
                     params.head["norm_f.bias"])
    # logits at the TRUE last prompt position (LN is per-position, so
    # slicing before the head matches generate()'s slice-after bitwise)
    h = jax.lax.dynamic_slice(
        x, (0, true_len - 1, 0), (1, 1, cfg.hidden_size))[:, 0]
    logits = jnp.einsum("bh,vh->bv", h, params.emb["wte.weight"])
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits.astype(jnp.float32) / jnp.maximum(temp, 1e-6)
    sampled = jax.random.categorical(key, scaled,
                                     axis=-1).astype(jnp.int32)
    first = jnp.where(temp > 0.0, sampled, greedy)[0]
    active = jnp.logical_and(
        true_len < stop,
        jnp.logical_not(jnp.logical_and(eos >= 0, first == eos)))
    out = dict(state)
    out.update(
        k=k_cache, v=v_cache,
        pos=state["pos"].at[slot].set(true_len),
        token=state["token"].at[slot].set(first),
        active=state["active"].at[slot].set(active),
        stop=state["stop"].at[slot].set(stop),
        eos=state["eos"].at[slot].set(eos),
        temp=state["temp"].at[slot].set(temp),
        key=state["key"].at[slot].set(key))
    return out, first, active


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class DecodeEngine:
    """See module docstring.  `auto_start=False` keeps the loop thread
    off so tests drive scheduling deterministically via `step()`."""

    def __init__(self, model_or_params, config=None, auto_start=True,
                 **kw):
        from ..models import generate as G

        self.config = cfg = config or DecodeConfig(**kw)
        if config is not None and kw:
            raise TypeError("pass either config= or keyword knobs, "
                            "not both")
        params = (model_or_params
                  if isinstance(model_or_params, G.DecodeParams)
                  else G.build_decode_params(model_or_params))
        self.params = params
        if cfg.max_len > params.cfg.max_seq_len:
            raise ValueError(
                f"max_len {cfg.max_len} exceeds the model's "
                f"max_seq_len {params.cfg.max_seq_len}")
        self._trees = (params.emb, params.blocks, params.head)
        self.stats = DecodeStats(cfg.label, slots=cfg.slots)
        self.breaker = CircuitBreaker(
            failure_threshold=cfg.breaker_threshold,
            cooldown_s=cfg.breaker_cooldown_s, clock=cfg.clock,
            name=cfg.label)
        self.stats.attach_breaker(self.breaker)
        self.watchdog = HangWatchdog(
            cfg.watchdog_stall_s, poll_s=cfg.watchdog_poll_s,
            clock=cfg.clock, stats=self.stats, label=cfg.label,
            pre_dump=self.emit_telemetry, on_poll=self.sweep_expired)
        self._lock = threading.RLock()
        self._cond = threading.Condition(self._lock)
        self._queue = deque()
        self._slot_req = [None] * cfg.slots
        self._live = set()
        self._rid = 0
        self._closed = False
        self._broken = False
        self._loop_thread = None
        self._build_programs()
        self._state = self._fresh_state()
        self.prewarmed = self._prewarm() if cfg.prewarm else 0
        if auto_start:
            self.start()

    # -- compiled programs ---------------------------------------------
    def _build_programs(self):
        import jax

        mon = _mon()
        cfg = self.config
        dec_cfg = self.params.cfg
        step = jax.jit(functools.partial(_decode_step_impl, cfg=dec_cfg),
                       donate_argnums=(0,))
        self._step_fn = mon.instrument_jit(
            step, key=f"{cfg.label}.decode_step")
        self._prefill_fns = {}
        pre = jax.jit(functools.partial(_prefill_impl, cfg=dec_cfg),
                      donate_argnums=(0,))
        for b in cfg.buckets:
            # one instrumented wrapper per bucket: the ledger wrappers
            # are signature-pinned, and per-bucket keys make the
            # "1 prefill compile per bucket" assertion a ledger query
            self._prefill_fns[b] = mon.instrument_jit(
                pre, key=f"{cfg.label}.prefill_b{b}")

    def _fresh_state(self):
        import jax.numpy as jnp

        cfg = self.config
        dec = self.params.cfg
        head_dim = dec.hidden_size // dec.num_heads
        kv = (dec.num_layers, cfg.slots, dec.num_heads, cfg.max_len,
              head_dim)
        return {
            "k": jnp.zeros(kv, dec.dtype),
            "v": jnp.zeros(kv, dec.dtype),
            "pos": jnp.zeros(cfg.slots, jnp.int32),
            "active": jnp.zeros(cfg.slots, bool),
            "token": jnp.zeros(cfg.slots, jnp.int32),
            "stop": jnp.zeros(cfg.slots, jnp.int32),
            "eos": jnp.full((cfg.slots,), -1, jnp.int32),
            "temp": jnp.zeros(cfg.slots, jnp.float32),
            "key": jnp.zeros((cfg.slots, 2), jnp.uint32),
        }

    def _prewarm(self):
        """Compile every program this engine will ever run (1 decode
        step + 1 prefill per bucket) against throwaway state, then
        rebuild the state zeros — donation consumed the warm buffers,
        and serving must start from an empty cache anyway."""
        cfg = self.config
        n = 0
        for b in cfg.buckets:
            self._state, _, _ = self._prefill_fns[b](
                self._state, self._trees,
                np.zeros((1, b), np.int32), np.int32(1), np.int32(0),
                np.int32(1), np.int32(-1), np.float32(0.0),
                np.zeros(2, np.uint32))
            n += 1
        self._state, _, _, _ = self._step_fn(
            self._state, self._trees, np.zeros(cfg.slots, bool))
        self._state = self._fresh_state()
        return n + 1

    # -- lifecycle ------------------------------------------------------
    def start(self):
        with self._lock:
            if self._loop_thread is not None or self._closed:
                return
            self._loop_thread = threading.Thread(
                target=self._loop, name=f"{self.config.label}-engine",
                daemon=True)
            self._loop_thread.start()
        self.watchdog.start()

    def close(self):
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            t = self._loop_thread
        if t is not None:
            t.join(timeout=10.0)
        err = ServingClosedError("decode engine closed")
        with self._lock:
            leftovers = list(self._live)
            self._queue.clear()
            self._slot_req = [None] * self.config.slots
        for req in leftovers:
            self._resolve_error(req, err, "cancelled")
        self.watchdog.stop()
        self.emit_telemetry()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- submission -----------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens, eos_id=None,
               temperature=0.0, token_budget_s=None, seed=None,
               traceparent=None):
        """Enqueue one generation request; returns a ServingFuture that
        resolves to the np.int32 token array (length max_new_tokens,
        or shorter if eos_id fires).  `traceparent` optionally joins an
        external W3C trace when FLAGS_request_tracing is on."""
        cfg = self.config
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new > cfg.max_len:
            raise ValueError(
                f"prompt+new = {prompt.size + max_new} exceeds the "
                f"engine's max_len {cfg.max_len}")
        bucket = next((b for b in cfg.buckets if b >= prompt.size),
                      None)
        if bucket is None:
            raise ValueError(
                f"prompt length {prompt.size} exceeds the largest "
                f"prefill bucket {cfg.buckets[-1]}")
        if token_budget_s is None:
            token_budget_s = cfg.default_token_budget_s
        with self._lock:
            if self._closed:
                raise ServingClosedError("decode engine is closed")
            if self._broken:
                raise EngineBrokenError(
                    "decode engine lost its device state (stalled or "
                    "failed step); build a fresh engine")
            # started after the closed/broken gates (those raise
            # without a ledger outcome, so no tree must exist for
            # them) but before the queue-full gate (rejected IS a
            # ledger outcome and its tree must close as "rejected")
            trace = _tracing().get().start_request(
                f"decode.request/{cfg.label}", label=cfg.label,
                traceparent=traceparent,
                attrs={"prompt_len": int(prompt.size),
                       "max_new": max_new})
            if len(self._queue) >= cfg.max_queue_depth:
                self.stats.note_outcome("rejected")
                if trace is not None:
                    trace.annotate(trace.root, "rejected: queue full",
                                   depth=len(self._queue))
                    trace.finish("rejected")
                raise QueueFullError(
                    f"decode queue at depth {cfg.max_queue_depth}")
            self._rid += 1
            rid = self._rid
            key = np.asarray(
                np.random.RandomState(
                    seed if seed is not None else rid).randint(
                    0, 2 ** 31, size=2), np.uint32)
            req = _DecodeRequest(prompt, max_new, eos_id,
                                 float(temperature),
                                 token_budget_s, rid, bucket, key)
            req.enqueue_t = cfg.clock()
            if trace is not None:
                trace.rid = rid
                req.trace = trace
                req.qspan = trace.child("queue", "queue")
            self._queue.append(req)
            self._live.add(req)
            self.stats.note_admitted(len(self._queue))
            self._cond.notify_all()
        return req.future

    # -- budget sweep (watchdog poll + loop tick) ----------------------
    def sweep_expired(self):
        """Shed queued requests and expire slot-resident ones whose
        per-token budget has passed — runs on the watchdog thread too,
        so budget expiry keeps resolving even while the engine thread
        is wedged inside a stalled step."""
        now = self.config.clock()
        shed, expired = [], []
        with self._lock:
            keep = deque()
            for req in self._queue:
                (shed.append if req.expired(now)
                 else keep.append)(req)
            self._queue = keep
            # slot-resident: mark for the next step's kill mask
            for req in self._slot_req:
                if req is not None and not req.kill \
                        and not req.future.done() and req.expired(now):
                    req.kill = True
                    expired.append(req)
            depth = len(self._queue)
        for req in shed:
            self._resolve_error(
                req, DeadlineExceeded(
                    f"first token budget "
                    f"({req.token_budget_s * 1e3:.1f}ms/token) expired "
                    f"in queue", budget_s=req.token_budget_s),
                "shed")
        for req in expired:
            self._resolve_error(
                req, DeadlineExceeded(
                    f"per-token budget "
                    f"({req.token_budget_s * 1e3:.1f}ms/token) expired "
                    f"after {len(req.tokens)} tokens",
                    budget_s=req.token_budget_s),
                "expired")
        if shed or expired:
            self.stats.note_queue_depth(depth)
        return len(shed) + len(expired)

    # -- resolution -----------------------------------------------------
    def _resolve_ok(self, req, now):
        if req.future._set_result(np.asarray(req.tokens, np.int32)):
            self.stats.note_outcome("completed",
                                    latency_s=now - req.enqueue_t)
            if req.trace is not None:
                req.trace.finish("completed")
        with self._lock:
            self._live.discard(req)

    def _resolve_error(self, req, exc, outcome):
        if req.future._set_exception(exc):
            self.stats.note_outcome(outcome)
            if req.trace is not None:
                req.trace.finish(outcome)
        with self._lock:
            self._live.discard(req)

    def _mark_broken(self, why):
        """The donated device state rode a doomed call: drain EVERY
        unresolved request — queued AND slot-resident — as cancelled,
        so no future (and no trace) stays open behind a dead engine.
        Requests the failing dispatch already resolved (stalled/
        failed) are skipped by the idempotent resolve."""
        with self._lock:
            self._broken = True
            queued = list(self._queue)
            self._queue.clear()
            resident = [r for r in self._slot_req if r is not None]
            self._slot_req = [None] * self.config.slots
        err = EngineBrokenError(f"decode engine broken: {why}")
        for req in queued:
            self._resolve_error(req, err, "cancelled")
        for req in resident:
            self._resolve_error(req, err, "cancelled")
        _fr().note_event("decode_engine_broken", severe=True,
                         label=self.config.label, reason=why)

    # -- guarded dispatch ----------------------------------------------
    def _dispatch(self, call, meta, requests):
        """Run one device call (prefill or decode step) on a worker
        thread under watchdog + retry + breaker, enforcing per-token
        budgets of the carried requests while it is in flight.
        Returns the call's value, or None when the dispatch stalled or
        failed (requests resolved, engine marked broken — the donated
        state rode the doomed call)."""
        cfg = self.config
        token, stalled = self.watchdog.track(meta)
        done = threading.Event()
        box = {}

        def runner():
            try:
                def _call():
                    if faultinject.is_armed():
                        faultinject.check_transient()
                        faultinject.stall_point("decode.step")
                    return call()

                if cfg.retry_policy is not None:
                    box["out"] = call_with_retry(
                        _call, cfg.retry_policy,
                        on_retry=lambda *a: self.stats.note_retry())
                else:
                    box["out"] = _call()
            except BaseException as e:  # noqa: BLE001
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(target=runner, daemon=True,
                             name=f"{cfg.label}-dispatch")
        t.start()
        try:
            while not done.wait(timeout=0.002):
                self.sweep_expired()
                # requests riding THIS dispatch may not be queue- or
                # slot-resident yet (a prefill's request is in limbo
                # between the two) — enforce their budgets directly
                now = cfg.clock()
                for req in requests:
                    if not req.future.done() and req.expired(now):
                        req.kill = True
                        self._resolve_error(
                            req, DeadlineExceeded(
                                "per-token budget expired in flight",
                                budget_s=req.token_budget_s),
                            "expired")
                if stalled.is_set():
                    stall = WatchdogStall(
                        f"decode {meta.get('op')} step in flight > "
                        f"{cfg.watchdog_stall_s}s")
                    self.breaker.note_failure(stall)
                    for req in requests:
                        self._resolve_error(req, stall, "stalled")
                    self._mark_broken("watchdog_stall")
                    return None
        finally:
            self.watchdog.untrack(token)
        if "error" in box:
            e = box["error"]
            self.breaker.note_failure(e)
            _fr().note_event(
                "decode_dispatch_failed", label=cfg.label,
                error=f"{type(e).__name__}: {e}"[:200],
                **{k: v for k, v in meta.items()
                   if k not in ("request_ids", "trace_ids")})
            for req in requests:
                self._resolve_error(req, e, "failed")
            self._mark_broken("dispatch_failed")
            self.emit_telemetry()
            return None
        self.breaker.note_success()
        return box["out"]

    # -- scheduling -----------------------------------------------------
    def _free_slots_locked(self):
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _admit_locked(self):
        """Pick (slot, request) pairs to prefill this iteration.
        Continuous mode refills any free slot the moment the queue has
        work; static (baseline) mode only admits a fresh cohort once
        EVERY slot is free — the pad-to-bucket re-prefill scheduling
        the bench row compares against."""
        free = self._free_slots_locked()
        if not free or not self._queue:
            return []
        if not self.config.continuous \
                and len(free) != self.config.slots:
            return []
        picks = []
        while free and self._queue:
            req = self._queue.popleft()
            if req.future.done():          # shed while queued
                continue
            picks.append((free.pop(0), req))
        self.stats.note_queue_depth(len(self._queue))
        return picks

    def step(self):
        """One engine iteration: sweep budgets, refill free slots via
        prefill, then run one full-width decode step.  Returns the
        number of device dispatches made (0 = idle)."""
        cfg = self.config
        self.sweep_expired()
        with self._lock:
            if self._broken:
                return 0
            picks = self._admit_locked()
        dispatched = 0
        for idx, (slot, req) in enumerate(picks):
            if not self.breaker.allow():
                # breaker open: requeue the whole remainder and let
                # budgets shed; the cooldown probe reopens admission.
                # A requeued request keeps its SAME trace (its queue
                # span never ended — requeued wait keeps accruing);
                # the detour is a point annotation, not a new tree.
                with self._lock:
                    for _, r in reversed(picks[idx:]):
                        if r.trace is not None:
                            r.trace.annotate(r.trace.root,
                                             "breaker_requeue")
                        self._queue.appendleft(r)
                picks = picks[:idx]
                break
            if not self._prefill(slot, req):
                err = EngineBrokenError(
                    "decode engine broke mid-admission")
                for _, r in picks[idx + 1:]:
                    self._resolve_error(r, err, "cancelled")
                return dispatched + 1      # engine broken
            dispatched += 1
        with self._lock:
            slot_reqs = list(self._slot_req)
        want_step = any(
            r is not None and (r.kill or not r.future.done())
            for r in slot_reqs)
        if want_step and self.breaker.allow():
            self._decode_once(slot_reqs)
            dispatched += 1
        return dispatched

    def _prefill(self, slot, req):
        cfg = self.config
        bucket = req.bucket
        prompt = np.zeros((1, bucket), np.int32)
        prompt[0, :req.prompt.size] = req.prompt
        true_len = req.prompt.size
        stop = true_len + req.max_new - 1   # position of the last token
        meta = {"op": "prefill", "bucket": bucket, "slot": slot,
                "rid": req.rid}
        pspan = None
        if req.trace is not None:
            meta["trace_id"] = req.trace.trace_id
            req.trace.end(req.qspan)
            pspan = req.trace.child(f"prefill/b{bucket}", "prefill",
                                    attrs={"bucket": bucket,
                                           "slot": slot})
        fn = self._prefill_fns[bucket]
        state = self._state

        def call():
            return fn(state, self._trees, prompt, np.int32(true_len),
                      np.int32(slot), np.int32(stop),
                      np.int32(-1 if req.eos_id is None else req.eos_id),
                      np.float32(req.temperature), req.key)

        out = self._dispatch(call, meta, [req])
        if out is None:
            return False
        self._state, first, active = out
        now = cfg.clock()
        first = int(first)
        active = bool(active)
        req.first_token_t = req.last_token_t = now
        if req.trace is not None:
            req.trace.annotate(pspan, "first_token")
            req.trace.end(pspan)
        if req.future.done():              # expired mid-prefill
            self.stats.note_prefill(ttft_s=None, now=now)
            req.kill = True
            with self._lock:
                self._slot_req[slot] = req if active else None
            return True
        self.stats.note_prefill(ttft_s=now - req.enqueue_t, now=now)
        req.tokens.append(first)
        req.slot = slot
        if not active:                     # max_new == 1 or instant eos
            self._resolve_ok(req, now)
            with self._lock:
                self._slot_req[slot] = None
        else:
            if req.trace is not None:
                # slot-resident decode: one span from slot entry to
                # the last token, per-token progress as annotations
                req.dspan = req.trace.child("decode", "decode",
                                            attrs={"slot": slot})
            with self._lock:
                self._slot_req[slot] = req
        return True

    def _decode_once(self, slot_reqs):
        cfg = self.config
        kill = np.array([r is not None and r.kill for r in slot_reqs],
                        bool)
        rids = [r.rid for r in slot_reqs if r is not None]
        meta = {"op": "decode", "active": int(sum(
            r is not None and not r.kill for r in slot_reqs)),
            "request_ids": rids}
        tids = [r.trace.trace_id for r in slot_reqs
                if r is not None and r.trace is not None]
        if tids:
            # a wedged decode step's stall dump names every resident
            # request's trace
            meta["trace_ids"] = tids
        state = self._state

        def call():
            return self._step_fn(state, self._trees, kill)

        waiting = [r for r in slot_reqs
                   if r is not None and not r.future.done()]
        out = self._dispatch(call, meta, waiting)
        if out is None:
            return False
        self._state, tokens, was_active, still = out
        now = cfg.clock()
        tokens = np.asarray(tokens)
        was_active = np.asarray(was_active)
        still = np.asarray(still)
        emitted = 0
        for i, req in enumerate(slot_reqs):
            if req is None:
                continue
            if not was_active[i]:
                # killed (budget-expired) or raced to done: release
                with self._lock:
                    if self._slot_req[i] is req:
                        self._slot_req[i] = None
                continue
            if not req.future.done():
                req.tokens.append(int(tokens[i]))
                if req.last_token_t is not None:
                    self.stats.note_token_latency(
                        now - req.last_token_t)
                req.last_token_t = now
                emitted += 1
                if req.trace is not None:
                    req.trace.annotate(req.dspan, "token",
                                       n=len(req.tokens))
                if not still[i]:
                    if req.trace is not None:
                        req.trace.end(req.dspan)
                    self._resolve_ok(req, now)
            if not still[i]:
                with self._lock:
                    if self._slot_req[i] is req:
                        self._slot_req[i] = None
        self.stats.note_decode_step(int(was_active.sum()), emitted,
                                    now=now)
        if self.stats.decode_steps % 64 == 0:
            self.emit_telemetry()
        return True

    def _loop(self):
        while True:
            with self._cond:
                while not self._closed and not self._queue \
                        and not any(r is not None
                                    for r in self._slot_req):
                    self._cond.wait(0.02)
                if self._closed or self._broken:
                    return
            try:
                did = self.step()
            except Exception as e:  # noqa: BLE001
                _fr().note_event(
                    "decode_engine_error", severe=True,
                    label=self.config.label,
                    error=f"{type(e).__name__}: {e}"[:200])
                self._mark_broken("engine_loop_error")
                return
            if self._broken:
                return
            if not did:
                time.sleep(0.001)

    # -- observability --------------------------------------------------
    def emit_telemetry(self):
        """Push the freshest kind="serving" decode record onto the
        telemetry JSONL stream (no-op while telemetry is off).  With
        request tracing on, the record carries the label's
        attribution/SLO summary."""
        rec = self.stats.to_record()
        store = _tracing().get()
        if store.enabled:
            s = store.summary(self.config.label)
            if s is not None:
                rec["tracing"] = s
        return _mon().record_serving(rec)

    def summary(self):
        return self.stats.summary()
