"""Versioned model registry + AOT cold-start cache (ISSUE 19).

The fleet's shared store of deployable model artifacts.  Every replica
loads from here; the router rolls versions forward and back by flipping
one pointer.  Crash safety reuses the PR-11 file-based coordination
idiom from checkpoint.py verbatim:

- a version directory under ``versions/v<NNNN>/`` holds a COPY of one
  ``save_inference_model`` output (``__model__.json`` +
  ``__params__.npz``, plus ``__compiled__.jaxexport`` when present);
- a per-file checksum ``_MANIFEST.json`` (size + crc32) is written
  after the payload, and the ``_COMPLETE`` marker LAST — so a reader
  that lists versions concurrently with a publish (or after a
  publisher was SIGKILL'd mid-copy) can never see a partial artifact:
  no marker, or a manifest mismatch, means the version does not exist;
- the ``CURRENT`` pointer is a one-line file flipped via tmp +
  ``os.replace`` — readers see the old version or the new one,
  atomically, never a torn write.  Rollback is the same flip pointed
  backwards: version payloads are immutable, so re-flipping to vN
  restores bitwise-identical predictions.

The AOT cache (``aot/v<NNNN>/<device_kind>/``) holds per-bucket
``jax.export`` executables serialized by the FIRST replica to warm a
version (BucketDispatcher.export_aot), under the same manifest+marker
protocol.  A cold replica imports them (import_aot) and reaches first
byte with ZERO compile-ledger events — the cache key is (program
version, device kind), so an artifact can never be replayed onto the
wrong program or the wrong chip generation.

Fault injection: ``publish``/``publish_aot`` visit
``registry.before_marker`` / ``registry.aot.before_marker`` crash
points between the payload write and the marker, so the
kill-during-publish reader race is testable on purpose.
"""

import os
import re
import shutil

from ..checkpoint import (_MANIFEST, _MARKER, _verify_manifest,
                          _write_manifest)

__all__ = ["ModelRegistry", "RegistryError"]

_VERSION_DIR = re.compile(r"^v(\d{4,})$")
_CURRENT = "CURRENT"
_KIND_RE = re.compile(r"[^a-zA-Z0-9_.-]+")


class RegistryError(RuntimeError):
    """A registry operation referenced a version that does not exist
    (or is incomplete — which, under the marker protocol, is the same
    thing)."""


def _crash_point(name):
    from ..resilience import faultinject

    faultinject.crash_point(name)


def _sanitize_kind(device_kind):
    """Device-kind strings name directories ("TPU v5 lite" and friends
    carry spaces); collapse anything unsafe to '_'."""
    return _KIND_RE.sub("_", str(device_kind)) or "unknown"


class ModelRegistry:
    """Shared-store registry of versioned inference-model artifacts.

    reg = ModelRegistry(root)
    v1 = reg.publish(model_dir)        # atomic: manifest, marker LAST
    reg.set_current(v1)                # atomic pointer flip
    Predictor(reg.version_dir(reg.current()))
    """

    def __init__(self, root):
        self.root = os.path.abspath(root)
        self.versions_root = os.path.join(self.root, "versions")
        self.aot_root = os.path.join(self.root, "aot")
        os.makedirs(self.versions_root, exist_ok=True)

    # -- versions -------------------------------------------------------
    def version_dir(self, version):
        return os.path.join(self.versions_root, "v%04d" % int(version))

    def _is_complete(self, path):
        return os.path.exists(os.path.join(path, _MARKER)) \
            and _verify_manifest(path)

    def versions(self):
        """Sorted COMPLETE versions — a publish in flight (or killed
        mid-copy) is invisible until its marker lands."""
        out = []
        for d in os.listdir(self.versions_root):
            m = _VERSION_DIR.match(d)
            if not m:
                continue
            if self._is_complete(os.path.join(self.versions_root, d)):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest(self):
        vs = self.versions()
        return vs[-1] if vs else None

    def publish(self, model_dir, version=None):
        """Copy one save_inference_model output into the store as the
        next (or an explicit) version.  Payload first, manifest second,
        marker LAST — a concurrent reader sees all of it or none of it.
        Returns the version number."""
        if version is None:
            taken = [int(m.group(1)) for d in os.listdir(self.versions_root)
                     for m in (_VERSION_DIR.match(d),) if m]
            version = (max(taken) + 1) if taken else 1
        vdir = self.version_dir(version)
        if os.path.exists(os.path.join(vdir, _MARKER)):
            raise RegistryError(f"version {version} already published")
        os.makedirs(vdir, exist_ok=True)
        for f in sorted(os.listdir(model_dir)):
            src = os.path.join(model_dir, f)
            if not os.path.isfile(src) or f in (_MARKER, _MANIFEST):
                continue
            shutil.copy2(src, os.path.join(vdir, f))
        _write_manifest(vdir)
        _crash_point("registry.before_marker")
        with open(os.path.join(vdir, _MARKER), "w") as f:
            f.write("ok\n")
        return int(version)

    # -- the CURRENT pointer --------------------------------------------
    def set_current(self, version):
        """Atomically flip the fleet-wide CURRENT pointer (tmp +
        os.replace).  Only a COMPLETE version may become current —
        flipping to a half-published artifact is exactly the race the
        marker protocol exists to kill."""
        version = int(version)
        if not self._is_complete(self.version_dir(version)):
            raise RegistryError(
                f"version {version} is not a complete published artifact")
        tmp = os.path.join(self.root, _CURRENT + ".tmp.%d" % os.getpid())
        with open(tmp, "w") as f:
            f.write("%d\n" % version)
        os.replace(tmp, os.path.join(self.root, _CURRENT))

    def current(self):
        """The pointed-at version, or None.  A pointer at a version
        that has stopped verifying (bit rot after publish) is treated
        as absent rather than served."""
        try:
            with open(os.path.join(self.root, _CURRENT)) as f:
                v = int(f.read().strip())
        except (OSError, ValueError):
            return None
        return v if self._is_complete(self.version_dir(v)) else None

    def current_dir(self):
        v = self.current()
        return self.version_dir(v) if v is not None else None

    # -- AOT artifact cache ---------------------------------------------
    def aot_dir(self, version, device_kind):
        return os.path.join(self.aot_root, "v%04d" % int(version),
                            _sanitize_kind(device_kind))

    def has_aot(self, version, device_kind):
        return self._is_complete(self.aot_dir(version, device_kind))

    def publish_aot(self, version, device_kind, writer):
        """Populate the (version, device kind) AOT cache cell under the
        manifest+marker protocol.  ``writer(dirname)`` stages the
        artifact files (BucketDispatcher.export_aot is the canonical
        writer) and returns how many it wrote; nothing is marked
        complete unless it wrote at least one.  Idempotent: an already-
        complete cell is left untouched (first publisher wins — the
        artifacts are deterministic per (program version, device))."""
        adir = self.aot_dir(version, device_kind)
        if self._is_complete(adir):
            return 0
        os.makedirs(adir, exist_ok=True)
        n = writer(adir)
        if not n:
            return 0
        _write_manifest(adir)
        _crash_point("registry.aot.before_marker")
        with open(os.path.join(adir, _MARKER), "w") as f:
            f.write("ok\n")
        return n
