"""Bucketed micro-batching over a Predictor / CompiledPredictor.

The serving problem with naive batching: every distinct total row
count is a distinct XLA shape, so organic traffic (1, 3, 7, 2, ...
rows) compiles an unbounded set of executables — a recompile storm
exactly when the service is busiest.  The classic fix (the reference's
serving stack pads to fixed batch sizes too) is a SMALL set of bucket
shapes, padded up to:

- buckets default to powers of two up to `max_batch` (1, 2, 4, 8...),
  so padding waste is < 2x and the executable set is O(log max_batch);
- every bucket is AOT-compiled at STARTUP (`prewarm`) through the
  monitor's compile ledger, so traffic never pays a trace+compile and
  the compile events are attributed like the executor's;
- the compiled-fn cache is keyed like the executor's compiled-step
  cache — (program identity, program version, bucket, per-feed
  feature signature, fetch names) — so a mutated program or a changed
  feature shape can never serve a stale executable.

Padding rows are zeros and are sliced off before results leave the
runtime; because XLA computes rows of these inference programs
independently, the non-padding rows are BITWISE identical to an
unbatched `Predictor.run` (asserted by tests/test_serving.py).
"""

import os

import numpy as np

import jax
import jax.numpy as jnp

try:
    from jax import export as _jax_export
except ImportError:  # pragma: no cover
    _jax_export = None

__all__ = ["default_buckets", "pick_bucket", "BucketDispatcher"]


def default_buckets(max_batch):
    """Powers of two up to max_batch, plus max_batch itself: the
    smallest executable set with bounded (<2x) padding waste."""
    if max_batch < 1:
        raise ValueError("max_batch must be >= 1")
    out = []
    b = 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(int(max_batch))
    return out


def pick_bucket(buckets, rows):
    """Smallest bucket that fits `rows` (buckets sorted ascending)."""
    for b in buckets:
        if rows <= b:
            return b
    raise ValueError(f"{rows} rows exceed the largest bucket "
                     f"{buckets[-1]}")


def _mon():
    from .. import monitor

    return monitor


class BucketDispatcher:
    """Shape the batching + compiled-fn cache around one predictor.

    Works over either engine:
      - `Predictor`: per-bucket AOT executables compiled from its pure
        fn; the eager (uncompiled interpret) path exists for degraded
        mode.
      - `CompiledPredictor`: the serialized artifact IS the single
        bucket (its exported batch dim); no eager path.
    """

    def __init__(self, predictor, buckets=None, max_batch=8,
                 label="serving"):
        self.predictor = predictor
        self.label = label
        self._cache = {}          # full key -> compiled executable
        self._exported_bucket = None
        if hasattr(predictor, "_exported"):       # CompiledPredictor
            bucket = self._exported_batch_dim()
            self.buckets = [bucket]
            self.feed_names = list(self._exported_feed_names())
            self._exported_dtypes = {
                n: a.dtype for n, a in self._exported_tree().items()}
            self._specs = None
        else:                                     # Predictor
            self.buckets = sorted(set(
                buckets if buckets else default_buckets(max_batch)))
            self.feed_names = list(predictor.get_input_names())
            self._specs = predictor.feed_specs()
        self.max_rows = self.buckets[-1]

    # -- CompiledPredictor introspection --------------------------------
    def _exported_tree(self):
        exported = self.predictor._exported
        args, _kwargs = jax.tree_util.tree_unflatten(
            exported.in_tree,
            list(exported.in_avals))
        return args[0]            # the feeds dict the fn was traced with

    def _exported_feed_names(self):
        return sorted(self._exported_tree())

    def _exported_batch_dim(self):
        tree = self._exported_tree()
        dims = {int(a.shape[0]) for a in tree.values() if a.shape}
        if len(dims) != 1:
            raise ValueError(
                f"CompiledPredictor artifact has no single batch dim "
                f"(leading dims {sorted(dims)}); serve it through "
                f"Predictor instead")
        return dims.pop()

    # -- feeds ----------------------------------------------------------
    def prepare(self, feed):
        """(prepared jnp feed dict, row count) for one request; raises
        on missing feeds, mismatched per-feed row counts, or a request
        larger than the biggest bucket (callers split those — admission
        control rejects them loudly instead)."""
        if hasattr(self.predictor, "prepare_feed"):
            prepared = self.predictor.prepare_feed(feed)
        else:
            prepared = {}
            for n in self.feed_names:
                if n not in feed:
                    raise KeyError(f"missing feed '{n}'")
                prepared[n] = jnp.asarray(
                    np.asarray(feed[n]),
                    dtype=self._exported_dtypes.get(n))
        rows = {n: (int(a.shape[0]) if a.ndim else 1)
                for n, a in prepared.items()}
        distinct = set(rows.values())
        if len(distinct) != 1:
            raise ValueError(f"feeds disagree on batch rows: {rows}")
        n_rows = distinct.pop()
        if n_rows < 1:
            raise ValueError("empty request (0 rows)")
        if n_rows > self.max_rows:
            raise ValueError(
                f"request of {n_rows} rows exceeds the largest serving "
                f"bucket {self.max_rows}; split it client-side or raise "
                f"max_batch")
        return prepared, n_rows

    def merge(self, prepared_list, bucket):
        """Concatenate prepared request feeds along the batch axis and
        zero-pad to `bucket` rows.  Returns (batched feed dict,
        [(start, stop) row slice per request])."""
        slices = []
        off = 0
        for p in prepared_list:
            rows = int(next(iter(p.values())).shape[0])
            slices.append((off, off + rows))
            off += rows
        if off > bucket:
            raise ValueError(f"{off} rows exceed bucket {bucket}")
        batched = {}
        for n in self.feed_names:
            parts = [p[n] for p in prepared_list]
            if off < bucket:
                pad_shape = (bucket - off,) + tuple(parts[0].shape[1:])
                parts.append(jnp.zeros(pad_shape, parts[0].dtype))
            batched[n] = parts[0] if len(parts) == 1 \
                else jnp.concatenate(parts, axis=0)
        return batched, slices

    @staticmethod
    def split(outs, slices):
        """Per-request output lists from one batched result: request i
        gets [fetch[start_i:stop_i] for each fetch] — padding rows
        never leave the runtime."""
        return [[o[start:stop] for o in outs] for start, stop in slices]

    # -- compiled-fn cache (keyed like the executor's) ------------------
    def _feat_sig(self, batched):
        return tuple(
            (n, tuple(batched[n].shape[1:]), str(batched[n].dtype))
            for n in sorted(batched))

    def _key(self, bucket, feat_sig):
        p = self.predictor
        if hasattr(p, "_exported"):
            return (id(p._exported), 0, bucket, feat_sig, None)
        return (id(p._program), getattr(p._program, "_version", 0),
                bucket, feat_sig,
                tuple(p.get_output_names()))

    def _compile(self, bucket, example, feat_sig):
        """Lower+compile the predictor's jitted fn at the bucket shape.
        Routed through the monitor's AOT instrumentation so the compile
        is wall-clocked and cost/memory-analyzed like an executor
        compile; falls back to the implicit-jit callable when the jax
        version cannot AOT."""
        mon = _mon()
        key = self._key(bucket, feat_sig)
        compiled = mon.aot_compile(
            self.predictor._fn, example,
            key=f"serving/{self.label}/b{bucket}") \
            if mon.is_enabled() else None
        if compiled is None:
            lower = getattr(self.predictor._fn, "lower", None)
            if lower is not None:
                try:
                    compiled = lower(example).compile()
                except Exception:
                    compiled = None
        if compiled is None:
            # ancient jax with no AOT: the implicit jit cache still
            # pins one executable per bucket shape
            compiled = self.predictor._fn
        self._cache[key] = compiled
        if mon.is_enabled():
            mon.counter("serving.bucket_compile").add(1)
        return compiled

    def _zero_example(self, bucket):
        """A zeros feed dict at the bucket shape, or None when any
        trailing dim is dynamic (prewarm then waits for real traffic
        to reveal the feature shapes)."""
        if self._specs is None:
            return None
        example = {}
        for n in self.feed_names:
            feat, dtype = self._specs[n]
            if feat is None or any(d is None for d in feat):
                return None
            example[n] = jnp.zeros((bucket,) + tuple(feat), dtype)
        return example

    def prewarm(self):
        """AOT-compile every bucket at startup so traffic never pays a
        trace+compile (the recompile-storm guard).  Returns the number
        of executables compiled; 0 when shapes are dynamic or the
        engine is a CompiledPredictor (already an executable)."""
        if hasattr(self.predictor, "_exported"):
            return 0
        n = 0
        for bucket in self.buckets:
            example = self._zero_example(bucket)
            if example is None:
                return n
            if self._key(bucket, self._feat_sig(example)) in self._cache:
                continue           # already imported from the AOT cache
            self._compile(bucket, example, self._feat_sig(example))
            n += 1
        return n

    # -- AOT artifact cache (ISSUE 19) ----------------------------------
    def export_aot(self, dirname, platforms=None):
        """Serialize one ``jax.export`` artifact per bucket
        (``b<bucket>.jaxexport``) into `dirname` — the cold-start cache
        payload a later replica imports instead of recompiling.  Rides
        the same serialization path as Predictor.export_compiled.
        Returns the number of artifacts written (0 for a
        CompiledPredictor — it already IS the artifact — or when shapes
        are dynamic / jax.export is unavailable)."""
        if hasattr(self.predictor, "_exported") or _jax_export is None:
            return 0
        os.makedirs(dirname, exist_ok=True)
        n = 0
        for bucket in self.buckets:
            example = self._zero_example(bucket)
            if example is None:
                return n
            exported = _jax_export.export(
                self.predictor._fn, platforms=platforms)(example)
            path = os.path.join(dirname, f"b{bucket}.jaxexport")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(exported.serialize())
            os.replace(tmp, path)
            n += 1
        return n

    def import_aot(self, dirname):
        """Load per-bucket serialized executables into the compiled-fn
        cache WITHOUT tracing or compiling — zero compile-ledger
        events, which is the whole point: a cold replica reaches first
        byte on cache hits alone.  Each artifact lands under the same
        cache key `_compile` would have used, so a version/shape
        mismatch simply misses and falls through to a (ledgered)
        compile instead of serving a stale executable.  Returns the
        number of buckets imported."""
        if hasattr(self.predictor, "_exported") or _jax_export is None:
            return 0
        n = 0
        for bucket in self.buckets:
            path = os.path.join(dirname, f"b{bucket}.jaxexport")
            if not os.path.isfile(path):
                continue
            example = self._zero_example(bucket)
            if example is None:
                return n
            with open(path, "rb") as f:
                exported = _jax_export.deserialize(f.read())
            key = self._key(bucket, self._feat_sig(example))
            self._cache[key] = exported.call
            n += 1
        mon = _mon()
        if n and mon.is_enabled():
            mon.counter("serving.aot_import").add(n)
        return n

    def dispatch(self, batched, bucket):
        """Run one padded bucket batch through the compiled executable
        for (bucket, feature signature) — compiling on miss (a shape
        prewarm could not predict) — and return the fetch list with
        results materialized (block_until_ready: a dispatch error must
        surface HERE, inside the breaker/retry/watchdog envelope, not
        at some caller's later sync point)."""
        if hasattr(self.predictor, "_exported"):
            outs = self.predictor._exported.call(batched)
        else:
            key = self._key(bucket, self._feat_sig(batched))
            fn = self._cache.get(key)
            if fn is None:
                fn = self._compile(bucket, batched,
                                   self._feat_sig(batched))
            outs = fn(batched)
        outs = list(outs)
        jax.block_until_ready(outs)
        return outs

    # -- degraded paths -------------------------------------------------
    @property
    def eager_available(self):
        return hasattr(self.predictor, "run_eager")

    def dispatch_eager(self, prepared):
        """One UNBATCHED request through the op-by-op interpreter — the
        breaker-open fallback that shares nothing with the compiled
        path it is standing in for."""
        outs = self.predictor.run_eager(prepared)
        return [jnp.asarray(o) for o in outs]
