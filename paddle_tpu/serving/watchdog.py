"""Hang watchdog — a wedged dispatch must be detected, explained, and
escalated, never waited on forever.

PR 4's retry layer handles dispatches that FAIL; this thread handles
dispatches that do NOTHING — a collective stuck at rendezvous, a
runtime bug, an injected hang.  The batcher registers every in-flight
dispatch (`track`) with its batch metadata; the watchdog polls the
registry, and any entry older than `stall_s`:

1. gets a flight-recorder post-mortem dump NOW (reason
   "serving_stall", carrying the in-flight batch's metadata — bucket,
   rows, request ids, and, with request tracing on, the wedged
   requests' trace_ids, elapsed — plus the usual last-K window),
   because a process wedged hard enough may never reach another dump
   point.  The trace_ids in the stall event join against the dump's
   kind="trace" / "trace_active" lines, so the post-mortem names the
   wedged requests' span trees, not just their count;
2. bumps `resilience.watchdog_stalls`;
3. has its `stalled` event set — the dispatch's WAITER escalates per
   policy (fail the batch with a classified WatchdogStall, or abandon
   the wedged call and retry degraded); the watchdog itself never
   kills anything (you cannot cancel an XLA dispatch, only stop
   waiting for it).

The clock is injectable and the poll interval adapts to the stall
threshold, so tests run with millisecond thresholds and zero flakes.
"""

import threading
import time

from ..resilience.taxonomy import DeadlineExceeded

__all__ = ["HangWatchdog", "WatchdogStall"]


class WatchdogStall(DeadlineExceeded):
    """A dispatch exceeded the watchdog's stall threshold and the
    escalation policy chose to fail it.  Subclasses DeadlineExceeded:
    classified DEADLINE (never blind-retried), `is_deadline`-true, and
    distinct from generic transients in every counter."""


def _fr():
    from ..monitor import flight_recorder

    return flight_recorder


class HangWatchdog:
    """Monitor in-flight serving dispatches for stalls."""

    def __init__(self, stall_s, poll_s=None, clock=time.monotonic,
                 stats=None, label="serving", pre_dump=None,
                 on_poll=None):
        # pre_dump: zero-arg callback run before the stall dump — the
        # runtime uses it to push its freshest kind="serving" record
        # into the flight recorder so the dump carries the serving
        # table, not a stale one
        self.pre_dump = pre_dump
        # on_poll: zero-arg callback run every poll tick — the runtime
        # hangs its queue deadline sweep here, so budget expiry is
        # enforced even while the batcher thread is wedged inside the
        # very stall this watchdog exists to catch
        self.on_poll = on_poll
        self.stall_s = float(stall_s)
        # poll fast enough to detect within ~12% of the threshold, but
        # never busy-spin; the cap keeps an idle runtime cheap
        self.poll_s = poll_s if poll_s is not None else \
            min(max(self.stall_s / 8.0, 0.005), 1.0)
        self.clock = clock
        self.stats = stats
        self.label = label
        self._lock = threading.Lock()
        self._inflight = {}          # token -> entry
        self._next_token = 0
        self._stop = threading.Event()
        self._thread = None
        if stats is not None:
            # back-link: the serving summary / the exporter's /healthz
            # ask "is a flagged dispatch STILL wedged right now"
            stats.attach_watchdog(self)

    def stalled_now(self):
        """How many flagged dispatches are still in flight — nonzero
        exactly while a detected stall remains unresolved."""
        with self._lock:
            return sum(1 for e in self._inflight.values() if e["flagged"])

    # -- lifecycle ------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name=f"{self.label}-watchdog",
            daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    # -- registry -------------------------------------------------------
    def track(self, meta):
        """Register one in-flight dispatch; returns (token, stalled
        threading.Event).  The waiter waits on `done OR stalled`."""
        entry = {"start": self.clock(), "meta": dict(meta or {}),
                 "stalled": threading.Event(), "flagged": False}
        with self._lock:
            token = self._next_token
            self._next_token += 1
            self._inflight[token] = entry
        if self.stats is not None:
            self.stats.note_in_flight(len(self._inflight))
        return token, entry["stalled"]

    def untrack(self, token):
        with self._lock:
            self._inflight.pop(token, None)
        if self.stats is not None:
            self.stats.note_in_flight(len(self._inflight))

    def check_now(self):
        """One scan pass (the loop body, callable directly by tests)."""
        now = self.clock()
        with self._lock:
            entries = list(self._inflight.items())
        for token, e in entries:
            elapsed = now - e["start"]
            if elapsed < self.stall_s or e["flagged"]:
                continue
            e["flagged"] = True
            if self.stats is not None:
                self.stats.note_watchdog_stall()
            fr = _fr()
            fr.note_event(
                "serving_stall", severe=True, label=self.label,
                elapsed_s=round(elapsed, 4),
                stall_threshold_s=self.stall_s, **e["meta"])
            # dump BEFORE escalation: if the waiter's policy raises and
            # the caller exits, the post-mortem already exists — and it
            # records what the wedged dispatch was doing
            if self.pre_dump is not None:
                try:
                    self.pre_dump()
                except Exception:
                    pass
            fr.dump(f"serving_stall:{self.label}")
            e["stalled"].set()

    def _loop(self):
        while not self._stop.wait(self.poll_s):
            if self.on_poll is not None:
                try:
                    self.on_poll()
                except Exception:
                    pass
            self.check_now()
