"""Replica serving worker: one HTTP process per fleet member (ISSUE 19).

Each replica wraps the PR-8 hardened ``ServingRuntime`` (batcher,
breaker, watchdog, outcome ledger) behind a tiny stdlib HTTP surface —
the same no-new-dependency stance as the PR-10 exporter:

- ``POST /infer``  — one request: JSON ``{"feed": {...}, "deadline_s"}``
  with an optional W3C ``traceparent`` header the runtime joins, so one
  request's span tree covers router + replica (ISSUE 18 groundwork).
- ``GET /healthz`` — the exporter's health verdict plus replica state:
  503 while DRAINING (the router stops routing, in-flight work
  completes) or while a swap warms the incoming version.
- ``GET /stats``   — per-version outcome ledgers + the merged replica
  ledger (``requests == sum(outcomes)`` across every runtime this
  process ever ran), current version, serving compile-event count, AOT
  import/export tallies — what the router scrapes for the fleet ledger.
- ``GET /metrics`` — the full Prometheus scrape (exporter.prometheus_text).
- ``GET /trace``   — retained span trees, so the bench can join a
  router-side tree to this replica's spans by trace id.
- ``POST /swap``   — hot-swap to ``{"version": N}`` from the registry.

Hot-swap is ZERO-DROP by construction: the incoming version is built
and warmed (AOT cache import when the registry has artifacts for this
device kind, ledgered compiles otherwise — and the first warmer
publishes the artifacts back) BEFORE the atomic flip; only then is the
outgoing runtime closed, whose ``close()`` drains the queue — the
batcher keeps dispatching until the queue is empty before failing
anything.  A request that races the flip into a closing runtime is
resubmitted once on the new one.

Chaos: the request path visits ``faultinject.kill_point("replica.infer")``
so an armed worker dies mid-request via ``os._exit(1)`` — the router
sees a reset socket, classifies it PREEMPTION, and fails over.
"""

import argparse
import http.server
import json
import os
import threading

import numpy as np

from ..inference import Predictor
from ..resilience import faultinject
from ..resilience.taxonomy import classify, is_transient
from .registry import ModelRegistry
from .runtime import (DeadlineExceeded, QueueFullError,
                      ServingClosedError, ServingRuntime)

__all__ = ["ModelHost", "ReplicaServer", "main"]


def _mon():
    from .. import monitor

    return monitor


def _device_kind():
    try:
        import jax

        return jax.devices()[0].device_kind
    except Exception:
        return "unknown"


def _serving_compile_events():
    """Compile-ledger events attributed to serving bucket warms — the
    number the cold-start acceptance pins at ZERO after an AOT import."""
    mon = _mon()
    try:
        return [e for e in mon.compile_events()
                if str(e.get("key", "")).startswith("serving/")]
    except Exception:
        return []


class ModelHost:
    """Owns the replica's active (version, ServingRuntime) pair and the
    per-version ledger history; performs zero-drop hot swaps."""

    def __init__(self, registry, name="replica", config_kw=None):
        self.registry = registry if isinstance(registry, ModelRegistry) \
            else ModelRegistry(registry)
        self.name = name
        self._config_kw = dict(config_kw or {})
        self._flip_lock = threading.Lock()   # guards the active pair
        self._swap_lock = threading.Lock()   # serializes swaps
        self._runtime = None
        self._version = None
        self._history = []    # [(version, ServingStats)] — every runtime
        self.aot_imported = 0
        self.aot_exported = 0
        self.swaps = 0

    # -- lifecycle ------------------------------------------------------
    @property
    def version(self):
        return self._version

    @property
    def runtime(self):
        return self._runtime

    def _build_runtime(self, version):
        """Build + WARM a runtime for `version`: import the AOT cache
        when the registry has artifacts for this device kind (zero
        compile-ledger events), compile through the ledger otherwise —
        and publish the artifacts back so the NEXT cold replica wins."""
        pred = Predictor(self.registry.version_dir(version))
        kind = _device_kind()
        kw = dict(self._config_kw)
        kw.setdefault("label", f"{self.name}/v{version}")
        kw["prewarm"] = False
        rt = ServingRuntime(pred, **kw)
        if self.registry.has_aot(version, kind):
            self.aot_imported += rt.dispatcher.import_aot(
                self.registry.aot_dir(version, kind))
        # warm whatever the cache did not cover (everything, on a cache
        # miss) through the compile ledger, BEFORE the flip
        rt.prewarmed = rt.dispatcher.prewarm()
        if rt.prewarmed:
            try:
                self.aot_exported += self.registry.publish_aot(
                    version, kind, rt.dispatcher.export_aot)
            except Exception:
                pass          # a torn cache write must not fail a swap
        return rt

    def start(self, version=None):
        if version is None:
            version = self.registry.current()
        if version is None:
            version = self.registry.latest()
        if version is None:
            raise ValueError("registry has no published versions")
        rt = self._build_runtime(int(version))
        with self._flip_lock:
            self._runtime, self._version = rt, int(version)
        self._history.append((int(version), rt.stats))
        return self._version

    def swap_to(self, version):
        """Hot-swap to `version`: build + warm the new runtime, flip
        atomically, THEN drain the old one (its close() serves the
        whole queue before failing anything) — zero dropped requests,
        asserted fleet-wide via the merged outcome ledger."""
        version = int(version)
        with self._swap_lock:
            old_version = self._version
            if version == old_version:
                return old_version
            rt = self._build_runtime(version)
            self._history.append((version, rt.stats))
            with self._flip_lock:
                old, self._runtime = self._runtime, rt
                self._version = version
            self.swaps += 1
            mon = _mon()
            if mon.is_enabled():
                mon.counter("fleet.model_swap").add(1)
            if old is not None:
                old.close(timeout=30.0)
            return old_version

    def close(self, timeout=10.0):
        with self._flip_lock:
            rt, self._runtime = self._runtime, None
        if rt is not None:
            rt.close(timeout=timeout)

    # -- request path ---------------------------------------------------
    def run(self, feed, deadline_s=None, timeout=None, traceparent=None):
        """One request through the ACTIVE runtime.  A submit that races
        a swap's flip into the closing runtime is resubmitted once on
        the new one — the drain contract still resolves everything that
        made it into the old queue."""
        for attempt in (0, 1):
            with self._flip_lock:
                rt = self._runtime
            if rt is None:
                raise ServingClosedError("replica is shut down")
            try:
                return rt.run(feed, deadline_s=deadline_s,
                              timeout=timeout, traceparent=traceparent)
            except ServingClosedError:
                if attempt:
                    raise
        raise AssertionError("unreachable")

    # -- ledgers --------------------------------------------------------
    def merged_ledger(self):
        """The replica-wide outcome ledger: requests/outcomes summed
        over EVERY runtime this process ran (drained versions keep
        their final counts) — the per-replica row of the fleet merge."""
        requests = 0
        outcomes = {}
        per_version = []
        for version, stats in self._history:
            s = stats.summary()
            requests += s["requests"]
            for k, v in s["outcomes"].items():
                outcomes[k] = outcomes.get(k, 0) + v
            per_version.append({"version": version, "key": s["key"],
                                "requests": s["requests"],
                                "outcomes": s["outcomes"],
                                "pending": s["pending"]})
        resolved = sum(outcomes.values())
        return {"requests": requests, "outcomes": outcomes,
                "resolved": resolved, "pending": requests - resolved,
                "per_version": per_version}

    def stats_doc(self):
        active = None
        with self._flip_lock:
            rt, version = self._runtime, self._version
        if rt is not None:
            active = rt.summary()
        return {
            "name": self.name,
            "version": version,
            "device_kind": _device_kind(),
            "merged": self.merged_ledger(),
            "active": active,
            "swaps": self.swaps,
            "aot_imported": self.aot_imported,
            "aot_exported": self.aot_exported,
            "serving_compile_events": len(_serving_compile_events()),
        }


def _make_handler(server):
    class _ReplicaHandler(http.server.BaseHTTPRequestHandler):
        def _reply(self, code, doc):
            body = json.dumps(doc, sort_keys=True).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_text(self, code, body, ctype):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 — http.server contract
            path = self.path.split("?", 1)[0]
            if path == "/healthz":
                code, doc = server.health_doc()
                self._reply(code, doc)
            elif path == "/stats":
                self._reply(200, server.host.stats_doc())
            elif path == "/trace":
                from ..monitor import tracing

                self._reply(200,
                            {"trees": tracing.get().retained_trees()})
            elif path == "/metrics":
                from ..monitor import exporter

                try:
                    body = exporter.prometheus_text().encode()
                except Exception as e:  # noqa: BLE001 — scrape safety
                    self._reply_text(500, f"# scrape failed: {e}\n"
                                     .encode(), "text/plain")
                    return
                self._reply_text(
                    200, body,
                    "text/plain; version=0.0.4; charset=utf-8")
            else:
                self._reply(404, {"error": "not found"})

        def do_POST(self):  # noqa: N802 — http.server contract
            path = self.path.split("?", 1)[0]
            try:
                length = int(self.headers.get("Content-Length") or 0)
                doc = json.loads(self.rfile.read(length) or b"{}")
            except Exception as e:
                self._reply(400, {"error": f"bad request body: {e}",
                                  "kind": "fatal"})
                return
            if path == "/infer":
                self._infer(doc)
            elif path == "/swap":
                self._swap(doc)
            else:
                self._reply(404, {"error": "not found"})

        def _infer(self, doc):
            if server.draining:
                self._reply(503, {"error": "replica is draining",
                                  "kind": "draining"})
                return
            # the chaos kill lands HERE: the request is in flight from
            # the router's point of view, so the death surfaces as a
            # mid-request connection reset — the failover shape
            faultinject.kill_point("replica.infer")
            try:
                feed = {k: np.asarray(v)
                        for k, v in (doc.get("feed") or {}).items()}
                outs = server.host.run(
                    feed, deadline_s=doc.get("deadline_s"),
                    traceparent=self.headers.get("traceparent"))
                self._reply(200, {
                    "outputs": [np.asarray(o).tolist() for o in outs],
                    "version": server.host.version,
                    "replica": server.host.name})
            except DeadlineExceeded as e:
                self._reply(504, {"error": str(e), "kind": "deadline"})
            except QueueFullError as e:
                self._reply(503, {"error": str(e), "kind": "overload"})
            except ServingClosedError as e:
                self._reply(503, {"error": str(e), "kind": "closed"})
            except Exception as e:  # noqa: BLE001 — classified reply
                self._reply(500, {
                    "error": f"{type(e).__name__}: {e}"[:500],
                    "kind": ("transient" if is_transient(e)
                             else classify(e))})

        def _swap(self, doc):
            try:
                version = int(doc["version"])
            except (KeyError, TypeError, ValueError):
                self._reply(400, {"error": "body must carry an integer "
                                           "'version'", "kind": "fatal"})
                return
            try:
                previous = server.host.swap_to(version)
            except Exception as e:  # noqa: BLE001 — classified reply
                self._reply(500, {
                    "error": f"{type(e).__name__}: {e}"[:500],
                    "kind": classify(e)})
                return
            self._reply(200, {"version": server.host.version,
                              "previous": previous})

        def log_message(self, *args):  # requests are not app logs
            pass

    return _ReplicaHandler


class ReplicaServer:
    """One replica process's HTTP front: a daemon-threaded stdlib
    server around a ModelHost.  ``port=0`` binds ephemeral (callers
    read ``.port`` back) — runnable in-process for tests or as the
    subprocess worker via ``python -m paddle_tpu.serving.replica``."""

    def __init__(self, registry, name="replica", host="127.0.0.1",
                 port=0, version=None, config_kw=None):
        self.host_model = self.host = ModelHost(registry, name=name,
                                                config_kw=config_kw)
        self.host.start(version)
        self.draining = False
        self._httpd = http.server.ThreadingHTTPServer(
            (host, port), _make_handler(self))
        self._httpd.daemon_threads = True
        self.addr = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name=f"paddle_tpu-replica-{name}", daemon=True)
        self._thread.start()

    @property
    def base_url(self):
        return f"http://{self.addr}:{self.port}"

    def health_doc(self):
        """(status code, body) for /healthz: the exporter's fleet-wide
        verdict plus replica drain state — 503 tells the router to stop
        routing here while in-flight work completes."""
        from ..monitor import exporter

        if self.draining:
            return 503, {"ok": False, "reason": "draining",
                         "replica": self.host.name,
                         "version": self.host.version}
        ok, checks = exporter.health()
        doc = {"ok": ok, "checks": checks, "replica": self.host.name,
               "version": self.host.version}
        if not ok:
            doc["reason"] = exporter._health_reason(checks)
        return (200 if ok else 503), doc

    def drain(self):
        self.draining = True

    def close(self, timeout=10.0):
        """Graceful: stop routing (the socket closes), then drain the
        runtime — every queued request resolves before shutdown."""
        self.draining = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)
        self.host.close(timeout=timeout)

    def kill(self):
        """Abrupt in-process death for tests: the socket goes away
        without draining anything — connections reset, exactly what a
        killed process looks like from the router (the REAL kill is
        faultinject.kill_point in the subprocess worker)."""
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)


def _write_endpoint_file(path, doc):
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def main(argv=None):
    """Subprocess worker entry (``python -m paddle_tpu.serving.replica``):
    serve one replica until killed.  Writes an endpoint file (atomic)
    once the socket is bound so the spawner can discover the ephemeral
    port; ``--kill-point`` arms the replica-kill chaos primitive."""
    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--registry", required=True)
    ap.add_argument("--name", default="replica")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--version", type=int, default=None)
    ap.add_argument("--endpoint-file", default=None)
    ap.add_argument("--telemetry", default=None,
                    help="enable monitor with this JSONL path")
    ap.add_argument("--max-batch", type=int, default=8)
    ap.add_argument("--kill-point", default=None, metavar="NAME:HIT",
                    help="arm faultinject kill_points={NAME: HIT}")
    args = ap.parse_args(argv)

    from .. import monitor

    if args.telemetry:
        monitor.reset()
        monitor.enable(jsonl_path=args.telemetry)
    else:
        monitor.enable()
    if args.kill_point:
        name, _, hit = args.kill_point.partition(":")
        faultinject.arm(kill_points={name: int(hit or 0)})

    srv = ReplicaServer(args.registry, name=args.name, host=args.host,
                        port=args.port, version=args.version,
                        config_kw={"max_batch_size": args.max_batch})
    if args.endpoint_file:
        _write_endpoint_file(args.endpoint_file, {
            "name": args.name, "host": args.host, "port": srv.port,
            "pid": os.getpid(), "version": srv.host.version})
    threading.Event().wait()      # serve until the spawner kills us


if __name__ == "__main__":
    main()
