"""paddle_tpu.serving — hardened inference serving runtime (ISSUE 8).

Layers a resilient request path over the inference engines
(`Predictor` / `CompiledPredictor`):

- **Dynamic micro-batching** into pre-warmed padded bucket shapes
  (`bucketing.py`) — no recompile storm, bitwise-equal results.
- **Admission control** — bounded queue + per-request deadlines;
  overload degrades to bounded latency (classified sheds and
  backpressure rejections), never unbounded queueing.
- **Circuit breaker + jittered retry** around the batched dispatch,
  reusing `resilience/retry.py` and the error taxonomy; while open,
  a degraded-mode fallback (smallest bucket or the eager interpreter)
  keeps serving.
- **Hang watchdog** — a stalled dispatch triggers a flight-recorder
  post-mortem with the in-flight batch's metadata, then escalates
  (classified failure or cancel-and-retry).

ISSUE 17 adds `decode.py`: a slot-based continuous-batching DECODE
engine on the same hardening stack — one donated-state compiled decode
step over a fixed slot×max_len KV ring buffer, per-bucket prefill
refills without retracing, per-TOKEN deadline budgets, and
tokens/s / TTFT / occupancy observability (`DecodeStats`).

ISSUE 19 adds the FLEET tier: `registry.py` (versioned model registry
with atomic `_COMPLETE`-markered publishes + per-version AOT artifact
cache), `replica.py` (a replica worker hosting one runtime per model
version with zero-drop hot-swap, behind an HTTP surface), and
`fleet.py` (a health-gated router with per-replica breakers,
classified failover, and a merged requests==sum(outcomes) fleet
ledger).

Observability: exact p50/p99 latency, queue-depth/in-flight gauges,
`resilience.*` shed/retry/breaker/watchdog counters, per-request spans
in the merged Chrome trace, `monitor.serving_table()`, and
kind="serving" records on the telemetry JSONL stream and in flight
dumps (tools/telemetry_report.py renders both).
"""

from .bucketing import (BucketDispatcher, default_buckets,  # noqa: F401
                        pick_bucket)
from .decode import (DecodeConfig, DecodeEngine,            # noqa: F401
                     EngineBrokenError, default_prompt_buckets)
from .fleet import (FleetRouter, NoReplicaAvailable,        # noqa: F401
                    ReplicaHandle, ReplicaRequestError,
                    ReplicaUnavailable, router_table)
from .registry import ModelRegistry, RegistryError          # noqa: F401
from .replica import ModelHost, ReplicaServer               # noqa: F401
from .runtime import (DeadlineExceeded, QueueFullError,     # noqa: F401
                      ServingClosedError, ServingConfig,
                      ServingFuture, ServingRuntime)
from .stats import DecodeStats, ServingStats, serving_table  # noqa: F401
from .watchdog import HangWatchdog, WatchdogStall           # noqa: F401

__all__ = [
    "ServingRuntime", "ServingConfig", "ServingFuture",
    "DecodeEngine", "DecodeConfig", "DecodeStats",
    "EngineBrokenError", "default_prompt_buckets",
    "QueueFullError", "ServingClosedError", "DeadlineExceeded",
    "WatchdogStall", "HangWatchdog", "ServingStats", "serving_table",
    "BucketDispatcher", "default_buckets", "pick_bucket",
    "FleetRouter", "ReplicaHandle", "NoReplicaAvailable",
    "ReplicaUnavailable", "ReplicaRequestError", "router_table",
    "ModelRegistry", "RegistryError", "ModelHost", "ReplicaServer",
]
