"""Serving observability: exact latency percentiles + outcome ledger.

Every request that enters a ServingRuntime ends in EXACTLY one of the
outcome buckets below — completed, shed (deadline expired in queue),
expired (deadline passed in flight), rejected (backpressure at
enqueue), failed (classified dispatch error), stalled (watchdog
escalation), cancelled (runtime closed) — so `requests ==
sum(outcomes)` is an invariant the chaos smoke asserts: a serving
runtime that silently loses a request has failed at its one job.

Latency percentiles are EXACT nearest-rank over the recorded samples
(bounded ring, default 8192): `p(q) = sorted[ceil(q*n)-1]`.  No
histogram buckets, no interpolation — the smoke row recomputes p99
from the raw samples and asserts equality with the table's number.

WINDOW SEMANTICS: the sample rings are bounded (`deque(maxlen=8192)`),
so under long traffic the oldest samples fall out — percentiles are
exact over the NEWEST <= 8192 samples, a sliding window, not the full
run.  Evictions are counted (`samples_dropped` in the latency tables
and the serving record), so a reader can tell a complete distribution
from a windowed one instead of being silently lied to.  The outcome
LEDGER is never windowed — counts are cumulative forever.

Counters are double-booked like the flight recorder's: gate-free local
fields (the serving table must work with telemetry off) plus
`resilience.*`/`serving.*` monitor counters while telemetry is on.
"""

import collections
import math
import threading
import weakref

__all__ = ["ServingStats", "DecodeStats", "exact_percentile",
           "serving_table", "all_stats"]

_SAMPLE_CAP = 8192

# live runtimes' stats, keyed by label — what monitor.serving_table()
# reads.  Weak values: a dropped runtime leaves the table (its final
# numbers persist in the telemetry JSONL / flight dump it emitted).
_REGISTRY = weakref.WeakValueDictionary()
_registry_lock = threading.Lock()


def exact_percentile(sorted_samples, q):
    """Nearest-rank percentile: the smallest recorded sample >= q of
    the distribution — an ACTUAL sample, never an interpolation, so
    re-deriving it from the raw samples is equality, not allclose."""
    n = len(sorted_samples)
    if not n:
        return None
    rank = max(1, math.ceil(q * n))
    return sorted_samples[min(n, rank) - 1]


def _mon():
    from .. import monitor

    return monitor


OUTCOMES = ("completed", "shed", "expired", "rejected", "failed",
            "stalled", "cancelled")


class ServingStats:
    """One runtime's gate-free outcome ledger + latency samples."""

    def __init__(self, label="serving", register=True):
        self.label = label
        self._lock = threading.Lock()
        self._outcomes = {k: 0 for k in OUTCOMES}
        self.requests = 0
        self.batches = 0
        self.padded_rows = 0
        self.dispatched_rows = 0
        self.degraded = 0
        self.retries = 0
        self.watchdog_stalls = 0
        self.cancel_retries = 0
        self._samples = collections.deque(maxlen=_SAMPLE_CAP)
        self.samples_dropped = 0      # ring evictions (window honesty)
        self._buckets = {}            # bucket size -> dispatch count
        self._breaker = None          # CircuitBreaker, set by runtime
        self._watchdog = None         # HangWatchdog, set by watchdog
        self.queue_depth = 0
        self.in_flight = 0
        if register:
            with _registry_lock:
                _REGISTRY[label] = self

    def attach_breaker(self, breaker):
        self._breaker = breaker

    def attach_watchdog(self, watchdog):
        """Back-link set by HangWatchdog so the summary (and /healthz)
        can see a CURRENTLY-wedged dispatch, not just the stall count
        it left behind."""
        self._watchdog = weakref.ref(watchdog)

    # -- recording ------------------------------------------------------
    def note_admitted(self, depth):
        with self._lock:
            self.requests += 1
            self.queue_depth = depth
        mon = _mon()
        if mon.is_enabled():
            mon.counter("serving.requests").add(1)
            mon.gauge("serving.queue_depth").set(depth)

    def note_queue_depth(self, depth):
        with self._lock:
            self.queue_depth = depth
        mon = _mon()
        if mon.is_enabled():
            mon.gauge("serving.queue_depth").set(depth)

    def note_in_flight(self, n):
        with self._lock:
            self.in_flight = n
        mon = _mon()
        if mon.is_enabled():
            mon.gauge("serving.in_flight").set(n)

    def note_outcome(self, outcome, latency_s=None):
        """Terminal state of one request.  `rejected` requests never
        counted as admitted, so they increment `requests` here — the
        invariant stays sum(outcomes) == requests."""
        with self._lock:
            self._outcomes[outcome] += 1
            if outcome == "rejected":
                self.requests += 1
            if latency_s is not None:
                if len(self._samples) == self._samples.maxlen:
                    self.samples_dropped += 1
                self._samples.append(float(latency_s))
        mon = _mon()
        if mon.is_enabled():
            name = {"completed": "serving.completed",
                    "shed": "resilience.serving_shed",
                    "expired": "resilience.serving_expired",
                    "rejected": "resilience.serving_rejected",
                    "failed": "resilience.serving_failed",
                    "stalled": "resilience.serving_stalled",
                    "cancelled": "resilience.serving_cancelled"}[outcome]
            mon.counter(name).add(1)

    def note_batch(self, bucket, rows, degraded=False):
        """One dispatched batch.  bucket=None means the dispatch went
        through a NON-bucketed path (the degraded eager interpreter):
        it counts as a batch but must not invent a bucket key in the
        bucket-mix observability."""
        with self._lock:
            self.batches += 1
            self.dispatched_rows += rows
            if bucket is not None:
                self.padded_rows += max(0, bucket - rows)
                self._buckets[bucket] = self._buckets.get(bucket, 0) + 1
            if degraded:
                self.degraded += 1
        mon = _mon()
        if mon.is_enabled():
            mon.counter("serving.batches").add(1)
            if bucket is not None:
                mon.counter(f"serving.bucket_{bucket}").add(1)
            if degraded:
                mon.counter("resilience.serving_degraded").add(1)

    def note_retry(self):
        with self._lock:
            self.retries += 1

    def note_watchdog_stall(self):
        with self._lock:
            self.watchdog_stalls += 1
        mon = _mon()
        if mon.is_enabled():
            mon.counter("resilience.watchdog_stalls").add(1)

    def note_cancel_retry(self):
        with self._lock:
            self.cancel_retries += 1
        mon = _mon()
        if mon.is_enabled():
            mon.counter("resilience.watchdog_cancel_retry").add(1)

    # -- reading --------------------------------------------------------
    def samples(self):
        with self._lock:
            return list(self._samples)

    def latency(self):
        """Exact latency stats over the recorded end-to-end samples —
        the newest <= maxlen window (see module docstring); the
        `samples_dropped` field counts what the window evicted."""
        with self._lock:
            dropped = self.samples_dropped
            s = sorted(self._samples)
        if not s:
            return None
        out = {
            "count": len(s),
            "mean_ms": round(sum(s) / len(s) * 1e3, 3),
            "p50_ms": round(exact_percentile(s, 0.50) * 1e3, 3),
            "p99_ms": round(exact_percentile(s, 0.99) * 1e3, 3),
            "max_ms": round(s[-1] * 1e3, 3),
        }
        if dropped:
            out["samples_dropped"] = dropped
        return out

    def summary(self):
        """json-safe serving-table row: outcomes, invariant check,
        latency percentiles, bucket mix, breaker + watchdog state."""
        with self._lock:
            outcomes = dict(self._outcomes)
            out = {
                "key": self.label,
                "requests": self.requests,
                "outcomes": outcomes,
                "resolved": sum(outcomes.values()),
                "pending": self.requests - sum(outcomes.values()),
                "batches": self.batches,
                "dispatched_rows": self.dispatched_rows,
                "padded_rows": self.padded_rows,
                "buckets": {str(k): v
                            for k, v in sorted(self._buckets.items())},
                "degraded_batches": self.degraded,
                "dispatch_retries": self.retries,
                "watchdog_stalls": self.watchdog_stalls,
                "cancel_retries": self.cancel_retries,
                "queue_depth": self.queue_depth,
                "in_flight": self.in_flight,
            }
        lat = self.latency()
        if lat:
            out["latency"] = lat
        if self._breaker is not None:
            out["breaker"] = self._breaker.summary()
        wd = self._watchdog() if self._watchdog is not None else None
        if wd is not None:
            out["stalled_in_flight"] = wd.stalled_now()
        return out

    def to_record(self):
        """The kind="serving" telemetry record — one line on the JSONL
        stream / flight dump, same shape the report tool parses."""
        rec = {"kind": "serving"}
        rec.update(self.summary())
        return rec


class DecodeStats(ServingStats):
    """The decode engine's ledger: everything ServingStats keeps (the
    outcome invariant, end-to-end latency samples, breaker/watchdog
    links) plus the token-level series continuous batching is judged
    by — tokens/s, time-to-first-token, inter-token latency, slot
    occupancy, prefill-vs-decode step split.

    TTFT and per-token latencies ride the SAME exact nearest-rank
    percentile machinery as request latency (bounded sample rings,
    `exact_percentile`) — no new estimator, so the smoke row can
    recompute any published percentile from the raw samples and assert
    equality."""

    def __init__(self, label="decode", slots=0, register=True):
        super().__init__(label, register=register)
        self.slots = int(slots)
        self.tokens_total = 0
        self.prefill_steps = 0
        self.decode_steps = 0
        self._occupancy_sum = 0.0      # sum of active/slots per step
        self._ttft = collections.deque(maxlen=_SAMPLE_CAP)
        self.ttft_dropped = 0
        self._tok_lat = collections.deque(maxlen=_SAMPLE_CAP)
        self.tok_lat_dropped = 0
        self._first_t = None           # first/last token wall-clock
        self._last_t = None            # (engine clock) for tokens/s

    # -- recording ------------------------------------------------------
    def note_prefill(self, ttft_s=None, now=None):
        """One prefill dispatch; ttft_s is the submitting request's
        enqueue->first-token latency."""
        with self._lock:
            self.prefill_steps += 1
            if ttft_s is not None:
                if len(self._ttft) == self._ttft.maxlen:
                    self.ttft_dropped += 1
                self._ttft.append(float(ttft_s))
            if now is not None:
                if self._first_t is None:
                    self._first_t = now
                self._last_t = now
        mon = _mon()
        if mon.is_enabled():
            mon.counter("serving.decode_prefills").add(1)

    def note_decode_step(self, active, emitted, now=None):
        """One decode-step dispatch: `active` slots were live going in,
        `emitted` tokens landed on live requests coming out."""
        with self._lock:
            self.decode_steps += 1
            self.tokens_total += int(emitted)
            if self.slots:
                self._occupancy_sum += active / self.slots
            if now is not None:
                if self._first_t is None:
                    self._first_t = now
                self._last_t = now
        mon = _mon()
        if mon.is_enabled():
            mon.counter("serving.decode_steps").add(1)
            mon.counter("serving.decode_tokens").add(int(emitted))
            if self.slots:
                mon.gauge("serving.decode_active_slots").set(active)

    def note_token_latency(self, latency_s):
        with self._lock:
            if len(self._tok_lat) == self._tok_lat.maxlen:
                self.tok_lat_dropped += 1
            self._tok_lat.append(float(latency_s))

    # -- reading --------------------------------------------------------
    def _percentiles(self, ring, dropped=0):
        s = sorted(ring)
        if not s:
            return None
        out = {
            "count": len(s),
            "mean_ms": round(sum(s) / len(s) * 1e3, 3),
            "p50_ms": round(exact_percentile(s, 0.50) * 1e3, 3),
            "p99_ms": round(exact_percentile(s, 0.99) * 1e3, 3),
            "max_ms": round(s[-1] * 1e3, 3),
        }
        if dropped:
            out["samples_dropped"] = dropped
        return out

    def ttft_samples(self):
        with self._lock:
            return list(self._ttft)

    def token_latency_samples(self):
        with self._lock:
            return list(self._tok_lat)

    def decode_summary(self):
        with self._lock:
            steps = self.decode_steps
            out = {
                "slots": self.slots,
                "tokens_total": self.tokens_total,
                "prefill_steps": self.prefill_steps,
                "decode_steps": steps,
                "slot_occupancy_mean": (
                    round(self._occupancy_sum / steps, 4) if steps
                    and self.slots else None),
            }
            span = (self._last_t - self._first_t
                    if self._first_t is not None
                    and self._last_t is not None else None)
            ttft_ring = list(self._ttft)
            ttft_dropped = self.ttft_dropped
            tok_ring = list(self._tok_lat)
            tok_dropped = self.tok_lat_dropped
        if span and span > 0:
            out["tokens_per_s"] = round(out["tokens_total"] / span, 2)
        ttft = self._percentiles(ttft_ring, dropped=ttft_dropped)
        if ttft:
            out["ttft"] = ttft
        tok = self._percentiles(tok_ring, dropped=tok_dropped)
        if tok:
            out["token_latency"] = tok
        return out

    def summary(self):
        out = super().summary()
        out["decode"] = self.decode_summary()
        return out


def all_stats():
    with _registry_lock:
        return dict(_REGISTRY)


def serving_table():
    """One summary row per live ServingRuntime (newest state, exact
    percentiles) — what monitor.serving_table() returns and
    snapshot()["serving"] embeds."""
    return [s.summary() for s in all_stats().values()]
