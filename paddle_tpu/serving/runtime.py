"""ServingRuntime — the hardened request path over Predictor /
CompiledPredictor (ISSUE 8 tentpole).

A bare `Predictor.run` is a synchronous call: one slow dispatch stalls
every caller, overload queues without bound, and a hang produces no
forensics.  This runtime wraps the same engines in the four layers a
"serve heavy traffic" path needs:

1. **Dynamic micro-batching** (bucketing.py): concurrent requests
   coalesce into a small set of pre-warmed padded bucket shapes; no
   recompile storm, padding sliced off before results leave.
2. **Admission control**: a bounded queue with per-request deadlines —
   budget expired in queue => shed with a classified DeadlineExceeded;
   queue full => enqueue rejects with QueueFullError (backpressure).
   Overload degrades to bounded latency, never unbounded queueing.
3. **Circuit breaker + jittered retry** (resilience/breaker.py +
   retry.py): transients are retried with backoff; N consecutive
   classified failures open the breaker, which then fails fast and
   serves through the degraded path (smallest bucket or the eager
   interpreter) until a half-open probe heals it.
4. **Hang watchdog** (watchdog.py): any dispatch in flight past the
   stall threshold triggers a flight-recorder dump with the batch's
   metadata, then escalates per policy — fail the batch with a
   classified WatchdogStall, or abandon the wedged call and retry.

Every request ends in exactly one classified outcome (stats.py keeps
the ledger; the chaos smoke asserts zero silent losses), latencies are
exact-percentile, and per-request/batch spans land in the merged
Chrome trace while profiling is on.

Usage::

    from paddle_tpu.serving import ServingRuntime
    rt = ServingRuntime(Predictor(model_dir), max_batch_size=8,
                        default_deadline_s=0.5)
    fut = rt.submit({"x": batch})          # non-blocking
    outs = fut.result()                    # or rt.run(feed) to block
    rt.close()
"""

import threading
import time
from collections import deque

import numpy as np

from .. import flags
from ..resilience import faultinject
from ..resilience.breaker import CircuitBreaker, CircuitOpenError
from ..resilience.retry import RetryPolicy, call_with_retry
from ..resilience.taxonomy import DeadlineExceeded
from .bucketing import BucketDispatcher, pick_bucket
from .stats import ServingStats
from .watchdog import HangWatchdog, WatchdogStall

__all__ = ["ServingConfig", "ServingRuntime", "ServingFuture",
           "QueueFullError", "ServingClosedError", "WatchdogStall",
           "DeadlineExceeded"]

_DEFAULT_RETRY = object()


class QueueFullError(RuntimeError):
    """Admission control rejected the request: the bounded queue is at
    depth.  This is BACKPRESSURE — the caller should shed or slow
    down; retrying immediately is exactly wrong, so the taxonomy
    classifies it fatal."""


class ServingClosedError(RuntimeError):
    """The runtime is closed (or closing); the request was not (or can
    no longer be) served."""


class ServingConfig:
    """Knobs for one runtime.  Flag-backed defaults so a fleet can
    retune without code changes; everything injectable for tests."""

    def __init__(self, max_batch_size=8, buckets=None,
                 max_queue_depth=None, default_deadline_s=None,
                 batch_window_s=0.002, retry_policy=_DEFAULT_RETRY,
                 breaker_threshold=5, breaker_cooldown_s=5.0,
                 watchdog_stall_s=None, watchdog_poll_s=None,
                 watchdog_policy="raise", degraded_mode="eager",
                 prewarm=True, label="serving", clock=time.monotonic):
        self.max_batch_size = int(max_batch_size)
        self.buckets = buckets
        self.max_queue_depth = int(
            max_queue_depth if max_queue_depth is not None
            else flags.flag("serving_queue_depth"))
        if default_deadline_s is None:
            default_deadline_s = flags.flag("serving_deadline_s") or None
        self.default_deadline_s = default_deadline_s
        self.batch_window_s = float(batch_window_s)
        if retry_policy is _DEFAULT_RETRY:
            retry_policy = RetryPolicy(max_retries=2, base_delay=0.02,
                                       max_delay=0.5, seed=0)
        self.retry_policy = retry_policy          # None disables retry
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.watchdog_stall_s = float(
            watchdog_stall_s if watchdog_stall_s is not None
            else flags.flag("serving_watchdog_stall_s"))
        self.watchdog_poll_s = watchdog_poll_s
        if watchdog_policy not in ("raise", "cancel_retry"):
            raise ValueError("watchdog_policy must be 'raise' or "
                             "'cancel_retry'")
        self.watchdog_policy = watchdog_policy
        if degraded_mode not in ("eager", "smallest_bucket", "fail"):
            raise ValueError("degraded_mode must be 'eager', "
                             "'smallest_bucket' or 'fail'")
        self.degraded_mode = degraded_mode
        self.prewarm = bool(prewarm)
        self.label = label
        self.clock = clock


class ServingFuture:
    """Resolution handle for one submitted request: exactly one of
    result/exception, set once, visible to any thread."""

    __slots__ = ("_event", "_result", "_error")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._error = None

    def _set_result(self, value):
        if self._event.is_set():
            return False
        self._result = value
        self._event.set()
        return True

    def _set_exception(self, exc):
        if self._event.is_set():
            return False
        self._error = exc
        self._event.set()
        return True

    def done(self):
        return self._event.is_set()

    def exception(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("serving request not resolved yet")
        return self._error

    def result(self, timeout=None):
        if not self._event.wait(timeout):
            raise TimeoutError("serving request not resolved yet")
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    __slots__ = ("prepared", "rows", "enqueue_t", "enqueue_pc_ns",
                 "deadline", "budget_s", "future", "rid", "trace",
                 "qspan")

    def __init__(self, prepared, rows, enqueue_t, deadline, budget_s,
                 rid, trace=None):
        self.prepared = prepared
        self.rows = rows
        self.enqueue_t = enqueue_t
        self.enqueue_pc_ns = time.perf_counter_ns()
        self.deadline = deadline
        self.budget_s = budget_s
        self.future = ServingFuture()
        self.rid = rid
        # request-scoped trace context (monitor/tracing.py); None when
        # FLAGS_request_tracing is off — every touch downstream guards
        # on that None, so the off path is one attribute read
        self.trace = trace
        self.qspan = None

    def expired(self, now):
        return self.deadline is not None and now >= self.deadline


def _fr():
    from ..monitor import flight_recorder

    return flight_recorder


def _tracing():
    from ..monitor import tracing

    return tracing


def _mon():
    from .. import monitor

    return monitor


def _profiler():
    import sys

    return sys.modules.get("paddle_tpu.profiler")


class ServingRuntime:
    """See module docstring.  `auto_start=False` keeps the batcher
    thread off so tests drive batching deterministically through
    `process_once()`."""

    def __init__(self, predictor, config=None, auto_start=True, **kw):
        self.config = cfg = config or ServingConfig(**kw)
        if config is not None and kw:
            raise TypeError("pass either config= or keyword knobs, "
                            "not both")
        self.dispatcher = BucketDispatcher(
            predictor, buckets=cfg.buckets,
            max_batch=cfg.max_batch_size, label=cfg.label)
        self.stats = ServingStats(cfg.label)
        self.breaker = CircuitBreaker(
            failure_threshold=cfg.breaker_threshold,
            cooldown_s=cfg.breaker_cooldown_s, clock=cfg.clock,
            name=cfg.label)
        self.stats.attach_breaker(self.breaker)
        self.watchdog = HangWatchdog(
            cfg.watchdog_stall_s, poll_s=cfg.watchdog_poll_s,
            clock=cfg.clock, stats=self.stats, label=cfg.label,
            pre_dump=self._note_serving, on_poll=self.sweep_expired)
        self._queue = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._batcher = None
        self._rid = 0
        # every admitted-but-unresolved request, queued OR in flight —
        # close() fails whatever is left here, so no future can stay
        # pending past shutdown even with the batcher wedged
        self._live = set()
        self.prewarmed = self.dispatcher.prewarm() if cfg.prewarm else 0
        # the predictor's load-time graph-optimizer report (conv+BN
        # folds, identity collapses — FLAGS_inference_fold), surfaced
        # on the runtime.  NOT re-recorded into the pass ledger: the
        # Predictor already emitted the kind="pass_pipeline" record at
        # load time, and a second key would double-count the same fold
        # work in telemetry_report's Passes section.
        self.fold_report = getattr(predictor, "_fold_report", None)
        if auto_start:
            self.start()

    # -- lifecycle ------------------------------------------------------
    def start(self):
        # live /metrics exporter (ISSUE 10): serving shares the same
        # session-entry hook training uses — a no-op unless
        # FLAGS_metrics_port says otherwise, never on the hot path
        from ..monitor import exporter

        exporter.ensure_started()
        self.watchdog.start()
        if self._batcher is None:
            self._batcher = threading.Thread(
                target=self._batcher_loop,
                name=f"{self.config.label}-batcher", daemon=True)
            self._batcher.start()

    def close(self, timeout=10.0):
        """Stop admission, drain what the deadline math still allows,
        fail the rest with ServingClosedError, emit the final
        kind="serving" telemetry record."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        t = self._batcher
        if t is not None:
            t.join(timeout=timeout)
        self.watchdog.stop()
        # anything still unresolved — queued OR in flight behind a
        # wedged dispatch the join timed out on — fails classified,
        # never silently dropped.  Failing an in-flight request also
        # unblocks its waiter loop (it exits once every future is
        # done), so the wedged batcher thread winds down too.
        with self._cond:
            self._queue.clear()
            leftovers = list(self._live)
        for req in leftovers:
            self._resolve_error(
                req, ServingClosedError("serving runtime closed"),
                "cancelled")
        self._note_serving()
        self.emit_telemetry()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- admission ------------------------------------------------------
    def submit(self, feed, deadline_s=None, traceparent=None):
        """Enqueue one request; returns a ServingFuture.  Raises
        synchronously on validation errors (bad feed), backpressure
        (QueueFullError) and a closed runtime — admission failures are
        the CALLER's bug or the CALLER's signal to back off, so they
        never consume queue budget.

        `traceparent` is an optional W3C trace-context header from the
        external caller; with FLAGS_request_tracing on, the request's
        span tree joins that trace instead of starting a fresh one."""
        if self._closed:
            raise ServingClosedError("serving runtime closed")
        prepared, rows = self.dispatcher.prepare(feed)
        budget = deadline_s if deadline_s is not None \
            else self.config.default_deadline_s
        now = self.config.clock()
        # None when tracing is off: start_request is the only flag
        # probe on the submit path, and the dispatch path never probes
        trace = _tracing().get().start_request(
            f"serving.request/{self.config.label}",
            label=self.config.label, traceparent=traceparent,
            attrs={"rows": rows})
        with self._cond:
            if self._closed:
                if trace is not None:
                    trace.finish("cancelled")
                raise ServingClosedError("serving runtime closed")
            if len(self._queue) >= self.config.max_queue_depth:
                self.stats.note_outcome("rejected")
                if trace is not None:
                    trace.annotate(trace.root, "rejected: queue full",
                                   depth=len(self._queue))
                    trace.finish("rejected")
                _fr().note_event("serving_rejected",
                                 label=self.config.label,
                                 depth=len(self._queue))
                raise QueueFullError(
                    f"serving queue at max depth "
                    f"{self.config.max_queue_depth}; request rejected "
                    f"(backpressure — shed load or slow down)")
            self._rid += 1
            req = _Request(prepared, rows, now,
                           now + budget if budget else None, budget,
                           self._rid, trace=trace)
            if trace is not None:
                trace.rid = req.rid
                req.qspan = trace.child("queue", "queue")
            self._queue.append(req)
            self._live.add(req)
            # counted INSIDE the lock: a dispatch resolving this
            # request on another thread must never observe
            # sum(outcomes) > requests in a concurrent snapshot
            self.stats.note_admitted(len(self._queue))
            self._cond.notify()
        return req.future

    def run(self, feed, deadline_s=None, timeout=None,
            traceparent=None):
        """Blocking convenience: submit + result."""
        return self.submit(feed, deadline_s=deadline_s,
                           traceparent=traceparent).result(
            timeout=timeout)

    # -- batching -------------------------------------------------------
    def _batcher_loop(self):
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(0.05)
                if self._closed and not self._queue:
                    return
            try:
                self.process_once()
            except Exception as e:  # noqa: BLE001 — must not die
                _fr().note_event("serving_batcher_error", severe=True,
                                 error=f"{type(e).__name__}: {e}"[:200])

    def _pop_batch_locked(self, now, batch, rows, shed):
        """Move queue head into `batch` while it fits the largest
        bucket, shedding expired requests as they surface."""
        while self._queue:
            r = self._queue[0]
            if r.expired(now):
                self._queue.popleft()
                shed.append(r)
                continue
            if rows + r.rows > self.dispatcher.max_rows:
                break
            self._queue.popleft()
            batch.append(r)
            rows += r.rows
        return rows

    def process_once(self):
        """Form and dispatch ONE batch (the batcher thread's body;
        callable directly in tests with auto_start=False).  Returns
        the number of requests resolved by this call."""
        cfg = self.config
        now = cfg.clock()
        batch, shed = [], []
        with self._cond:
            rows = self._pop_batch_locked(now, batch, 0, shed)
            # coalescing window: once ONE request is in hand, wait up
            # to batch_window_s for peers to share the dispatch —
            # bounded, so a lone request never waits long
            window_end = now + cfg.batch_window_s
            while (batch and cfg.batch_window_s > 0
                   and rows < self.dispatcher.max_rows
                   and not self._closed):
                remaining = window_end - cfg.clock()
                if remaining <= 0:
                    break
                if not self._queue:
                    self._cond.wait(remaining)
                if self._queue:
                    rows = self._pop_batch_locked(cfg.clock(), batch,
                                                  rows, shed)
                else:
                    break
            depth = len(self._queue)
        self.stats.note_queue_depth(depth)
        for r in shed:
            elapsed = cfg.clock() - r.enqueue_t
            self._resolve_error(
                r, DeadlineExceeded(
                    f"request deadline exceeded after "
                    f"{elapsed * 1e3:.1f}ms in queue "
                    f"(budget {r.budget_s * 1e3:.1f}ms); shed before "
                    f"dispatch", elapsed_s=elapsed,
                    budget_s=r.budget_s),
                "shed")
        if not batch:
            return len(shed)
        try:
            self._dispatch_batch(batch, rows)
        except Exception as e:  # noqa: BLE001
            # an unexpected error OUTSIDE the guarded dispatch (merge,
            # bucket math, a bug) must still resolve every popped
            # request classified — a request the runtime holds and
            # never answers is the one failure mode worse than any
            # other
            for r in batch:
                self._resolve_error(r, e, "failed")
            _fr().note_event("serving_batch_error", severe=True,
                             label=self.config.label,
                             error=f"{type(e).__name__}: {e}"[:200])
        return len(shed) + len(batch)

    def sweep_expired(self):
        """Shed every QUEUED request whose deadline has passed.  Runs
        on the watchdog's poll tick (and is callable directly), so
        budget expiry is enforced even while the batcher thread is
        wedged inside a stalled dispatch — bounded latency must not
        depend on the component most likely to be stuck."""
        now = self.config.clock()
        expired = []
        with self._cond:
            if not self._queue:
                return 0
            keep = deque()
            for r in self._queue:
                (expired if r.expired(now) else keep).append(r)
            if expired:
                self._queue = keep
        for r in expired:
            elapsed = now - r.enqueue_t
            self._resolve_error(
                r, DeadlineExceeded(
                    f"request deadline exceeded after "
                    f"{elapsed * 1e3:.1f}ms in queue (budget "
                    f"{r.budget_s * 1e3:.1f}ms); shed before dispatch",
                    elapsed_s=elapsed, budget_s=r.budget_s),
                "shed")
        if expired:
            self.stats.note_queue_depth(len(self._queue))
        return len(expired)

    # -- resolution helpers ---------------------------------------------
    def _request_span(self, req, suffix):
        prof = _profiler()
        if prof is None or not prof.is_profiling():
            return
        prof.add_span(
            f"serving.request/{self.config.label}/{suffix}",
            req.enqueue_pc_ns, time.perf_counter_ns())

    def _resolve_ok(self, req, outs):
        if not req.future._set_result([np.asarray(o) for o in outs]):
            return False
        self._live.discard(req)
        now = self.config.clock()
        self.stats.note_outcome("completed",
                                latency_s=now - req.enqueue_t)
        self._request_span(req, "ok")
        self._finish_trace(req, "completed")
        return True

    def _resolve_error(self, req, exc, outcome):
        if not req.future._set_exception(exc):
            return False
        self._live.discard(req)
        self.stats.note_outcome(outcome)
        self._request_span(req, outcome)
        self._finish_trace(req, outcome)
        return True

    def _finish_trace(self, req, outcome):
        """Close the request's span tree with its ledger outcome.
        Called ONLY from the two _resolve_* terminal points (which are
        idempotent), so the trace-outcome multiset reconciles with the
        outcome ledger by construction."""
        if req.trace is not None:
            req.trace.finish(outcome)

    def _note_serving(self):
        fr = _fr()
        if fr.get().enabled:
            fr.get().note_serving(self.stats.to_record())

    def emit_telemetry(self):
        """Write the current kind="serving" record onto the telemetry
        JSONL stream (no-op while telemetry is off).  With request
        tracing on, the record carries the label's attribution/SLO
        summary."""
        rec = self.stats.to_record()
        store = _tracing().get()
        if store.enabled:
            s = store.summary(self.config.label)
            if s is not None:
                rec["tracing"] = s
        return _mon().record_serving(rec)

    # -- dispatch -------------------------------------------------------
    def _dispatch_batch(self, batch, rows):
        bucket = pick_bucket(self.dispatcher.buckets, rows)
        for r in batch:
            if r.trace is not None:
                r.trace.end(r.qspan)
                r.trace.annotate(r.trace.root, "batch_join",
                                 bucket=bucket, rows=rows,
                                 requests=len(batch))
        if not self.breaker.allow():
            self._degraded_serve(batch)
            return
        merged, slices = self.dispatcher.merge(
            [r.prepared for r in batch], bucket)
        meta = {"bucket": bucket, "rows": rows,
                "requests": len(batch),
                "request_ids": [r.rid for r in batch]}
        tids = [r.trace.trace_id for r in batch if r.trace is not None]
        if tids:
            # carried in the watchdog meta: a stall escalation's
            # flight dump names the wedged requests' traces
            meta["trace_ids"] = tids
        outcome = self._dispatch_guarded(merged, bucket, batch, slices,
                                         meta, final_attempt=False)
        if outcome == "cancel_retry":
            # abandon the wedged call (it cannot be cancelled, only
            # stopped being waited for) and give the SAME batch one
            # fresh dispatch; a second stall fails classified
            self.stats.note_cancel_retry()
            _fr().note_event("serving_cancel_retry",
                             label=self.config.label, **meta)
            live = [r for r in batch if not r.future.done()]
            if not live:
                self.breaker.release_probe()
                return
            merged, slices = self.dispatcher.merge(
                [r.prepared for r in live], bucket)
            outcome = self._dispatch_guarded(merged, bucket, live,
                                             slices, meta,
                                             final_attempt=True)
        if outcome == "abandoned":
            # no verdict reached the breaker (every waiter expired
            # mid-flight): a consumed half-open probe token must not
            # wedge the breaker — hand it back
            self.breaker.release_probe()

    def _dispatch_guarded(self, merged, bucket, batch, slices, meta,
                          final_attempt):
        """One watched dispatch attempt: retry envelope inside, breaker
        accounting + deadline enforcement + watchdog escalation
        outside.  Returns "ok" | "failed" | "stalled" | "cancel_retry"
        | "abandoned"."""
        cfg = self.config
        token, stalled = self.watchdog.track(meta)
        done = threading.Event()
        box = {}
        # per-request dispatch-attempt spans (None-trace requests pay
        # one attribute read and are skipped — the gate-free contract)
        attempt = 2 if final_attempt else 1
        tspans = [(r, r.trace.child(f"dispatch/b{bucket}", "dispatch",
                                    attrs={"bucket": bucket,
                                           "attempt": attempt}))
                  for r in batch if r.trace is not None]
        rspans = {}

        def _close_attempt(outcome, category=None):
            for r, ds in tspans:
                sp = rspans.pop(r, None)
                if sp is not None:
                    r.trace.end(sp)
                if ds is not None:
                    if category is not None:
                        r.trace.recategorize(ds, category)
                    r.trace.end(ds, outcome=outcome)

        def _note_retry(*_a):
            self.stats.note_retry()
            # the remainder of this attempt (backoff + re-dispatch) is
            # retry-caused latency: charge it to "retry", one level
            # under the dispatch span
            for r, ds in tspans:
                if ds is None:
                    continue
                prev = rspans.pop(r, None)
                if prev is not None:
                    r.trace.end(prev)
                sp = r.trace.child("retry", "retry", parent=ds)
                if sp is not None:
                    rspans[r] = sp

        def call():
            prof = _profiler()
            span = prof.RecordEvent(
                f"serving.dispatch/{cfg.label}/b{bucket}") \
                if prof is not None else None
            try:
                if span is not None:
                    span.__enter__()
                feeds = faultinject.on_step_feed(merged) \
                    if faultinject.is_armed() else merged

                def _dispatch():
                    if faultinject.is_armed():
                        faultinject.check_transient()
                        faultinject.stall_point("serving.dispatch")
                    return self.dispatcher.dispatch(feeds, bucket)

                if cfg.retry_policy is not None:
                    box["outs"] = call_with_retry(
                        _dispatch, cfg.retry_policy,
                        on_retry=_note_retry)
                else:
                    box["outs"] = _dispatch()
            except BaseException as e:  # noqa: BLE001
                box["error"] = e
            finally:
                if span is not None:
                    span.__exit__(None, None, None)
                done.set()

        t = threading.Thread(target=call, daemon=True,
                             name=f"{cfg.label}-dispatch")
        t.start()
        try:
            while not done.wait(timeout=0.005):
                now = cfg.clock()
                for r in batch:
                    if not r.future.done() and r.expired(now):
                        elapsed = now - r.enqueue_t
                        self._resolve_error(
                            r, DeadlineExceeded(
                                f"request deadline exceeded after "
                                f"{elapsed * 1e3:.1f}ms (budget "
                                f"{r.budget_s * 1e3:.1f}ms) with the "
                                f"dispatch still in flight",
                                elapsed_s=elapsed,
                                budget_s=r.budget_s),
                            "expired")
                if all(r.future.done() for r in batch):
                    # nobody is waiting for this result anymore
                    _close_attempt("abandoned")
                    return "abandoned"
                if stalled.is_set():
                    if cfg.watchdog_policy == "cancel_retry" \
                            and not final_attempt:
                        # the wedged attempt's wall time is STALL, not
                        # dispatch — the fresh attempt gets its own
                        # span on the SAME trace
                        _close_attempt("cancel_retry", category="stall")
                        return "cancel_retry"
                    stall = WatchdogStall(
                        f"serving dispatch watchdog stall: batch "
                        f"(bucket {bucket}, {meta['rows']} rows) in "
                        f"flight > {cfg.watchdog_stall_s}s")
                    self.breaker.note_failure(stall)
                    _close_attempt("stalled", category="stall")
                    for r in batch:
                        self._resolve_error(r, stall, "stalled")
                    return "stalled"
        finally:
            self.watchdog.untrack(token)
        if "error" in box:
            e = box["error"]
            self.breaker.note_failure(e)
            self._note_serving()
            _fr().note_event(
                "serving_dispatch_failed", label=cfg.label,
                error=f"{type(e).__name__}: {e}"[:200], **{
                    k: v for k, v in meta.items()
                    if k not in ("request_ids", "trace_ids")})
            _close_attempt("failed")
            for r in batch:
                self._resolve_error(r, e, "failed")
            return "failed"
        self.breaker.note_success()
        self.stats.note_batch(bucket, meta["rows"])
        _close_attempt("ok")
        for r, outs in zip(batch, self.dispatcher.split(box["outs"],
                                                        slices)):
            self._resolve_ok(r, outs)
        return "ok"

    # -- degraded mode --------------------------------------------------
    def _degraded_serve(self, batch):
        """Breaker-open path: serve each request individually through
        the configured fallback — the eager interpreter (shares nothing
        with the compiled path) or the smallest fitting bucket — or
        fail fast when degraded_mode='fail'.  Deadlines still hold."""
        cfg = self.config
        mode = cfg.degraded_mode
        if mode == "eager" and not self.dispatcher.eager_available:
            mode = "smallest_bucket"
        for req in batch:
            if req.future.done():
                continue
            if req.trace is not None:
                req.trace.annotate(req.trace.root, "breaker_open",
                                   mode=mode)
            now = cfg.clock()
            if req.expired(now):
                elapsed = now - req.enqueue_t
                self._resolve_error(
                    req, DeadlineExceeded(
                        f"request deadline exceeded after "
                        f"{elapsed * 1e3:.1f}ms (breaker open)",
                        elapsed_s=elapsed, budget_s=req.budget_s),
                    "shed")
                continue
            if mode == "fail":
                self._resolve_error(
                    req, CircuitOpenError(
                        f"serving circuit breaker open after "
                        f"{self.breaker.failure_threshold} consecutive "
                        f"failures; degraded_mode='fail' — failing "
                        f"fast"),
                    "failed")
                continue
            dspan = req.trace.child(f"degraded/{mode}", "degraded") \
                if req.trace is not None else None
            try:
                if mode == "eager":
                    outs = self.dispatcher.dispatch_eager(req.prepared)
                    self.stats.note_batch(None, req.rows,
                                          degraded=True)
                else:
                    bucket = pick_bucket(self.dispatcher.buckets,
                                         req.rows)
                    merged, slices = self.dispatcher.merge(
                        [req.prepared], bucket)
                    outs = self.dispatcher.split(
                        self.dispatcher.dispatch(merged, bucket),
                        slices)[0]
                    self.stats.note_batch(bucket, req.rows,
                                          degraded=True)
                if dspan is not None:
                    req.trace.end(dspan, outcome="ok")
                self._resolve_ok(req, outs)
            except Exception as e:  # noqa: BLE001
                if dspan is not None:
                    req.trace.end(dspan, outcome="failed")
                self._resolve_error(req, e, "failed")

    # -- reading --------------------------------------------------------
    def summary(self):
        return self.stats.summary()
