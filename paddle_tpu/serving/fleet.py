"""FleetRouter: health-gated routing + failover over N replicas (ISSUE 19).

The scheduling tier above the per-replica engine (the Orca shape: the
replica's batcher/decode engine is untouched; the fleet layer only
decides WHERE a request runs):

- **Health gating** — a poll thread scrapes each replica's
  ``/healthz``; a 503 (draining, open breaker, wedged watchdog) or a
  connection failure demotes the replica out of the routing set, and a
  recovered 200 restores it.  Repeated connection failures mark it
  DEAD (its ledger is carried at last-known value in the fleet merge).
- **Per-replica circuit breaker** — consecutive dispatch failures trip
  the replica's breaker open; the router stops offering it traffic
  before the health poll even runs, and half-open probes readmit it.
- **Failover, not blind retry** — a per-replica failure classified by
  ``taxonomy.is_failover`` (connection reset from a killed process,
  overload 503, transient infrastructure) is retried on a DIFFERENT
  replica, bounded by ``FLAGS_fleet_failover_attempts``.  Deadline and
  fatal shapes fail fast: a spent budget cannot be un-spent by moving
  replicas, and a bad request fails identically everywhere.
- **Merged outcome ledger** — the router's own registered
  ``ServingStats`` (every routed request ends in exactly one outcome)
  plus each replica's scraped per-version ledgers merge into one fleet
  ledger whose ``requests == sum(outcomes)`` identity is the zero-
  silent-loss assertion; UNACCOUNTED is the difference.  Router-side
  per-ATTEMPT accounting (started vs resolved) covers even replicas
  that died with their ledgers.
- **Tracing** — each routed request opens a trace (joining the
  caller's ``traceparent`` when given) with one ``dispatch`` child
  span per route attempt, and forwards its own traceparent on the
  router hop — the replica's runtime joins the same trace id, so one
  request's tree spans router + replica (ISSUE 18 groundwork).

Model rollout rides the same surface: ``roll(version)`` hot-swaps every
live replica (each drains its outgoing runtime — zero drops), and
``registry.set_current`` flips the fleet-wide pointer for replicas yet
to be born.
"""

import http.client
import json
import threading
import time
import weakref

import numpy as np

from .. import flags
from ..resilience.breaker import CircuitBreaker
from ..resilience.taxonomy import DeadlineExceeded, classify, is_failover
from .stats import ServingStats

__all__ = ["FleetRouter", "ReplicaHandle", "NoReplicaAvailable",
           "ReplicaUnavailable", "ReplicaRequestError", "router_table"]

# live routers keyed by label — what the exporter's fleet families and
# /healthz read (the serving/stats.py weak-registry idiom)
_ROUTERS = weakref.WeakValueDictionary()
_routers_lock = threading.Lock()

_DEAD_AFTER = 3         # consecutive failed health polls -> dead


class NoReplicaAvailable(RuntimeError):
    """No healthy, breaker-closed replica is accepting traffic — the
    router's backpressure rejection (counted `rejected`, never queued)."""


class ReplicaUnavailable(ConnectionError):
    """A replica answered with an unavailable/overload shape (503, a
    closed runtime, a transient-classified 500).  Derives from
    ConnectionError so the taxonomy classifies it PREEMPTION by TYPE —
    the failover class — exactly like the raw socket reset a killed
    replica produces."""


class ReplicaRequestError(RuntimeError):
    """A replica rejected the request as fatal (4xx/fatal-classified
    500): failing over would re-run a bad request N more times."""


def _mon():
    from .. import monitor

    return monitor


def _tracing():
    from ..monitor import tracing

    return tracing


class ReplicaHandle:
    """Router-side state for one replica endpoint."""

    def __init__(self, name, host, port, breaker_threshold=3,
                 breaker_cooldown_s=2.0, clock=time.monotonic):
        self.name = name
        self.host = host
        self.port = int(port)
        self.breaker = CircuitBreaker(
            failure_threshold=breaker_threshold,
            cooldown_s=breaker_cooldown_s, clock=clock,
            name=f"replica:{name}")
        self.healthy = True          # optimistic until the first poll
        self.draining = False
        self.dead = False
        self.version = None
        self.last_stats = None       # newest /stats doc (kept for dead)
        self.last_error = None
        self.health_fails = 0

    def summary(self):
        merged = (self.last_stats or {}).get("merged")
        return {
            "name": self.name,
            "endpoint": f"{self.host}:{self.port}",
            "healthy": self.healthy,
            "draining": self.draining,
            "dead": self.dead,
            "version": self.version,
            "breaker": self.breaker.summary(),
            "last_error": self.last_error,
            "ledger": merged,
        }


class FleetRouter:
    """Route requests across replicas; merge their ledgers.

    router = FleetRouter([("r0", "127.0.0.1", 8070), ...])
    outs = router.run({"x": batch})          # list of np.ndarray
    router.roll(2)                           # hot-swap the fleet
    router.close()
    """

    def __init__(self, replicas, label="fleet_router",
                 health_poll_s=None, failover_attempts=None,
                 request_timeout_s=None, breaker_threshold=3,
                 breaker_cooldown_s=2.0, clock=time.monotonic,
                 auto_poll=True, policy="round_robin"):
        if policy not in ("round_robin", "least_loaded"):
            raise ValueError(
                f"unknown routing policy {policy!r}: want 'round_robin' "
                f"or 'least_loaded'")
        self.label = label
        self.policy = policy
        self.clock = clock
        self.health_poll_s = float(
            health_poll_s if health_poll_s is not None
            else flags.flag("fleet_health_poll_s"))
        self.failover_attempts = int(
            failover_attempts if failover_attempts is not None
            else flags.flag("fleet_failover_attempts"))
        self.request_timeout_s = float(
            request_timeout_s if request_timeout_s is not None
            else flags.flag("fleet_request_timeout_s"))
        self.replicas = []
        for spec in replicas:
            if isinstance(spec, ReplicaHandle):
                self.replicas.append(spec)
                continue
            if isinstance(spec, dict):
                name, host, port = (spec["name"], spec["host"],
                                    spec["port"])
            else:
                name, host, port = spec
            self.replicas.append(ReplicaHandle(
                name, host, port, breaker_threshold=breaker_threshold,
                breaker_cooldown_s=breaker_cooldown_s, clock=clock))
        # the router's own registered ledger: rides serving_table(),
        # the exporter's serving families and /healthz automatically
        self.stats = ServingStats(label)
        self.failovers = 0
        self.attempts_started = 0
        self.attempts_resolved = 0
        self._rr = 0
        self._lock = threading.Lock()
        self._closed = False
        self._poll_stop = threading.Event()
        self._poll_thread = None
        with _routers_lock:
            _ROUTERS[label] = self
        if auto_poll and self.health_poll_s > 0:
            self._poll_thread = threading.Thread(
                target=self._poll_loop,
                name=f"paddle_tpu-fleet-poll-{label}", daemon=True)
            self._poll_thread.start()

    # -- transport ------------------------------------------------------
    def _http(self, rep, method, path, body=None, headers=None,
              timeout=None):
        conn = http.client.HTTPConnection(
            rep.host, rep.port,
            timeout=timeout if timeout is not None
            else self.request_timeout_s)
        try:
            conn.request(method, path, body=body,
                         headers=dict(headers or {}))
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    def _get_json(self, rep, path, timeout=None):
        status, body = self._http(rep, "GET", path, timeout=timeout)
        try:
            return status, json.loads(body)
        except ValueError:
            return status, {}

    # -- health gating --------------------------------------------------
    def poll_once(self):
        """One health sweep: /healthz gates routing, /stats refreshes
        the replica's version + merged ledger for the fleet merge."""
        for rep in self.replicas:
            try:
                status, doc = self._get_json(
                    rep, "/healthz", timeout=max(1.0,
                                                 self.health_poll_s * 4))
                rep.health_fails = 0
                rep.dead = False
                rep.healthy = (status == 200)
                rep.draining = (doc.get("reason") == "draining")
                rep.last_error = (None if status == 200
                                  else doc.get("reason"))
                if doc.get("version") is not None:
                    rep.version = doc["version"]
                try:
                    _, st = self._get_json(rep, "/stats")
                    rep.last_stats = st
                    if st.get("version") is not None:
                        rep.version = st["version"]
                except Exception:
                    pass
            except Exception as e:
                rep.healthy = False
                rep.health_fails += 1
                rep.last_error = f"{type(e).__name__}: {e}"[:200]
                if rep.health_fails >= _DEAD_AFTER:
                    rep.dead = True
        mon = _mon()
        if mon.is_enabled():
            mon.gauge("fleet.healthy_replicas").set(
                sum(1 for r in self.replicas if r.healthy))

    def _poll_loop(self):
        while not self._poll_stop.wait(self.health_poll_s):
            try:
                self.poll_once()
            except Exception:
                pass             # the poll must outlive any one scrape

    # -- routing --------------------------------------------------------
    def _routable(self, tried):
        return [r for r in self.replicas
                if r.healthy and not r.draining and not r.dead
                and r.name not in tried]

    @staticmethod
    def _load(rep):
        """Scraped load of one replica: queued + in-flight requests
        from its newest /stats doc (the runtime's own admission
        gauges).  None when no poll has landed a stats doc yet — the
        least-loaded policy treats that as unknown, not as idle."""
        active = (rep.last_stats or {}).get("active") or {}
        depth = active.get("queue_depth")
        in_flight = active.get("in_flight")
        if depth is None and in_flight is None:
            return None
        return int(depth or 0) + int(in_flight or 0)

    def _pick(self, tried):
        """Pick a routable replica whose breaker admits traffic.

        round_robin (default): rotate over the candidates.
        least_loaded: order candidates by their scraped queue-depth +
        in-flight load (ISSUE 20 satellite — the first consumer of the
        metrics the observability tier exports); replicas with no
        scraped gauges sort last, and ties keep the round-robin
        rotation order, so a fleet with no stats yet degrades to exact
        round-robin.  allow() is only asked in candidate order (it
        hands out half-open probe tokens — polling every breaker would
        burn probes on replicas we don't pick)."""
        with self._lock:
            candidates = self._routable(tried)
            if not candidates:
                return None
            start = self._rr
            self._rr += 1
        n = len(candidates)
        ordered = [candidates[(start + i) % n] for i in range(n)]
        if self.policy == "least_loaded":
            loads = [self._load(rep) for rep in ordered]
            if any(ld is not None for ld in loads):
                order = sorted(range(n),
                               key=lambda i: (loads[i] is None,
                                              loads[i] or 0))
                ordered = [ordered[i] for i in order]
        for rep in ordered:
            if rep.breaker.allow():
                return rep
        return None

    def _post_infer(self, rep, payload, traceparent):
        headers = {"Content-Type": "application/json"}
        if traceparent:
            headers["traceparent"] = traceparent
        status, body = self._http(rep, "POST", "/infer", body=payload,
                                  headers=headers)
        try:
            doc = json.loads(body)
        except ValueError:
            doc = {"error": body.decode(errors="replace")[:200],
                   "kind": "unknown"}
        if status == 200:
            return [np.asarray(o) for o in doc["outputs"]], doc
        err = doc.get("error") or f"HTTP {status}"
        kind = doc.get("kind")
        if status == 504 or kind == "deadline":
            raise DeadlineExceeded(f"replica {rep.name}: {err}")
        if status == 503 or kind in ("overload", "closed", "draining",
                                     "transient", "preemption"):
            raise ReplicaUnavailable(
                f"replica {rep.name} unavailable ({kind}): {err}")
        raise ReplicaRequestError(
            f"replica {rep.name} failed the request ({kind}): {err}")

    def run(self, feed, deadline_s=None, traceparent=None):
        """Route one request; returns the fetch list (np arrays).  On a
        classified-transient replica failure the request FAILS OVER to
        a different replica (bounded attempts); deadline/fatal shapes
        raise immediately.  Every call lands in exactly one router
        ledger outcome."""
        if self._closed:
            raise NoReplicaAvailable("router is closed")
        start = self.clock()
        tried = set()
        # this pick doubles as the first attempt's routing decision —
        # picking twice would consume two half-open probe tokens and
        # advance round-robin for a request that only routes once
        first = self._pick(tried)
        if first is None:
            # backpressure, not a queued failure: counted `rejected`
            # (note_outcome increments `requests` for rejections)
            self.stats.note_outcome("rejected")
            mon = _mon()
            if mon.is_enabled():
                mon.counter("fleet.no_replica").add(1)
            raise NoReplicaAvailable(
                "no healthy replica is accepting traffic")
        self.stats.note_admitted(0)
        tr = _tracing().get().start_request(
            "fleet.infer", label=self.label, traceparent=traceparent)
        hop_traceparent = tr.traceparent() if tr is not None \
            else traceparent
        payload = json.dumps({
            "feed": {k: np.asarray(v).tolist() for k, v in feed.items()},
            "deadline_s": deadline_s}).encode()
        last_exc = None
        attempts = 0
        while attempts <= self.failover_attempts:
            rep, first = (first, None) if first is not None \
                else (self._pick(tried), None)
            if rep is None:
                break
            tried.add(rep.name)
            attempts += 1
            span = tr.child(f"route:{rep.name}", "dispatch",
                            attrs={"replica": rep.name}) \
                if tr is not None else None
            with self._lock:
                self.attempts_started += 1
            try:
                outs, _doc = self._post_infer(rep, payload,
                                              hop_traceparent)
            except Exception as e:  # noqa: BLE001 — classified below
                with self._lock:
                    self.attempts_resolved += 1
                last_exc = e
                rep.breaker.note_failure(e)
                if tr is not None:
                    tr.end(span, outcome="error")
                if isinstance(e, DeadlineExceeded) or not is_failover(e):
                    break         # terminal: budget spent / fatal shape
                # demote immediately on a RAW socket failure (reset /
                # refused: the process is likely gone) — the health
                # poll will readmit a blip, but routing must not wait a
                # poll interval to stop feeding a dead socket.  A
                # ReplicaUnavailable is an ANSWER (alive, just busy or
                # draining): failover, but leave it health-gated by the
                # poll.
                if isinstance(e, ConnectionError) and \
                        not isinstance(e, ReplicaUnavailable):
                    rep.healthy = False
                with self._lock:
                    self.failovers += 1
                mon = _mon()
                if mon.is_enabled():
                    mon.counter("fleet.failover").add(1)
                continue
            with self._lock:
                self.attempts_resolved += 1
            rep.breaker.note_success()
            if tr is not None:
                tr.end(span, outcome="completed")
                tr.finish("completed")
            self.stats.note_outcome("completed",
                                    latency_s=self.clock() - start)
            return outs
        # terminal failure: classify into the ledger
        latency = self.clock() - start
        if isinstance(last_exc, DeadlineExceeded):
            outcome = "expired"
        else:
            outcome = "failed"
        self.stats.note_outcome(outcome, latency_s=latency)
        if tr is not None:
            tr.finish(outcome)
        if last_exc is None:
            raise NoReplicaAvailable(
                f"all routable replicas exhausted after {attempts} "
                f"attempts")
        raise last_exc

    # -- model rollout --------------------------------------------------
    def roll(self, version):
        """Hot-swap every live replica to `version` (each drains its
        outgoing runtime — zero drops).  Returns {replica: result}."""
        out = {}
        for rep in self.replicas:
            if rep.dead:
                out[rep.name] = {"error": "dead"}
                continue
            try:
                status, doc = self._http(
                    rep, "POST", "/swap",
                    body=json.dumps({"version": int(version)}).encode(),
                    headers={"Content-Type": "application/json"})
                doc = json.loads(doc)
                if status != 200:
                    out[rep.name] = {"error": doc.get("error"),
                                     "status": status}
                    continue
                rep.version = doc.get("version")
                out[rep.name] = doc
            except Exception as e:  # noqa: BLE001 — per-replica verdict
                out[rep.name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    # -- merged ledger / records ----------------------------------------
    def fleet_ledger(self):
        """The merged fleet view: router ledger + per-replica scraped
        ledgers summed into one ``requests == sum(outcomes)`` identity.
        UNACCOUNTED > 0 at quiesce means a request entered a ledger and
        never reached an outcome — a silent loss.  Dead replicas are
        reported at last-known value but EXCLUDED from the identity sum
        (their in-flight work at death is accounted by the router's
        failover path); the per-attempt row covers them: every attempt
        the router ever started must be resolved."""
        router = self.stats.summary()
        reps = [rep.summary() for rep in self.replicas]
        requests = router["requests"]
        outcomes = dict(router["outcomes"])
        for rep, row in zip(self.replicas, reps):
            ledger = row.get("ledger")
            if rep.dead or not ledger:
                continue
            requests += ledger["requests"]
            for k, v in ledger["outcomes"].items():
                outcomes[k] = outcomes.get(k, 0) + v
        resolved = sum(outcomes.values())
        with self._lock:
            attempts = {
                "started": self.attempts_started,
                "resolved": self.attempts_resolved,
                "unaccounted": (self.attempts_started
                                - self.attempts_resolved),
            }
            failovers = self.failovers
        return {
            "router": router,
            "replicas": reps,
            "merged": {"requests": requests, "outcomes": outcomes,
                       "resolved": resolved,
                       "unaccounted": requests - resolved},
            "attempts": attempts,
            "failovers": failovers,
        }

    def fleet_record(self):
        rec = {"kind": "fleet_serving", "label": self.label,
               "policy": self.policy}
        rec.update(self.fleet_ledger())
        return rec

    def emit_telemetry(self):
        return _mon().record_fleet_serving(self.fleet_record())

    def exporter_row(self):
        """Scrape-shaped snapshot from CACHED state only (no network
        I/O on the scrape path)."""
        with self._lock:
            failovers = self.failovers
            att_unaccounted = (self.attempts_started
                               - self.attempts_resolved)
        return {
            "label": self.label,
            "failovers": failovers,
            "attempts_unaccounted": att_unaccounted,
            "replicas": [{
                "name": rep.name,
                "healthy": rep.healthy,
                "dead": rep.dead,
                "version": rep.version,
                "breaker_open": rep.breaker.state == "open",
            } for rep in self.replicas],
        }

    def close(self, emit=True):
        if self._closed:
            return
        self._closed = True
        self._poll_stop.set()
        if self._poll_thread is not None:
            self._poll_thread.join(timeout=5.0)
        if emit:
            try:
                self.emit_telemetry()
            except Exception:
                pass


def router_table():
    """One exporter_row per live FleetRouter — what the exporter's
    fleet-serving families and /healthz read."""
    with _routers_lock:
        routers = list(_ROUTERS.values())
    return [r.exporter_row() for r in routers]
