"""nn.Layer — the eager module base class.

Parity: /root/reference/python/paddle/fluid/dygraph/layers.py (Layer:
sublayers, parameters, add_parameter, state_dict, hooks, train/eval) with
a functional extension for TPU: `functional_call(layer, params, *args)`
runs forward with parameter values substituted from a flat dict, which is
what lets a Layer be jitted/differentiated/sharded as a pure function
(the analogue of the dygraph tracer capturing ops — imperative/tracer.cc:45
— except here JAX is the tracer).
"""

import contextlib
from collections import OrderedDict

import jax.numpy as jnp
import numpy as np

from ..framework import unique_name
# the op-profile sampler's single-slot handle (op_profile imports only
# stdlib, so this is cycle- and jax-free): Layer.__call__ checks
# `_op_sampler[0] is not None` — one list load — while sampling is off
from ..monitor.op_profile import _ACTIVE as _op_sampler
from .parameter import EagerParameter, default_rng


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        prefix = name_scope or type(self).__name__.lower()
        self._full_name = unique_name.generate(prefix)
        self._dtype = dtype
        self._parameters = OrderedDict()
        self._sub_layers = OrderedDict()
        self._buffers = OrderedDict()
        self.training = True

    # -- attribute plumbing ----------------------------------------------

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        if isinstance(value, EagerParameter) and params is not None:
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer) and subs is not None:
            subs[name] = value
            self.__dict__.pop(name, None)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        if "_parameters" in self.__dict__ and name in self._parameters:
            return self._parameters[name]
        if "_sub_layers" in self.__dict__ and name in self._sub_layers:
            return self._sub_layers[name]
        if "_buffers" in self.__dict__ and name in self._buffers:
            return self._buffers[name]
        raise AttributeError(
            f"{type(self).__name__} has no attribute {name!r}")

    # -- parameter management --------------------------------------------

    def create_parameter(self, shape, dtype=None, is_bias=False,
                         default_initializer=None, attr=None):
        from ..framework.initializer import (
            ConstantInitializer, XavierInitializer,
        )
        from ..framework.param_attr import ParamAttr

        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        init = (attr.initializer if attr and attr.initializer
                else default_initializer)
        name = (attr.name if attr and attr.name else
                unique_name.generate(self._full_name + (".b" if is_bias else ".w")))
        value = _materialize_init(init, shape, dtype, is_bias)
        p = EagerParameter(value, name=name,
                          trainable=attr.trainable if attr else True)
        return p

    def add_parameter(self, name, param):
        self._parameters[name] = param
        return param

    def add_sublayer(self, name, layer):
        self._sub_layers[name] = layer
        return layer

    def register_buffer(self, name, value):
        self._buffers[name] = jnp.asarray(value)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers)]

    def named_parameters(self, include_sublayers=True, prefix=""):
        out = []
        for n, p in self._parameters.items():
            if p is not None:
                out.append((f"{prefix}{n}" if prefix else n, p))
        if include_sublayers:
            for sn, sub in self._sub_layers.items():
                sp = f"{prefix}{sn}." if prefix else f"{sn}."
                out.extend(sub.named_parameters(True, sp))
        return out

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for sub in self._sub_layers.values():
            out.append(sub)
            out.extend(sub.sublayers())
        return out

    def named_buffers(self, prefix=""):
        out = []
        for n, b in self._buffers.items():
            out.append((f"{prefix}{n}" if prefix else n, b))
        for sn, sub in self._sub_layers.items():
            sp = f"{prefix}{sn}." if prefix else f"{sn}."
            out.extend(sub.named_buffers(sp))
        return out

    # -- modes ------------------------------------------------------------

    def train(self):
        self.training = True
        for sub in self._sub_layers.values():
            sub.train()
        return self

    def eval(self):
        self.training = False
        for sub in self._sub_layers.values():
            sub.eval()
        return self

    # -- state dict (dygraph/checkpoint.py parity) ------------------------

    def state_dict(self, include_sublayers=True):
        out = OrderedDict()
        for n, p in self.named_parameters(include_sublayers):
            out[n] = np.asarray(p.value)
        for n, b in self.named_buffers():
            out[n] = np.asarray(b)
        return out

    def set_state_dict(self, state_dict, use_structured_name=True):
        params = dict(self.named_parameters())
        buffers = dict(self.named_buffers())
        missing = []
        for n, v in state_dict.items():
            if n in params:
                params[n].set_value(v)
            elif n in buffers:
                self._set_buffer_by_path(n, v)
            else:
                missing.append(n)
        return missing

    load_dict = set_state_dict

    def _set_buffer_by_path(self, path, value):
        parts = path.split(".")
        layer = self
        for p in parts[:-1]:
            layer = layer._sub_layers[p]
        layer._buffers[parts[-1]] = jnp.asarray(value)

    # -- call -------------------------------------------------------------

    def __call__(self, *args, **kwargs):
        from ..tape import current_tape

        if _op_sampler[0] is not None:
            # per-op sampling mode (monitor.op_profile.sampling): time
            # this layer call host-side with block_until_ready — the
            # dygraph twin of the eager executor's per-op sampling
            return self._sampled_call(_op_sampler[0], args, kwargs)
        tape = current_tape()
        if tape is None:
            return self.forward(*args, **kwargs)
        return self._record_call(tape, args, kwargs)

    def _sampled_call(self, sampler, args, kwargs):
        import time as _time

        import jax as _jax

        from ..tape import current_tape

        t0 = _time.perf_counter_ns()
        tape = current_tape()
        if tape is None:
            out = self.forward(*args, **kwargs)
        else:
            out = self._record_call(tape, args, kwargs)
        try:
            _jax.block_until_ready(out)
        except Exception:
            pass   # tracers under an outer trace can't block
        sampler.note(f"dygraph/{self._full_name}",
                     (_time.perf_counter_ns() - t0) / 1e3)
        return out

    def _record_call(self, tape, args, kwargs):
        """Record this call on the dygraph tape: the forward runs as a
        pure function of (params, inputs) under jax.vjp; buffer updates
        (batch-norm stats) come back as explicit outputs and are
        committed to the layer with their concrete values."""
        # dict of EagerParameters: the tape wires each as a diff input
        params = {n: p for n, p in self.named_parameters() if p.trainable}
        buffers = buffer_dict(self)

        def fn(ps, *xs, **kw):
            out, new_buffers = functional_call_with_state(
                self, ps, buffers, *xs, **kw)
            return out, new_buffers

        out, new_buffers = tape.record(fn, (params,) + args, kwargs)
        for path, v in new_buffers.items():
            # tape.record wraps array outputs as Variables; buffers stay
            # plain arrays on the layer
            self._set_buffer_by_path(
                path, v.value if hasattr(v, "value") else v)
        return out

    def clear_gradients(self):
        """Zero out parameter gradient slots (dygraph Layer API)."""
        for _, p in self.named_parameters():
            p.clear_gradient()

    def forward(self, *args, **kwargs):
        raise NotImplementedError


def _materialize_init(init, shape, dtype, is_bias):
    """Run an initializer eagerly (no startup program in dygraph mode)."""
    from ..core.dtype import to_jax_dtype
    from ..framework import initializer as I

    jdt = to_jax_dtype(dtype)
    key = default_rng.next_key()
    import jax

    if init is None:
        init = I.ConstantInitializer(0.0) if is_bias else I.XavierInitializer()
    if isinstance(init, I.ConstantInitializer):
        return jnp.full(shape, init.value, dtype=jdt)
    if isinstance(init, I.UniformInitializer):
        return jax.random.uniform(key, tuple(shape), minval=init.low,
                                  maxval=init.high).astype(jdt)
    if isinstance(init, I.NormalInitializer):
        return (jax.random.normal(key, tuple(shape)) * init.scale
                + init.loc).astype(jdt)
    if isinstance(init, I.TruncatedNormalInitializer):
        return (jax.random.truncated_normal(key, -2.0, 2.0, tuple(shape))
                * init.scale + init.loc).astype(jdt)
    if isinstance(init, I.XavierInitializer):
        fi, fo = I._fan_in_out(tuple(shape))
        fi = init.fan_in or fi
        fo = init.fan_out or fo
        if init.uniform:
            limit = float(np.sqrt(6.0 / (fi + fo)))
            return jax.random.uniform(key, tuple(shape), minval=-limit,
                                      maxval=limit).astype(jdt)
        std = float(np.sqrt(2.0 / (fi + fo)))
        return (jax.random.normal(key, tuple(shape)) * std).astype(jdt)
    if isinstance(init, I.MSRAInitializer):
        fi, _ = I._fan_in_out(tuple(shape))
        fi = init.fan_in or fi
        if init.uniform:
            limit = float(np.sqrt(6.0 / fi))
            return jax.random.uniform(key, tuple(shape), minval=-limit,
                                      maxval=limit).astype(jdt)
        std = float(np.sqrt(2.0 / fi))
        return (jax.random.normal(key, tuple(shape)) * std).astype(jdt)
    if isinstance(init, I.NumpyArrayInitializer):
        return jnp.asarray(init.value, dtype=jdt)
    raise TypeError(f"unsupported initializer {init!r}")


# ---------------------------------------------------------------------------
# Functional bridge: run a Layer as a pure function of a params dict
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def _swap_params(layer, values):
    named = dict(layer.named_parameters())
    old = {}
    for n, v in values.items():
        if n in named:
            old[n] = named[n].value
            named[n].value = v
    try:
        yield
    finally:
        for n, v in old.items():
            named[n].value = v


def functional_call(layer, params, *args, **kwargs):
    """Forward pass with parameter values taken from `params`
    (dict name->array). Safe under jax tracing; the Layer's own values are
    restored afterwards."""
    with _swap_params(layer, params):
        return layer(*args, **kwargs)


def _walk_sublayers(layer, prefix):
    for n, sub in layer._sub_layers.items():
        path = f"{prefix}{n}" if not prefix else f"{prefix}.{n}"
        yield path, sub
        yield from _walk_sublayers(sub, path)


def _buffer_owner(layers_by_prefix, path):
    if "." in path:
        owner_path, leaf = path.rsplit(".", 1)
    else:
        owner_path, leaf = "", path
    return layers_by_prefix[owner_path], leaf


def functional_call_with_state(layer, params, buffers, *args, _method=None,
                               **kwargs):
    """Forward with params AND mutable buffers (batch-norm running stats)
    substituted; returns (output, new_buffers).  This is how a stateful
    Layer becomes a pure jittable function — the TPU answer to the
    reference's in-place MeanOut/VarianceOut aliasing.

    _method: optional fn(layer, *args, **kwargs) to call instead of
    layer.__call__ (e.g. a loss method)."""
    call = _method if _method is not None else (
        lambda l, *a, **kw: l(*a, **kw))
    layers_by_prefix = {"": layer}
    for name, sub in _walk_sublayers(layer, ""):
        layers_by_prefix[name] = sub
    with _swap_params(layer, params):
        old = {}
        for path, v in buffers.items():
            owner, leaf = _buffer_owner(layers_by_prefix, path)
            old[path] = owner._buffers[leaf]
            owner._buffers[leaf] = v
        try:
            out = call(layer, *args, **kwargs)
            new_buffers = {}
            for path in buffers:
                owner, leaf = _buffer_owner(layers_by_prefix, path)
                new_buffers[path] = owner._buffers[leaf]
        finally:
            for path, v in old.items():
                owner, leaf = _buffer_owner(layers_by_prefix, path)
                owner._buffers[leaf] = v
    return out, new_buffers


def buffer_dict(layer):
    return {n: b for n, b in layer.named_buffers()}


def param_dict(layer, trainable_only=False):
    unbuilt = [type(m).__name__ for m in layer.sublayers(include_self=True)
               if getattr(m, "_lazy_unbuilt", False)]
    if unbuilt:
        import warnings
        warnings.warn(
            f"param_dict: {unbuilt} have lazily-built weights that do not "
            f"exist yet — call the layer once (or pass input_size at "
            f"construction) before collecting params, or those weights "
            f"will be invisible to the optimizer", stacklevel=2)
    return {
        n: p.value
        for n, p in layer.named_parameters()
        if (p.trainable or not trainable_only)
    }


def load_param_dict(layer, values):
    named = dict(layer.named_parameters())
    for n, v in values.items():
        if n in named:
            named[n].value = jnp.asarray(v)
