"""Eager (dygraph) layers.

Parity: /root/reference/python/paddle/fluid/dygraph/nn.py (Conv2D, Pool2D,
FC/Linear, BatchNorm, Embedding, LayerNorm, GRUUnit, ...) plus the
transformer building blocks the flagship models need.
"""

import math

import jax.numpy as jnp

from . import functional as F
from .functional import scaled_dot_product_attention
from .layers import (
    Layer,
    functional_call,
    param_dict,
    load_param_dict,
)
from .parameter import EagerParameter, seed, default_rng
from ..framework.initializer import (
    ConstantInitializer,
    NormalInitializer,
    UniformInitializer,
    XavierInitializer,
)

__all__ = [
    "Layer", "EagerParameter", "functional_call", "param_dict",
    "load_param_dict", "seed", "functional", "Linear", "Conv2D",
    "Conv2DTranspose", "Pool2D", "MaxPool2D", "AvgPool2D", "BatchNorm",
    "LayerNorm", "GroupNorm", "Embedding", "Dropout", "Sequential",
    "LayerList", "ParameterList", "ReLU", "GELU", "Sigmoid", "Tanh",
    "Softmax", "MultiHeadAttention", "TransformerEncoderLayer",
    "TransformerEncoder", "scaled_dot_product_attention", "LSTMCell",
    "GRUCell", "RNN", "Conv3D", "Conv3DTranspose", "GRUUnit", "NCE",
    "PRelu", "BilinearTensorProduct", "SequenceConv", "RowConv",
    "SpectralNorm", "TreeConv",
]

functional = F


class Linear(Layer):
    """Parity: dygraph/nn.py Linear (mul + bias via core.ops)."""

    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter([input_dim, output_dim],
                                            attr=param_attr)
        if bias_attr is not False:
            self.bias = self.create_parameter([output_dim], is_bias=True,
                                              attr=bias_attr)
        else:
            self.bias = None
        self._act = act

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        return _apply_act(out, self._act)


class Conv2D(Layer):
    """Parity: dygraph/nn.py Conv2D (NCHW)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, data_format="NCHW",
                 dtype="float32"):
        super().__init__(dtype=dtype)
        fs = [filter_size] * 2 if isinstance(filter_size, int) else list(filter_size)
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups] + fs, attr=param_attr)
        if bias_attr is not False:
            self.bias = self.create_parameter([num_filters], is_bias=True,
                                              attr=bias_attr)
        else:
            self.bias = None
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        self._act = act
        self._data_format = data_format

    def forward(self, x):
        out = F.conv2d(x, self.weight, self.bias, self._stride,
                       self._padding, self._dilation, self._groups,
                       data_format=self._data_format)
        return _apply_act(out, self._act)


class Conv2DTranspose(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        fs = [filter_size] * 2 if isinstance(filter_size, int) else list(filter_size)
        self.weight = self.create_parameter(
            [num_channels, num_filters // groups] + fs, attr=param_attr)
        if bias_attr is not False:
            self.bias = self.create_parameter([num_filters], is_bias=True,
                                              attr=bias_attr)
        else:
            self.bias = None
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        self._act = act

    def forward(self, x):
        out = F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                 self._padding, self._dilation, self._groups)
        return _apply_act(out, self._act)


class Pool2D(Layer):
    """Parity: dygraph/nn.py Pool2D."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False,
                 data_format="NCHW"):
        super().__init__()
        self._pool_size = pool_size
        self._pool_type = pool_type
        self._pool_stride = pool_stride
        self._pool_padding = pool_padding
        self._global = global_pooling
        self._data_format = data_format

    def forward(self, x):
        if self._global:
            axis = (2, 3) if self._data_format == "NCHW" else (1, 2)
            if self._pool_type == "max":
                return jnp.max(x, axis=axis, keepdims=True)
            return jnp.mean(x, axis=axis, keepdims=True)
        if self._pool_type == "max":
            return F.max_pool2d(x, self._pool_size, self._pool_stride,
                                self._pool_padding,
                                data_format=self._data_format)
        return F.avg_pool2d(x, self._pool_size, self._pool_stride,
                            self._pool_padding,
                            data_format=self._data_format)


class MaxPool2D(Pool2D):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW"):
        super().__init__(kernel_size, "max", stride or kernel_size,
                         padding, data_format=data_format)


class AvgPool2D(Pool2D):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW"):
        super().__init__(kernel_size, "avg", stride or kernel_size,
                         padding, data_format=data_format)


class BatchNorm(Layer):
    """Parity: dygraph/nn.py BatchNorm. Running stats are buffers; under a
    functional train step use nn.layers.functional_call with
    collect_buffers (see train utilities in paddle_tpu.jit)."""

    def __init__(self, num_channels, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, act=None,
                 data_format="NCHW", dtype="float32", stats_sample=0):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [num_channels], attr=param_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_channels], is_bias=True,
                                          attr=bias_attr)
        self.register_buffer("_mean", jnp.zeros(num_channels))
        self.register_buffer("_variance", jnp.ones(num_channels))
        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = data_format
        self._act = act
        # ghost-batch stats subsample (0 = full batch); see the
        # batch_norm kernel for the measured on-chip rationale
        self._stats_sample = stats_sample

    def forward(self, x):
        y, new_mean, new_var = F.batch_norm(
            x, self._buffers["_mean"], self._buffers["_variance"],
            self.weight, self.bias, training=self.training,
            momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format,
            stats_sample=self._stats_sample)
        if self.training:
            self._buffers["_mean"] = new_mean
            self._buffers["_variance"] = new_var
        return _apply_act(y, self._act)


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, param_attr=None,
                 bias_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        ns = ([normalized_shape] if isinstance(normalized_shape, int)
              else list(normalized_shape))
        self._normalized_shape = ns
        self.weight = self.create_parameter(
            ns, attr=param_attr, default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter(ns, is_bias=True, attr=bias_attr)
        self._epsilon = epsilon

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [num_channels], default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_channels], is_bias=True)
        self._groups = num_groups
        self._epsilon = epsilon

    def forward(self, x):
        from ..ops import nn_ops

        return nn_ops.group_norm(
            {"X": x, "Scale": self.weight.value, "Bias": self.bias.value},
            {"groups": self._groups, "epsilon": self._epsilon})["Y"]


class Embedding(Layer):
    """Parity: dygraph/nn.py Embedding."""

    def __init__(self, size, padding_idx=None, param_attr=None,
                 dtype="float32", is_sparse=False):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            list(size), attr=param_attr,
            default_initializer=XavierInitializer())
        self._padding_idx = padding_idx

    def forward(self, ids):
        return F.embedding(ids, self.weight, self._padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, mode="upscale_in_train"):
        super().__init__()
        self._p = p
        self._mode = mode

    def forward(self, x):
        return F.dropout(x, self._p, training=self.training, mode=self._mode)


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        for i, l in enumerate(layers):
            if isinstance(l, tuple):
                self.add_sublayer(l[0], l[1])
            else:
                self.add_sublayer(str(i), l)

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]


class LayerList(Layer):
    def __init__(self, layers=None):
        super().__init__()
        for i, l in enumerate(layers or []):
            self.add_sublayer(str(i), l)

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def __iter__(self):
        return iter(self._sub_layers.values())

    def __len__(self):
        return len(self._sub_layers)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]


def _apply_act(x, act):
    if act is None:
        return x
    return getattr(F, act)(x)


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class GELU(Layer):
    def __init__(self, approximate=False):
        super().__init__()
        self._approx = approximate

    def forward(self, x):
        return F.gelu(x, self._approx)


class Sigmoid(Layer):
    def forward(self, x):
        return F.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return F.tanh(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


# ---------------------------------------------------------------------------
# Transformer blocks (flagship path; fused attention kernels underneath)
# ---------------------------------------------------------------------------

class MultiHeadAttention(Layer):
    """Self/cross attention with the fused SDPA kernel. Replaces the
    reference's fused/multihead_matmul_op.cu transformer path."""

    def __init__(self, embed_dim, num_heads, dropout=0.0, bias_attr=None,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        assert embed_dim % num_heads == 0
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.embed_dim = embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr, dtype=dtype)
        self.k_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr, dtype=dtype)
        self.v_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr, dtype=dtype)
        self.out_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr, dtype=dtype)
        self._dropout = dropout

    def forward(self, query, key=None, value=None, attn_mask=None,
                is_causal=False):
        key = key if key is not None else query
        value = value if value is not None else query
        b, sq, _ = query.shape
        sk = key.shape[1]
        q = self.q_proj(query).reshape(b, sq, self.num_heads, self.head_dim)
        k = self.k_proj(key).reshape(b, sk, self.num_heads, self.head_dim)
        v = self.v_proj(value).reshape(b, sk, self.num_heads, self.head_dim)
        q = jnp.transpose(q, (0, 2, 1, 3))
        k = jnp.transpose(k, (0, 2, 1, 3))
        v = jnp.transpose(v, (0, 2, 1, 3))
        out = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self._dropout if self.training else 0.0,
            is_causal=is_causal, training=self.training)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, sq, self.embed_dim)
        return self.out_proj(out)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="gelu", normalize_before=False, dtype="float32"):
        super().__init__(dtype=dtype)
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=dropout,
                                            dtype=dtype)
        self.linear1 = Linear(d_model, dim_feedforward, dtype=dtype)
        self.linear2 = Linear(dim_feedforward, d_model, dtype=dtype)
        self.norm1 = LayerNorm(d_model, dtype=dtype)
        self.norm2 = LayerNorm(d_model, dtype=dtype)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self._activation = activation
        self._pre_norm = normalize_before

    def forward(self, src, src_mask=None):
        residual = src
        if self._pre_norm:
            src = self.norm1(src)
        src = self.self_attn(src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self._pre_norm:
            src = self.norm1(src)
        residual = src
        if self._pre_norm:
            src = self.norm2(src)
        src = self.linear2(_apply_act(self.linear1(src), self._activation))
        src = residual + self.dropout2(src)
        if not self._pre_norm:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer_fn, num_layers):
        super().__init__()
        self.layers = LayerList([encoder_layer_fn() for _ in range(num_layers)])

    def forward(self, src, src_mask=None):
        for layer in self.layers:
            src = layer(src, src_mask)
        return src


class LSTMCell(Layer):
    """Standard LSTM cell (parity: the reference's lstm/dynamic_lstm op
    family, operators/lstm_op.h math with forget-bias folded in).

    call(x [B,I], (h [B,H], c [B,H])) -> (h', (h', c'))
    """

    def __init__(self, input_size, hidden_size, forget_bias=0.0,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.forget_bias = forget_bias
        self.weight_ih = self.create_parameter([input_size, 4 * hidden_size])
        self.weight_hh = self.create_parameter([hidden_size, 4 * hidden_size])
        self.bias = self.create_parameter([4 * hidden_size], is_bias=True)

    def forward(self, x, state):
        import jax.numpy as jnp

        h, c = state
        gates = (x @ F._val(self.weight_ih) + h @ F._val(self.weight_hh)
                 + F._val(self.bias))
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = F.sigmoid(i)
        f = F.sigmoid(f + self.forget_bias)
        g = jnp.tanh(g)
        o = F.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)

    def zero_state(self, batch):
        import jax.numpy as jnp

        z = jnp.zeros((batch, self.hidden_size), self._dtype)
        return (z, z)


class GRUCell(Layer):
    """GRU cell (parity: gru_op.h / dynamic_gru)."""

    def __init__(self, input_size, hidden_size, dtype="float32"):
        super().__init__(dtype=dtype)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter([input_size, 3 * hidden_size])
        self.weight_hh = self.create_parameter([hidden_size, 3 * hidden_size])
        self.bias = self.create_parameter([3 * hidden_size], is_bias=True)

    def forward(self, x, state):
        import jax.numpy as jnp

        h = state
        xi = x @ F._val(self.weight_ih) + F._val(self.bias)
        hi = h @ F._val(self.weight_hh)
        xr, xz, xn = jnp.split(xi, 3, axis=-1)
        hr, hz, hn = jnp.split(hi, 3, axis=-1)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h_new = (1 - z) * n + z * h
        return h_new, h_new

    def zero_state(self, batch):
        import jax.numpy as jnp

        return jnp.zeros((batch, self.hidden_size), self._dtype)


class ParameterList(Layer):
    """Indexed parameter container (parity: dygraph/container.py
    ParameterList:91)."""

    def __init__(self, parameters=None):
        super().__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __setitem__(self, idx, param):
        self._parameters[str(idx)] = param

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self


class Conv3D(Layer):
    """NCDHW 3-D convolution (parity: dygraph/nn.py Conv3D:272)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        groups = groups or 1
        fs = ([filter_size] * 3 if isinstance(filter_size, int)
              else list(filter_size))
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups] + fs, attr=param_attr)
        self.bias = (self.create_parameter([num_filters], is_bias=True,
                                           attr=bias_attr)
                     if bias_attr is not False else None)
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        self._act = act

    def forward(self, x):
        from ..ops import extended_ops

        out = extended_ops.conv3d(
            {"Input": x, "Filter": self.weight.value},
            {"strides": self._stride, "paddings": self._padding,
             "dilations": self._dilation, "groups": self._groups})["Output"]
        if self.bias is not None:
            out = out + self.bias.value.reshape(1, -1, 1, 1, 1)
        return _apply_act(out, self._act)


class Conv3DTranspose(Layer):
    """NCDHW transposed 3-D convolution (parity: dygraph/nn.py
    Conv3DTranspose:474)."""

    def __init__(self, num_channels, num_filters, filter_size, padding=0,
                 stride=1, dilation=1, groups=None, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        groups = groups or 1
        fs = ([filter_size] * 3 if isinstance(filter_size, int)
              else list(filter_size))
        self.weight = self.create_parameter(
            [num_channels, num_filters // groups] + fs, attr=param_attr)
        self.bias = (self.create_parameter([num_filters], is_bias=True,
                                           attr=bias_attr)
                     if bias_attr is not False else None)
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        self._act = act

    def forward(self, x):
        from ..ops import extended_ops

        out = extended_ops.conv3d_transpose(
            {"Input": x, "Filter": self.weight.value},
            {"strides": self._stride, "paddings": self._padding,
             "dilations": self._dilation, "groups": self._groups})["Output"]
        if self.bias is not None:
            out = out + self.bias.value.reshape(1, -1, 1, 1, 1)
        return _apply_act(out, self._act)


class GRUUnit(Layer):
    """Single GRU step over pre-projected input (parity: dygraph/nn.py
    GRUUnit:1505; op semantics operators/gru_unit_op.h).

    `size` is 3*H as in the reference; call(input [B, 3H], hidden [B, H])
    -> (hidden', reset_hidden_prev, gate)."""

    def __init__(self, size, param_attr=None, bias_attr=None,
                 activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__(dtype=dtype)
        h = size // 3
        self.weight = self.create_parameter([h, 3 * h], attr=param_attr)
        self.bias = (self.create_parameter([1, 3 * h], is_bias=True,
                                           attr=bias_attr)
                     if bias_attr is not False else None)
        self._activation = activation
        self._gate_activation = gate_activation
        self._origin_mode = origin_mode

    def forward(self, input, hidden):
        from ..ops import rnn_ops

        ins = {"Input": input, "HiddenPrev": hidden,
               "Weight": self.weight.value}
        if self.bias is not None:
            ins["Bias"] = self.bias.value
        outs = rnn_ops.gru_unit(
            ins, {"activation": self._activation,
                  "gate_activation": self._gate_activation,
                  "origin_mode": self._origin_mode})
        return outs["Hidden"], outs["ResetHiddenPrev"], outs["Gate"]


class NCE(Layer):
    """Noise-contrastive estimation loss head (parity: dygraph/nn.py
    NCE:1683; op operators/nce_op.cc).  call(input [N, D], label [N, 1])
    -> cost [N, 1], scaled per-example by `sample_weight` [N] when given
    (at construction or per call).  Negatives are drawn fresh each call:
    uniform / log-uniform / custom_dist samplers; the loss's
    noise-probability correction uses the uniform form (documented
    approximation for the non-uniform samplers)."""

    def __init__(self, num_total_classes, dim, sample_weight=None,
                 param_attr=None, bias_attr=None, num_neg_samples=None,
                 sampler="uniform", custom_dist=None, seed=0,
                 is_sparse=False, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter([num_total_classes, dim],
                                            attr=param_attr)
        self.bias = (self.create_parameter([num_total_classes, 1],
                                           is_bias=True, attr=bias_attr)
                     if bias_attr is not False else None)
        self._num_total_classes = num_total_classes
        self._num_neg = int(num_neg_samples or 10)
        self._sample_weight = sample_weight   # [N] per-example cost scale
        if sampler not in ("uniform", "log_uniform", "custom_dist"):
            raise ValueError(f"unknown NCE sampler {sampler!r}")
        if sampler == "custom_dist" and custom_dist is None:
            raise ValueError("custom_dist sampler needs custom_dist probs")
        self._sampler = sampler
        self._custom_dist = custom_dist

    def _sample_ids(self, n):
        import jax

        key = default_rng.next_key()
        c, s = self._num_total_classes, self._num_neg
        if self._sampler == "uniform":
            return jax.random.randint(key, (n, s), 0, c)
        if self._sampler == "log_uniform":
            # inverse-CDF of P(k) ~ log((k+2)/(k+1)) / log(C+1)
            u = jax.random.uniform(key, (n, s))
            return (jnp.exp(u * math.log(c + 1.0)) - 1.0).astype(jnp.int32)
        probs = jnp.asarray(self._custom_dist)
        return jax.random.choice(key, c, (n, s), p=probs / probs.sum())

    def forward(self, input, label, sample_weight=None):
        from ..ops import loss_ops

        ins = {"Input": input, "Label": label,
               "Weight": self.weight.value,
               "SampleIds": self._sample_ids(input.shape[0])}
        if self.bias is not None:
            ins["Bias"] = self.bias.value
        cost = loss_ops.nce(
            ins, {"num_total_classes": self._num_total_classes,
                  "num_neg_samples": self._num_neg})["Cost"]
        sw = sample_weight if sample_weight is not None \
            else self._sample_weight
        if sw is not None:
            cost = cost * jnp.reshape(
                sw.value if hasattr(sw, "value") else jnp.asarray(sw),
                (-1, 1))
        return cost


class PRelu(Layer):
    """Learnable leaky-ReLU (parity: dygraph/nn.py PRelu:1917).  mode
    'all' (one alpha), 'channel' (per-channel), 'element' (per-element,
    needs input_shape)."""

    def __init__(self, mode, channel=None, input_shape=None,
                 param_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            if channel is None:
                raise ValueError("PRelu mode 'channel' needs `channel`")
            shape = [1, channel, 1, 1]
        elif mode == "element":
            if input_shape is None:
                raise ValueError("PRelu mode 'element' needs `input_shape`")
            # batch dim is NOT part of the parameter (ref nn.py:1999)
            shape = [1] + list(input_shape)[1:]
        else:
            raise ValueError(f"unknown PRelu mode {mode!r}")
        self._mode = mode
        # Constant(1.0) = identity at init, matching the dygraph class
        # (ref nn.py:2007); the static fluid.layers.prelu builder keeps
        # the op default 0.25
        self.weight = self.create_parameter(
            shape, attr=param_attr,
            default_initializer=ConstantInitializer(1.0))

    def forward(self, x):
        from ..ops import nn_ops

        return nn_ops.prelu({"X": x, "Alpha": self.weight.value},
                            {"mode": self._mode})["Out"]


class BilinearTensorProduct(Layer):
    """out_t = x W_t y^T + b (parity: dygraph/nn.py
    BilinearTensorProduct:2020)."""

    def __init__(self, input1_dim, input2_dim, output_dim, name=None,
                 act=None, param_attr=None, bias_attr=None,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [output_dim, input1_dim, input2_dim], attr=param_attr)
        self.bias = (self.create_parameter([1, output_dim], is_bias=True,
                                           attr=bias_attr)
                     if bias_attr is not False else None)
        self._act = act

    def forward(self, x, y):
        from ..ops import misc_ops

        ins = {"X": x, "Y": y, "Weight": self.weight.value}
        if self.bias is not None:
            ins["Bias"] = self.bias.value
        return _apply_act(
            misc_ops.bilinear_tensor_product(ins, {})["Out"], self._act)


class SequenceConv(Layer):
    """Context-window projection over padded sequences (parity:
    dygraph/nn.py SequenceConv:2356 — which the reference REFUSES to run
    in dygraph mode; this one works).  Weights are built lazily from the
    input feature dim on first call; call(x [B, T, D], lengths [B])."""

    def __init__(self, name_scope=None, num_filters=None, filter_size=3,
                 filter_stride=1, padding=None, bias_attr=None,
                 param_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope=name_scope, dtype=dtype)
        if num_filters is None:
            raise ValueError("SequenceConv needs num_filters")
        self._num_filters = num_filters
        self._filter_size = filter_size
        self._filter_stride = filter_stride
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self.weight = None
        self.bias = None

    @property
    def _lazy_unbuilt(self):
        return "weight" not in self._parameters

    def _build(self, x):
        if self._lazy_unbuilt:
            d = int(x.shape[-1])
            self.weight = self.create_parameter(
                [self._filter_size * d, self._num_filters],
                attr=self._param_attr)
            if self._bias_attr is not False:
                self.bias = self.create_parameter(
                    [self._num_filters], is_bias=True, attr=self._bias_attr)

    def __call__(self, *args, **kwargs):
        # build BEFORE the tape snapshots the parameter list, so the
        # first recorded call already differentiates through the weights
        self._build(args[0])
        return super().__call__(*args, **kwargs)

    def forward(self, x, lengths=None):
        from ..ops import sequence_ops

        if lengths is None:
            lengths = jnp.full((x.shape[0],), x.shape[1], jnp.int32)
        out = sequence_ops.sequence_conv(
            {"X": x, "Filter": self.weight.value, "Length": lengths},
            {"contextLength": self._filter_size,
             "contextStart": -(self._filter_size // 2),
             "contextStride": self._filter_stride})["Out"]
        if "bias" in self._parameters and self.bias is not None:
            out = out + self.bias.value.reshape(1, 1, -1)
        return _apply_act(out, self._act)


class RowConv(Layer):
    """Lookahead (row) convolution, DeepSpeech2-style (parity:
    dygraph/nn.py RowConv:2450 — reference refuses dygraph mode; this
    one works).  Filter [future_context_size+1, D] built lazily."""

    def __init__(self, name_scope=None, future_context_size=None,
                 param_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope=name_scope, dtype=dtype)
        if future_context_size is None:
            raise ValueError("RowConv needs future_context_size")
        self._future_context_size = future_context_size
        self._param_attr = param_attr
        self._act = act
        self.weight = None

    @property
    def _lazy_unbuilt(self):
        return "weight" not in self._parameters

    def _build(self, x):
        if self._lazy_unbuilt:
            self.weight = self.create_parameter(
                [self._future_context_size + 1, int(x.shape[-1])],
                attr=self._param_attr)

    def __call__(self, *args, **kwargs):
        self._build(args[0])
        return super().__call__(*args, **kwargs)

    def forward(self, x, lengths=None):
        from ..ops import rnn_ops

        ins = {"X": x, "Filter": self.weight.value}
        if lengths is not None:
            ins["Length"] = lengths
        return _apply_act(rnn_ops.row_conv(ins, {})["Out"], self._act)


class SpectralNorm(Layer):
    """Spectral weight normalization via power iteration (parity:
    dygraph/nn.py SpectralNorm:2629; op operators/spectral_norm_op.h).
    call(weight) -> weight / sigma_max; u/v are persistent non-trainable
    power-iteration vectors."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        h = int(weight_shape[dim])
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= int(s)
        self.weight_u = self.create_parameter(
            [h], default_initializer=NormalInitializer(0.0, 1.0))
        self.weight_v = self.create_parameter(
            [w], default_initializer=NormalInitializer(0.0, 1.0))
        self.weight_u.trainable = False
        self.weight_v.trainable = False
        self._dim, self._power_iters, self._eps = dim, power_iters, eps

    def forward(self, weight):
        from ..ops import misc_ops

        return misc_ops.spectral_norm(
            {"Weight": weight, "U": self.weight_u.value,
             "V": self.weight_v.value},
            {"dim": self._dim, "power_iters": self._power_iters,
             "eps": self._eps})["Out"]


class TreeConv(Layer):
    """Tree-based convolution (TBCNN) over (nodes, edges) (parity:
    dygraph/nn.py TreeConv:2734; op operators/tree_conv_op.cc).
    call(nodes_vector [B, M, F], edge_set [B, E, 2]) ->
    [B, M, output_size, num_filters]."""

    def __init__(self, feature_size, output_size, num_filters=1,
                 max_depth=2, act="tanh", param_attr=None, bias_attr=None,
                 name=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [feature_size, 3, output_size, num_filters], attr=param_attr)
        self.bias = (self.create_parameter([num_filters], is_bias=True,
                                           attr=bias_attr)
                     if bias_attr is not False else None)
        self._max_depth = max_depth
        self._act = act

    def forward(self, nodes_vector, edge_set):
        from ..ops import extended_ops

        out = extended_ops.tree_conv(
            {"NodesVector": nodes_vector, "EdgeSet": edge_set,
             "Filter": self.weight.value},
            {"max_depth": self._max_depth})["Out"]
        if self.bias is not None:
            out = out + self.bias.value.reshape(1, 1, 1, -1)
        return _apply_act(out, self._act)


class RNN(Layer):
    """Run a cell over [B, T, I] with lax.scan; optional length masking
    freezes state past each sequence's end (dynamic_rnn parity)."""

    def __init__(self, cell, time_major=False):
        super().__init__()
        self.cell = cell
        self.time_major = time_major

    def forward(self, x, initial_state=None, length=None):
        import jax
        import jax.numpy as jnp

        if not self.time_major:
            x = jnp.swapaxes(x, 0, 1)          # [T, B, I]
        batch = x.shape[1]
        state = (initial_state if initial_state is not None
                 else self.cell.zero_state(batch))

        def step(carry, inp):
            t, st = carry
            out, new_st = self.cell(inp, st)
            if length is not None:
                alive = (t < length).reshape((batch,) + (1,))
                new_st = jax.tree.map(
                    lambda n, o: jnp.where(alive, n, o), new_st, st)
                out = jnp.where(alive, out, 0.0)
            return (t + 1, new_st), out

        (_, final_state), outs = jax.lax.scan(step, (0, state), x)
        if not self.time_major:
            outs = jnp.swapaxes(outs, 0, 1)    # [B, T, H]
        return outs, final_state
