"""Eager (dygraph) layers.

Parity: /root/reference/python/paddle/fluid/dygraph/nn.py (Conv2D, Pool2D,
FC/Linear, BatchNorm, Embedding, LayerNorm, GRUUnit, ...) plus the
transformer building blocks the flagship models need.
"""

import math

import jax.numpy as jnp

from . import functional as F
from .functional import scaled_dot_product_attention
from .layers import (
    Layer,
    functional_call,
    param_dict,
    load_param_dict,
)
from .parameter import EagerParameter, seed, default_rng
from ..framework.initializer import (
    ConstantInitializer,
    NormalInitializer,
    UniformInitializer,
    XavierInitializer,
)

__all__ = [
    "Layer", "EagerParameter", "functional_call", "param_dict",
    "load_param_dict", "seed", "functional", "Linear", "Conv2D",
    "Conv2DTranspose", "Pool2D", "MaxPool2D", "AvgPool2D", "BatchNorm",
    "LayerNorm", "GroupNorm", "Embedding", "Dropout", "Sequential",
    "LayerList", "ReLU", "GELU", "Sigmoid", "Tanh", "Softmax",
    "MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
    "scaled_dot_product_attention", "LSTMCell", "GRUCell", "RNN",
]

functional = F


class Linear(Layer):
    """Parity: dygraph/nn.py Linear (mul + bias via core.ops)."""

    def __init__(self, input_dim, output_dim, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter([input_dim, output_dim],
                                            attr=param_attr)
        if bias_attr is not False:
            self.bias = self.create_parameter([output_dim], is_bias=True,
                                              attr=bias_attr)
        else:
            self.bias = None
        self._act = act

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        return _apply_act(out, self._act)


class Conv2D(Layer):
    """Parity: dygraph/nn.py Conv2D (NCHW)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, data_format="NCHW",
                 dtype="float32"):
        super().__init__(dtype=dtype)
        fs = [filter_size] * 2 if isinstance(filter_size, int) else list(filter_size)
        self.weight = self.create_parameter(
            [num_filters, num_channels // groups] + fs, attr=param_attr)
        if bias_attr is not False:
            self.bias = self.create_parameter([num_filters], is_bias=True,
                                              attr=bias_attr)
        else:
            self.bias = None
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        self._act = act
        self._data_format = data_format

    def forward(self, x):
        out = F.conv2d(x, self.weight, self.bias, self._stride,
                       self._padding, self._dilation, self._groups,
                       data_format=self._data_format)
        return _apply_act(out, self._act)


class Conv2DTranspose(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        fs = [filter_size] * 2 if isinstance(filter_size, int) else list(filter_size)
        self.weight = self.create_parameter(
            [num_channels, num_filters // groups] + fs, attr=param_attr)
        if bias_attr is not False:
            self.bias = self.create_parameter([num_filters], is_bias=True,
                                              attr=bias_attr)
        else:
            self.bias = None
        self._stride, self._padding = stride, padding
        self._dilation, self._groups = dilation, groups
        self._act = act

    def forward(self, x):
        out = F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                 self._padding, self._dilation, self._groups)
        return _apply_act(out, self._act)


class Pool2D(Layer):
    """Parity: dygraph/nn.py Pool2D."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False,
                 data_format="NCHW"):
        super().__init__()
        self._pool_size = pool_size
        self._pool_type = pool_type
        self._pool_stride = pool_stride
        self._pool_padding = pool_padding
        self._global = global_pooling
        self._data_format = data_format

    def forward(self, x):
        if self._global:
            axis = (2, 3) if self._data_format == "NCHW" else (1, 2)
            if self._pool_type == "max":
                return jnp.max(x, axis=axis, keepdims=True)
            return jnp.mean(x, axis=axis, keepdims=True)
        if self._pool_type == "max":
            return F.max_pool2d(x, self._pool_size, self._pool_stride,
                                self._pool_padding,
                                data_format=self._data_format)
        return F.avg_pool2d(x, self._pool_size, self._pool_stride,
                            self._pool_padding,
                            data_format=self._data_format)


class MaxPool2D(Pool2D):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW"):
        super().__init__(kernel_size, "max", stride or kernel_size,
                         padding, data_format=data_format)


class AvgPool2D(Pool2D):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW"):
        super().__init__(kernel_size, "avg", stride or kernel_size,
                         padding, data_format=data_format)


class BatchNorm(Layer):
    """Parity: dygraph/nn.py BatchNorm. Running stats are buffers; under a
    functional train step use nn.layers.functional_call with
    collect_buffers (see train utilities in paddle_tpu.jit)."""

    def __init__(self, num_channels, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, act=None,
                 data_format="NCHW", dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [num_channels], attr=param_attr,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_channels], is_bias=True,
                                          attr=bias_attr)
        self.register_buffer("_mean", jnp.zeros(num_channels))
        self.register_buffer("_variance", jnp.ones(num_channels))
        self._momentum, self._epsilon = momentum, epsilon
        self._data_format = data_format
        self._act = act

    def forward(self, x):
        y, new_mean, new_var = F.batch_norm(
            x, self._buffers["_mean"], self._buffers["_variance"],
            self.weight, self.bias, training=self.training,
            momentum=self._momentum, epsilon=self._epsilon,
            data_format=self._data_format)
        if self.training:
            self._buffers["_mean"] = new_mean
            self._buffers["_variance"] = new_var
        return _apply_act(y, self._act)


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, param_attr=None,
                 bias_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        ns = ([normalized_shape] if isinstance(normalized_shape, int)
              else list(normalized_shape))
        self._normalized_shape = ns
        self.weight = self.create_parameter(
            ns, attr=param_attr, default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter(ns, is_bias=True, attr=bias_attr)
        self._epsilon = epsilon

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, dtype="float32"):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            [num_channels], default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([num_channels], is_bias=True)
        self._groups = num_groups
        self._epsilon = epsilon

    def forward(self, x):
        from ..ops import nn_ops

        return nn_ops.group_norm(
            {"X": x, "Scale": self.weight.value, "Bias": self.bias.value},
            {"groups": self._groups, "epsilon": self._epsilon})["Y"]


class Embedding(Layer):
    """Parity: dygraph/nn.py Embedding."""

    def __init__(self, size, padding_idx=None, param_attr=None,
                 dtype="float32", is_sparse=False):
        super().__init__(dtype=dtype)
        self.weight = self.create_parameter(
            list(size), attr=param_attr,
            default_initializer=XavierInitializer())
        self._padding_idx = padding_idx

    def forward(self, ids):
        return F.embedding(ids, self.weight, self._padding_idx)


class Dropout(Layer):
    def __init__(self, p=0.5, mode="upscale_in_train"):
        super().__init__()
        self._p = p
        self._mode = mode

    def forward(self, x):
        return F.dropout(x, self._p, training=self.training, mode=self._mode)


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        for i, l in enumerate(layers):
            if isinstance(l, tuple):
                self.add_sublayer(l[0], l[1])
            else:
                self.add_sublayer(str(i), l)

    def forward(self, x):
        for l in self._sub_layers.values():
            x = l(x)
        return x

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]


class LayerList(Layer):
    def __init__(self, layers=None):
        super().__init__()
        for i, l in enumerate(layers or []):
            self.add_sublayer(str(i), l)

    def append(self, layer):
        self.add_sublayer(str(len(self._sub_layers)), layer)
        return self

    def __iter__(self):
        return iter(self._sub_layers.values())

    def __len__(self):
        return len(self._sub_layers)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]


def _apply_act(x, act):
    if act is None:
        return x
    return getattr(F, act)(x)


class ReLU(Layer):
    def forward(self, x):
        return F.relu(x)


class GELU(Layer):
    def __init__(self, approximate=False):
        super().__init__()
        self._approx = approximate

    def forward(self, x):
        return F.gelu(x, self._approx)


class Sigmoid(Layer):
    def forward(self, x):
        return F.sigmoid(x)


class Tanh(Layer):
    def forward(self, x):
        return F.tanh(x)


class Softmax(Layer):
    def __init__(self, axis=-1):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, self._axis)


# ---------------------------------------------------------------------------
# Transformer blocks (flagship path; fused attention kernels underneath)
# ---------------------------------------------------------------------------

class MultiHeadAttention(Layer):
    """Self/cross attention with the fused SDPA kernel. Replaces the
    reference's fused/multihead_matmul_op.cu transformer path."""

    def __init__(self, embed_dim, num_heads, dropout=0.0, bias_attr=None,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        assert embed_dim % num_heads == 0
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.embed_dim = embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr, dtype=dtype)
        self.k_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr, dtype=dtype)
        self.v_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr, dtype=dtype)
        self.out_proj = Linear(embed_dim, embed_dim, bias_attr=bias_attr, dtype=dtype)
        self._dropout = dropout

    def forward(self, query, key=None, value=None, attn_mask=None,
                is_causal=False):
        key = key if key is not None else query
        value = value if value is not None else query
        b, sq, _ = query.shape
        sk = key.shape[1]
        q = self.q_proj(query).reshape(b, sq, self.num_heads, self.head_dim)
        k = self.k_proj(key).reshape(b, sk, self.num_heads, self.head_dim)
        v = self.v_proj(value).reshape(b, sk, self.num_heads, self.head_dim)
        q = jnp.transpose(q, (0, 2, 1, 3))
        k = jnp.transpose(k, (0, 2, 1, 3))
        v = jnp.transpose(v, (0, 2, 1, 3))
        out = scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask,
            dropout_p=self._dropout if self.training else 0.0,
            is_causal=is_causal, training=self.training)
        out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, sq, self.embed_dim)
        return self.out_proj(out)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="gelu", normalize_before=False, dtype="float32"):
        super().__init__(dtype=dtype)
        self.self_attn = MultiHeadAttention(d_model, nhead, dropout=dropout,
                                            dtype=dtype)
        self.linear1 = Linear(d_model, dim_feedforward, dtype=dtype)
        self.linear2 = Linear(dim_feedforward, d_model, dtype=dtype)
        self.norm1 = LayerNorm(d_model, dtype=dtype)
        self.norm2 = LayerNorm(d_model, dtype=dtype)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self._activation = activation
        self._pre_norm = normalize_before

    def forward(self, src, src_mask=None):
        residual = src
        if self._pre_norm:
            src = self.norm1(src)
        src = self.self_attn(src, attn_mask=src_mask)
        src = residual + self.dropout1(src)
        if not self._pre_norm:
            src = self.norm1(src)
        residual = src
        if self._pre_norm:
            src = self.norm2(src)
        src = self.linear2(_apply_act(self.linear1(src), self._activation))
        src = residual + self.dropout2(src)
        if not self._pre_norm:
            src = self.norm2(src)
        return src


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer_fn, num_layers):
        super().__init__()
        self.layers = LayerList([encoder_layer_fn() for _ in range(num_layers)])

    def forward(self, src, src_mask=None):
        for layer in self.layers:
            src = layer(src, src_mask)
        return src


class LSTMCell(Layer):
    """Standard LSTM cell (parity: the reference's lstm/dynamic_lstm op
    family, operators/lstm_op.h math with forget-bias folded in).

    call(x [B,I], (h [B,H], c [B,H])) -> (h', (h', c'))
    """

    def __init__(self, input_size, hidden_size, forget_bias=0.0,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.forget_bias = forget_bias
        self.weight_ih = self.create_parameter([input_size, 4 * hidden_size])
        self.weight_hh = self.create_parameter([hidden_size, 4 * hidden_size])
        self.bias = self.create_parameter([4 * hidden_size], is_bias=True)

    def forward(self, x, state):
        import jax.numpy as jnp

        h, c = state
        gates = (x @ F._val(self.weight_ih) + h @ F._val(self.weight_hh)
                 + F._val(self.bias))
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = F.sigmoid(i)
        f = F.sigmoid(f + self.forget_bias)
        g = jnp.tanh(g)
        o = F.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)

    def zero_state(self, batch):
        import jax.numpy as jnp

        z = jnp.zeros((batch, self.hidden_size), self._dtype)
        return (z, z)


class GRUCell(Layer):
    """GRU cell (parity: gru_op.h / dynamic_gru)."""

    def __init__(self, input_size, hidden_size, dtype="float32"):
        super().__init__(dtype=dtype)
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.weight_ih = self.create_parameter([input_size, 3 * hidden_size])
        self.weight_hh = self.create_parameter([hidden_size, 3 * hidden_size])
        self.bias = self.create_parameter([3 * hidden_size], is_bias=True)

    def forward(self, x, state):
        import jax.numpy as jnp

        h = state
        xi = x @ F._val(self.weight_ih) + F._val(self.bias)
        hi = h @ F._val(self.weight_hh)
        xr, xz, xn = jnp.split(xi, 3, axis=-1)
        hr, hz, hn = jnp.split(hi, 3, axis=-1)
        r = F.sigmoid(xr + hr)
        z = F.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h_new = (1 - z) * n + z * h
        return h_new, h_new

    def zero_state(self, batch):
        import jax.numpy as jnp

        return jnp.zeros((batch, self.hidden_size), self._dtype)


class RNN(Layer):
    """Run a cell over [B, T, I] with lax.scan; optional length masking
    freezes state past each sequence's end (dynamic_rnn parity)."""

    def __init__(self, cell, time_major=False):
        super().__init__()
        self.cell = cell
        self.time_major = time_major

    def forward(self, x, initial_state=None, length=None):
        import jax
        import jax.numpy as jnp

        if not self.time_major:
            x = jnp.swapaxes(x, 0, 1)          # [T, B, I]
        batch = x.shape[1]
        state = (initial_state if initial_state is not None
                 else self.cell.zero_state(batch))

        def step(carry, inp):
            t, st = carry
            out, new_st = self.cell(inp, st)
            if length is not None:
                alive = (t < length).reshape((batch,) + (1,))
                new_st = jax.tree.map(
                    lambda n, o: jnp.where(alive, n, o), new_st, st)
                out = jnp.where(alive, out, 0.0)
            return (t + 1, new_st), out

        (_, final_state), outs = jax.lax.scan(step, (0, state), x)
        if not self.time_major:
            outs = jnp.swapaxes(outs, 0, 1)    # [B, T, H]
        return outs, final_state
