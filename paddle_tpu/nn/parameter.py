"""Eager Parameter + RNG state.

Parity: the dygraph VarBase parameter half
(/root/reference/paddle/fluid/imperative/layer.h:56) — an eager tensor with
a name, trainable flag and in-place `set_value`, minus the grad slot (JAX
autodiff is transform-based, not tape-based).
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np

from .. import flags


class _EagerRng:
    """Global PRNG stream for eager-mode stochastic ops (dropout, init).

    Under jax tracing (jit train steps), a traced key must be threaded in
    explicitly — use key_context so stochastic ops split from the traced
    key instead of baking a constant into the compiled function."""

    def __init__(self):
        self._lock = threading.Lock()
        # lazy: creating a key initializes the jax backend, which must not
        # happen at import time
        self._key = None
        self._override = None

    def seed(self, s):
        with self._lock:
            self._key = jax.random.PRNGKey(s)

    def key_context(self, key):
        import contextlib

        @contextlib.contextmanager
        def ctx():
            old = self._override
            self._override = [key]
            try:
                yield
            finally:
                self._override = old

        return ctx()

    def next_key(self):
        if self._override is not None:
            self._override[0], sub = jax.random.split(self._override[0])
            return sub
        with self._lock:
            if self._key is None:
                self._key = jax.random.PRNGKey(flags.flag("global_seed"))
            self._key, sub = jax.random.split(self._key)
            return sub


default_rng = _EagerRng()


def seed(s):
    """Parity: fluid.dygraph seed / paddle.seed."""
    default_rng.seed(s)
    return default_rng


class EagerParameter:
    """Named trainable array container used by nn.Layer."""

    def __init__(self, value, name=None, trainable=True):
        self.value = jnp.asarray(value)
        self.name = name
        self.trainable = trainable
        self.stop_gradient = not trainable
        # gradient slot filled by the dygraph tape's backward sweep
        # (imperative/layer.h grad_var_); None until a backward runs
        self.grad = None

    @property
    def shape(self):
        return tuple(self.value.shape)

    @property
    def dtype(self):
        return str(self.value.dtype)

    def numpy(self):
        return np.asarray(self.value)

    def set_value(self, v):
        self.value = jnp.asarray(v, dtype=self.value.dtype)

    def gradient(self):
        """Accumulated gradient as numpy, or None (VarBase.gradient())."""
        return None if self.grad is None else np.asarray(self.grad)

    def clear_gradient(self):
        self.grad = None

    def __jax_array__(self):
        # lets elementwise jnp dunders and jnp.asarray consume a Parameter
        # directly (the dygraph VarBase-is-a-tensor ergonomics,
        # imperative/layer.h:56). Reductions (jnp.sum) and jit
        # abstractification reject __jax_array__ on jax>=0.9 — use
        # param.value there.
        return self.value

    def astype(self, dtype):
        return self.value.astype(dtype)

    def reshape(self, *shape):
        return self.value.reshape(*shape)

    def __add__(self, o):
        return self.value + o

    def __radd__(self, o):
        return o + self.value

    def __sub__(self, o):
        return self.value - o

    def __rsub__(self, o):
        return o - self.value

    def __mul__(self, o):
        return self.value * o

    def __rmul__(self, o):
        return o * self.value

    def __truediv__(self, o):
        return self.value / o

    def __rtruediv__(self, o):
        return o / self.value

    def __neg__(self):
        return -self.value

    def __matmul__(self, o):
        return self.value @ o

    def __getitem__(self, idx):
        return self.value[idx]

    def __repr__(self):
        return (f"EagerParameter(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, trainable={self.trainable})")
