"""Eager functional ops (`paddle_tpu.nn.functional`).

The eager twin of the registered op kernels: pythonic signatures over jax
arrays, sharing the kernel implementations in paddle_tpu/ops/ so static and
dygraph modes have identical numerics (the reference achieves this by
routing dygraph through the same OpKernel registry — tracer.cc:45).
"""

import jax
import jax.numpy as jnp

from ..ops import math_ops as _m
from ..ops import nn_ops as _n
from ..ops import tensor_ops as _t
from .parameter import default_rng


def _val(x):
    from ..tape import Variable
    from .parameter import EagerParameter

    if isinstance(x, (EagerParameter, Variable)):
        return x.value
    return x


# -- activations ------------------------------------------------------------

def relu(x):
    return jax.nn.relu(_val(x))


def relu6(x):
    return jnp.clip(_val(x), 0.0, 6.0)


def sigmoid(x):
    return jax.nn.sigmoid(_val(x))


def tanh(x):
    return jnp.tanh(_val(x))


def gelu(x, approximate=False):
    return jax.nn.gelu(_val(x), approximate=approximate)


def leaky_relu(x, negative_slope=0.01):
    return _n.leaky_relu({"X": _val(x)}, {"alpha": negative_slope})["Out"]


def elu(x, alpha=1.0):
    return jax.nn.elu(_val(x), alpha)


def softplus(x):
    return jax.nn.softplus(_val(x))


def silu(x):
    return jax.nn.silu(_val(x))


def swish(x, beta=1.0):
    return _n.swish({"X": _val(x)}, {"beta": beta})["Out"]


def hard_swish(x):
    return _n.hard_swish({"X": _val(x)}, {})["Out"]


def hard_sigmoid(x):
    return _n.hard_sigmoid({"X": _val(x)}, {})["Out"]


def softmax(x, axis=-1):
    x = _amp_cast("softmax", _val(x))
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1):
    x = _amp_cast("log_softmax", _val(x))
    return jax.nn.log_softmax(x, axis=axis)


def _amp_cast(op_type, *xs):
    """Autocast hook: list-aware dispatch under amp.auto_cast (white ops
    run in the compute dtype, black ops are protected back to fp32)."""
    from ..amp import autocast_enabled, cast_for_op

    if not autocast_enabled():
        return xs if len(xs) > 1 else xs[0]
    return cast_for_op(op_type, *xs)


# -- linear / conv / pool ---------------------------------------------------

def linear(x, weight, bias=None):
    xv, wv = _amp_cast("matmul", _val(x), _val(weight))
    out = xv @ wv
    if bias is not None:
        out = out + _val(bias).astype(out.dtype)
    return out


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW"):
    attrs = {
        "strides": [stride, stride] if isinstance(stride, int) else list(stride),
        "paddings": [padding, padding] if isinstance(padding, int) else list(padding),
        "dilations": [dilation, dilation] if isinstance(dilation, int) else list(dilation),
        "groups": groups,
        "data_format": data_format,
    }
    xv, wv = _amp_cast("conv2d", _val(x), _val(weight))
    out = _n.conv2d({"Input": xv, "Filter": wv}, attrs)["Output"]
    if bias is not None:
        b = _val(bias).astype(out.dtype)
        bshape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = out + b.reshape(bshape)
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, dilation=1,
                     groups=1):
    attrs = {
        "strides": [stride, stride] if isinstance(stride, int) else list(stride),
        "paddings": [padding, padding] if isinstance(padding, int) else list(padding),
        "dilations": [dilation, dilation] if isinstance(dilation, int) else list(dilation),
        "groups": groups,
    }
    out = _n.conv2d_transpose({"Input": _val(x), "Filter": _val(weight)},
                              attrs)["Output"]
    if bias is not None:
        out = out + _val(bias).reshape(1, -1, 1, 1)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0,
               data_format="NCHW"):
    stride = stride if stride is not None else kernel_size
    return _n.pool2d({"X": _val(x)}, {
        "ksize": [kernel_size] * 2 if isinstance(kernel_size, int) else list(kernel_size),
        "strides": [stride] * 2 if isinstance(stride, int) else list(stride),
        "paddings": [padding] * 2 if isinstance(padding, int) else list(padding),
        "pooling_type": "max", "data_format": data_format})["Out"]


def avg_pool2d(x, kernel_size, stride=None, padding=0, exclusive=True,
               data_format="NCHW"):
    stride = stride if stride is not None else kernel_size
    return _n.pool2d({"X": _val(x)}, {
        "ksize": [kernel_size] * 2 if isinstance(kernel_size, int) else list(kernel_size),
        "strides": [stride] * 2 if isinstance(stride, int) else list(stride),
        "paddings": [padding] * 2 if isinstance(padding, int) else list(padding),
        "pooling_type": "avg", "exclusive": exclusive,
        "data_format": data_format})["Out"]


def adaptive_avg_pool2d(x, output_size):
    return _n.pool2d({"X": _val(x)}, {
        "ksize": [output_size] * 2 if isinstance(output_size, int) else list(output_size),
        "pooling_type": "avg", "adaptive": True})["Out"]


def adaptive_max_pool2d(x, output_size):
    return _n.pool2d({"X": _val(x)}, {
        "ksize": [output_size] * 2 if isinstance(output_size, int) else list(output_size),
        "pooling_type": "max", "adaptive": True})["Out"]


# -- norm -------------------------------------------------------------------

def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-5):
    x = _val(x)
    if normalized_shape is None:
        begin = x.ndim - 1
    else:
        ns = ([normalized_shape] if isinstance(normalized_shape, int)
              else list(normalized_shape))
        begin = x.ndim - len(ns)
    ins = {"X": x}
    if weight is not None:
        ins["Scale"] = _val(weight).reshape(-1)
    if bias is not None:
        ins["Bias"] = _val(bias).reshape(-1)
    return _n.layer_norm(ins, {"begin_norm_axis": begin,
                               "epsilon": epsilon})["Y"]


def batch_norm(x, running_mean, running_var, weight, bias, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW",
               stats_sample=0):
    out = _n.batch_norm(
        {"X": _val(x), "Scale": _val(weight), "Bias": _val(bias),
         "Mean": _val(running_mean), "Variance": _val(running_var)},
        {"momentum": momentum, "epsilon": epsilon, "is_test": not training,
         "data_layout": data_format, "stats_sample": stats_sample})
    return out["Y"], out["MeanOut"], out["VarianceOut"]


def dropout(x, p=0.5, training=True, mode="upscale_in_train", rng_key=None):
    if p == 0.0 or (not training and mode == "upscale_in_train"):
        return _val(x)
    if rng_key is not None:
        key = rng_key
    elif not training:
        # eval in downgrade_in_infer mode scales by (1-p) deterministically;
        # the kernel ignores the key when is_test
        key = jax.random.PRNGKey(0)
    else:
        key = default_rng.next_key()
    return _n.dropout({"X": _val(x)},
                      {"dropout_prob": p, "is_test": not training,
                       "dropout_implementation": mode, "_rng": key})["Out"]


# -- losses -----------------------------------------------------------------

def cross_entropy(input, label, soft_label=False, axis=-1, reduction="mean",
                  ignore_index=-100):
    """Logits-based CE (softmax fused), matching the reference's
    softmax_with_cross_entropy kernel."""
    logits = _amp_cast("softmax_with_cross_entropy", _val(input))
    out = _n.softmax_with_cross_entropy(
        {"Logits": logits, "Label": _val(label)},
        {"soft_label": soft_label, "axis": axis,
         "ignore_index": ignore_index})["Loss"]
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def mse_loss(input, label, reduction="mean"):
    out = jnp.square(_val(input) - _val(label))
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def binary_cross_entropy_with_logits(logit, label, reduction="mean"):
    out = _n.sigmoid_cross_entropy_with_logits(
        {"X": _val(logit), "Label": _val(label)}, {})["Out"]
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def nll_loss(log_probs, label, reduction="mean"):
    lp = _val(log_probs)
    idx = _val(label).astype(jnp.int32)
    picked = jnp.take_along_axis(lp, idx[..., None], axis=-1)[..., 0]
    out = -picked
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


# -- embedding / misc -------------------------------------------------------

def embedding(ids, weight, padding_idx=None):
    return _n.lookup_table_v2(
        {"Ids": _val(ids), "W": _val(weight)},
        {"padding_idx": -1 if padding_idx is None else padding_idx})["Out"]


def one_hot(x, num_classes):
    return jax.nn.one_hot(_val(x).astype(jnp.int32), num_classes)


def pad(x, pad_width, mode="constant", value=0.0):
    return jnp.pad(_val(x), pad_width, mode=mode,
                   constant_values=value) if mode == "constant" else \
        jnp.pad(_val(x), pad_width, mode=mode)


def interpolate(x, size=None, scale_factor=None, mode="nearest"):
    attrs = {"interp_method": mode}
    if size is not None:
        attrs["out_h"], attrs["out_w"] = int(size[0]), int(size[1])
    if scale_factor is not None:
        attrs["scale"] = float(scale_factor)
    return _n.interpolate({"X": _val(x)}, attrs)["Out"]


def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, scale=None, training=True):
    """Fused attention entry point. Uses the Pallas flash-attention kernel
    on TPU when shapes allow, else the XLA softmax(QK^T)V composition.

    q/k/v: [batch, heads, seq, head_dim]."""
    q, k, v = _val(q), _val(k), _val(v)
    from ..kernels import attention as _attn

    return _attn.dot_product_attention(
        q, k, v, mask=attn_mask, dropout_p=dropout_p, is_causal=is_causal,
        scale=scale, training=training)


# -- dygraph tape integration ------------------------------------------------
# Every public functional op records on the active dygraph tape when called
# with Variables/Parameters (the analogue of the reference routing dygraph
# ops through the tracer, imperative/tracer.cc:45).  With no tape active the
# wrapper is a passthrough.

def _wrap_module_for_tape():
    import types

    from ..tape import wrap_eager_fn

    g = globals()
    for name in list(g):
        f = g[name]
        if (not name.startswith("_") and isinstance(f, types.FunctionType)
                and f.__module__ == __name__):
            g[name] = wrap_eager_fn(f)


_wrap_module_for_tape()
