"""Compatibility shims for the range of jax releases this repo meets.

The package is developed against the TPU host's jax (where `jax.shard_map`
is public API and takes `check_vma=`), but CI containers pin older
releases where shard_map still lives in `jax.experimental.shard_map` and
the kwarg is spelled `check_rep`.  Importing this module (the first thing
`paddle_tpu/__init__.py` does) installs a forwarding `jax.shard_map` when
the attribute is missing, so every call site — package modules, tests,
tools — can uniformly say `jax.shard_map(...)` / `from jax import
shard_map` with `check_vma=` and run on both.  Same treatment for the
other new-jax spellings the package uses: `jax.lax.axis_size`,
`jax.enable_x64`, and Pallas' `CompilerParams`.

Nothing is patched when the attribute already exists.
"""

import inspect

import jax


def _install_shard_map():
    if hasattr(jax, "shard_map"):
        _esm = jax.shard_map
        # mid-window releases have PUBLIC jax.shard_map but still the
        # old `check_rep` kwarg — those need the translation below just
        # as much as the experimental-module ones
        if "check_vma" in inspect.signature(_esm).parameters:
            return
    else:
        from jax.experimental.shard_map import shard_map as _esm

    _params = set(inspect.signature(_esm).parameters)

    def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kw):
        # new-jax spelling -> old-jax spelling (same meaning: replication
        # / varying-manual-axes checking of the per-device body)
        if "check_vma" in kw and "check_vma" not in _params:
            kw["check_rep"] = kw.pop("check_vma")
        return _esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    **kw)

    jax.shard_map = shard_map


def _install_axis_size():
    if hasattr(jax.lax, "axis_size"):
        return

    def axis_size(axis_name):
        # psum of a Python constant is special-cased to a STATIC int
        # (size * 1), so reshapes over the result stay shape-legal
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = axis_size


def _install_enable_x64():
    if hasattr(jax, "enable_x64"):
        return
    from jax.experimental import enable_x64

    jax.enable_x64 = enable_x64


def _install_pallas_compiler_params():
    # new jax renamed pltpu.TPUCompilerParams -> CompilerParams; the
    # kernels say the new name.  Pallas may legitimately be absent.
    try:
        import jax.experimental.pallas.tpu as pltpu
    except Exception:
        return
    if not hasattr(pltpu, "CompilerParams") \
            and hasattr(pltpu, "TPUCompilerParams"):
        pltpu.CompilerParams = pltpu.TPUCompilerParams


_install_shard_map()
_install_axis_size()
_install_enable_x64()
_install_pallas_compiler_params()
