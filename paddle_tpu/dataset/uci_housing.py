"""UCI-housing-shaped synthetic regression dataset.

Parity: /root/reference/python/paddle/dataset/uci_housing.py — 13 features,
scalar target; linear ground truth + noise so fit-a-line converges
(tests/book/test_fit_a_line.py parity).
"""

import numpy as np

FEATURE_DIM = 13
_W = np.random.RandomState(11).uniform(-1, 1, FEATURE_DIM).astype(np.float32)
_B = 0.5


def reader_creator(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        x = rng.uniform(-1, 1, (n, FEATURE_DIM)).astype(np.float32)
        y = x @ _W + _B + rng.normal(0, 0.05, n).astype(np.float32)
        for i in range(n):
            yield x[i], y[i:i + 1].astype(np.float32)

    return reader


def train(n=512):
    return reader_creator(n, seed=3)


def test(n=128):
    return reader_creator(n, seed=4)
