"""Stock datasets.

Parity: /root/reference/python/paddle/dataset/ (mnist, uci_housing, ...).
No network egress is assumed: datasets are deterministic synthetic stand-ins
with the same shapes/dtypes/reader API as the reference, sufficient for the
book-style convergence tests (tests/book/) which only need learnable
structure, not real data.
"""

from . import mnist, uci_housing  # noqa: F401
from .multislot import DatasetFactory, InMemoryDataset, QueueDataset  # noqa: F401
