"""`paddle.dataset` compatibility surface.

The stock dataset zoo lives in `paddle_tpu.datasets` (ONE
implementation — this module aliases it so both reference import paths,
`paddle.dataset.mnist`-style and the plural `datasets` package, resolve
to the same modules).  The industrial tabular feeds (DatasetFactory /
InMemoryDataset / QueueDataset, parity fluid/dataset.py:22) live in
`datasets.multislot` and are re-exported here.
"""

import sys as _sys

from ..datasets import (cifar, conll05, flowers, imdb, imikolov,  # noqa: F401
                        mnist, movielens, multislot, sentiment,
                        uci_housing, voc2012, wmt14, wmt16)
from ..datasets.multislot import (BoxPSDataset, DatasetFactory,  # noqa: F401
                                  InMemoryDataset, QueueDataset)

# make `import paddle_tpu.dataset.mnist`-style submodule imports resolve
for _name in ("mnist", "cifar", "uci_housing", "imdb", "movielens",
              "conll05", "wmt14", "multislot", "flowers", "imikolov",
              "sentiment", "wmt16", "voc2012"):
    _sys.modules[__name__ + "." + _name] = globals()[_name]

__all__ = ["mnist", "cifar", "uci_housing", "imdb", "movielens",
           "conll05", "wmt14", "multislot", "flowers", "imikolov",
           "sentiment", "wmt16", "voc2012", "DatasetFactory",
           "InMemoryDataset", "QueueDataset", "BoxPSDataset"]
