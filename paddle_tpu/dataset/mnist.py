"""MNIST-shaped synthetic dataset.

Parity: /root/reference/python/paddle/dataset/mnist.py (train()/test()
readers yielding (784-float image in [-1,1], int label)).  Images are
class-conditional gaussian blobs so a LeNet/MLP can actually learn —
mirrors the role of tests/book/test_recognize_digits.py fixtures.
"""

import numpy as np

IMAGE_SIZE = 784
NUM_CLASSES = 10


def _make_split(n, seed):
    rng = np.random.RandomState(seed)
    # fixed per-class template patterns
    templates = np.random.RandomState(7).uniform(-1, 1, (NUM_CLASSES, IMAGE_SIZE))
    labels = rng.randint(0, NUM_CLASSES, size=n)
    images = templates[labels] + rng.normal(0, 0.35, (n, IMAGE_SIZE))
    images = np.clip(images, -1.0, 1.0).astype(np.float32)
    return images, labels.astype(np.int64)


def reader_creator(n, seed):
    def reader():
        images, labels = _make_split(n, seed)
        for i in range(n):
            yield images[i], labels[i]

    return reader


def train(n=2048):
    return reader_creator(n, seed=1)


def test(n=512):
    return reader_creator(n, seed=2)
