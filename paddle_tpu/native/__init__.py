"""Native (C++) runtime components, loaded via ctypes.

The reference's host-side runtime is C++ (executors, PS, data feed); the
TPU rebuild keeps the device path in XLA/Pallas and implements the
host-side data plane natively here: sparse-embedding shards and the
MultiSlot text parser live in csrc/ps_shard.cpp, compiled on first use
(g++ -O3 -shared) and bound through ctypes — pybind11 is deliberately
not a dependency.
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRCS = [os.path.join(_REPO, "csrc", f)
         for f in ("ps_shard.cpp", "data_feed.cpp")]
_SO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "libpaddle_tpu_native.so")

_lib = None
_lock = threading.Lock()


def _build():
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           "-o", _SO] + _SRCS
    subprocess.run(cmd, check=True, capture_output=True)


def load():
    """Load (building if needed) the native library; None if unavailable."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < max(os.path.getmtime(s)
                                                   for s in _SRCS)):
                _build()
            lib = ctypes.CDLL(_SO)
        except (OSError, subprocess.CalledProcessError):
            return None
        c = ctypes
        lib.ps_create.restype = c.c_void_p
        lib.ps_create.argtypes = [c.c_int64, c.c_float, c.c_uint64,
                                  c.c_int, c.c_float, c.c_float]
        lib.ps_destroy.argtypes = [c.c_void_p]
        lib.ps_set_lr.argtypes = [c.c_void_p, c.c_float]
        lib.ps_pull.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                c.c_void_p]
        lib.ps_push.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                c.c_void_p]
        lib.ps_assign.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                  c.c_void_p]
        lib.ps_size.restype = c.c_int64
        lib.ps_size.argtypes = [c.c_void_p]
        lib.ps_export.restype = c.c_int64
        lib.ps_export.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                  c.c_int64]
        lib.ps_row_width.restype = c.c_int64
        lib.ps_row_width.argtypes = [c.c_void_p]
        lib.ps_export_full.restype = c.c_int64
        lib.ps_export_full.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                       c.c_int64]
        lib.ps_assign_full.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                       c.c_void_p]
        lib.ps_parse_multislot.restype = c.c_int64
        lib.ps_parse_multislot.argtypes = [
            c.c_char_p, c.c_int64, c.c_int, c.c_void_p, c.c_void_p,
            c.c_int64, c.c_void_p, c.c_int64, c.c_void_p, c.c_int64]
        lib.reader_create.restype = c.c_void_p
        lib.reader_create.argtypes = [
            c.POINTER(c.c_char_p), c.c_int, c.c_int, c.c_void_p,
            c.c_void_p, c.c_int, c.c_int, c.c_int]
        lib.reader_int_width.restype = c.c_int64
        lib.reader_int_width.argtypes = [c.c_void_p]
        lib.reader_float_width.restype = c.c_int64
        lib.reader_float_width.argtypes = [c.c_void_p]
        lib.reader_next.restype = c.c_int64
        lib.reader_next.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p,
                                    c.c_void_p]
        lib.reader_destroy.argtypes = [c.c_void_p]
        _lib = lib
        return _lib


def available():
    return load() is not None


class NativeShard:
    """ctypes wrapper over one C++ embedding shard."""

    OPT = {"sgd": 0, "adagrad": 1}

    def __init__(self, dim, init_range=0.05, seed=0, optimizer="adagrad",
                 lr=0.05, adagrad_eps=1e-6):
        lib = load()
        if lib is None:
            raise RuntimeError("native ps_shard library unavailable")
        self._lib = lib
        self.dim = int(dim)
        self._h = lib.ps_create(self.dim, float(init_range), int(seed),
                                self.OPT[optimizer], float(lr),
                                float(adagrad_eps))

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.ps_destroy(h)
            self._h = None

    def set_lr(self, lr):
        self._lib.ps_set_lr(self._h, float(lr))

    def pull(self, ids):
        ids = np.ascontiguousarray(ids, dtype=np.int64)
        out = np.empty((len(ids), self.dim), dtype=np.float32)
        self._lib.ps_pull(self._h, ids.ctypes.data, len(ids),
                          out.ctypes.data)
        return out

    def push(self, ids, grads):
        ids = np.ascontiguousarray(ids, dtype=np.int64)
        grads = np.ascontiguousarray(grads, dtype=np.float32)
        assert grads.shape == (len(ids), self.dim)
        self._lib.ps_push(self._h, ids.ctypes.data, len(ids),
                          grads.ctypes.data)

    def assign(self, ids, vals):
        ids = np.ascontiguousarray(ids, dtype=np.int64)
        vals = np.ascontiguousarray(vals, dtype=np.float32)
        self._lib.ps_assign(self._h, ids.ctypes.data, len(ids),
                            vals.ctypes.data)

    def __len__(self):
        return int(self._lib.ps_size(self._h))

    def export(self):
        n = len(self)
        ids = np.empty(n, dtype=np.int64)
        vals = np.empty((n, self.dim), dtype=np.float32)
        written = self._lib.ps_export(self._h, ids.ctypes.data,
                                      vals.ctypes.data, n)
        return ids[:written], vals[:written]

    @property
    def row_width(self):
        return int(self._lib.ps_row_width(self._h))

    def export_full(self):
        """(ids, [n, row_width]) including optimizer accumulators."""
        n = len(self)
        w = self.row_width
        ids = np.empty(n, dtype=np.int64)
        vals = np.empty((n, w), dtype=np.float32)
        written = self._lib.ps_export_full(self._h, ids.ctypes.data,
                                           vals.ctypes.data, n)
        return ids[:written], vals[:written]

    def assign_full(self, ids, vals):
        ids = np.ascontiguousarray(ids, dtype=np.int64)
        vals = np.ascontiguousarray(vals, dtype=np.float32)
        assert vals.shape == (len(ids), self.row_width)
        self._lib.ps_assign_full(self._h, ids.ctypes.data, len(ids),
                                 vals.ctypes.data)


def parse_multislot(text, slot_types):
    """Parse MultiSlot lines (data_feed.cc format) with the native parser.

    text: str/bytes of newline-separated instances; slot_types: sequence
    of "float"/"int64" per slot. Returns (counts [n_inst, n_slots],
    int_values flat, float_values flat).
    """
    lib = load()
    if lib is None:
        raise RuntimeError("native ps_shard library unavailable")
    if isinstance(text, str):
        text = text.encode()
    n_slots = len(slot_types)
    is_float = np.array([1 if t == "float" else 0 for t in slot_types],
                        dtype=np.uint8)
    n_lines = max(1, text.count(b"\n") + 1)
    max_groups = n_lines * n_slots
    counts = np.zeros(max_groups, dtype=np.int64)
    # every value consumes >= 2 input bytes, so len(text) bounds the count
    cap = len(text) // 2 + 16
    int_vals = np.empty(cap, dtype=np.int64)
    float_vals = np.empty(cap, dtype=np.float32)
    n = lib.ps_parse_multislot(
        text, len(text), n_slots, is_float.ctypes.data, counts.ctypes.data,
        max_groups, int_vals.ctypes.data, cap, float_vals.ctypes.data, cap)
    if n < 0:
        raise ValueError("malformed MultiSlot input")
    counts = counts[: n * n_slots].reshape(n, n_slots)
    n_int = int(counts[:, is_float == 0].sum()) if n else 0
    n_float = int(counts[:, is_float == 1].sum()) if n else 0
    return counts, int_vals[:n_int].copy(), float_vals[:n_float].copy()


class MultiSlotFileReader:
    """Threaded native file reader: parses MultiSlot text files into
    padded numpy batches off the Python thread (data_feed.cc +
    blocking_queue.h parity).

    slots: list of (name, "int64"|"float", max_values). Iterate to get
    dicts {name: np.ndarray [batch, max_values]} plus "<name>:count".
    """

    def __init__(self, files, slots, batch_size, n_threads=2, queue_cap=8):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.slots = slots
        self.batch_size = batch_size
        n = len(slots)
        is_float = np.array([1 if t == "float" else 0 for _, t, _ in slots],
                            dtype=np.uint8)
        smax = np.array([m for _, _, m in slots], dtype=np.int64)
        arr = (ctypes.c_char_p * len(files))(
            *[f.encode() for f in files])
        self._h = lib.reader_create(
            arr, len(files), n, is_float.ctypes.data, smax.ctypes.data,
            batch_size, n_threads, queue_cap)
        self._iw = lib.reader_int_width(self._h)
        self._fw = lib.reader_float_width(self._h)

    def __iter__(self):
        return self

    def __next__(self):
        n_slots = len(self.slots)
        counts = np.empty((self.batch_size, n_slots), np.int64)
        ints = np.empty((self.batch_size, self._iw), np.int64)
        floats = np.empty((self.batch_size, self._fw), np.float32)
        n = self._lib.reader_next(self._h, counts.ctypes.data,
                                  ints.ctypes.data, floats.ctypes.data)
        if n < 0:
            raise ValueError("malformed MultiSlot input file")
        if n == 0:
            raise StopIteration
        out = {}
        iw = fw = 0
        for si, (name, typ, m) in enumerate(self.slots):
            if typ == "float":
                out[name] = floats[:n, fw:fw + m]
                fw += m
            else:
                out[name] = ints[:n, iw:iw + m]
                iw += m
            out[name + ":count"] = counts[:n, si]
        return out

    def close(self):
        if getattr(self, "_h", None):
            self._lib.reader_destroy(self._h)
            self._h = None

    def __del__(self):
        self.close()
