"""Tensor interop utilities.

Parity: /root/reference/paddle/fluid/framework/dlpack_tensor.cc (DLPack
import/export on the Tensor stack) — jax arrays speak DLPack natively,
so these are thin, documented entry points for zero-copy exchange with
torch/numpy/cupy, plus the convenience converters user code expects.
"""

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["to_dlpack", "from_dlpack", "to_numpy", "to_tensor"]


def to_dlpack(x):
    """Export a device array for DLPack exchange (dlpack_tensor.cc
    parity).  Modern protocol: returns the array itself, which carries
    __dlpack__/__dlpack_device__ — exactly what torch.from_dlpack,
    cupy.from_dlpack, np.from_dlpack, and our from_dlpack consume.
    (A raw capsule would NOT round-trip: jnp.from_dlpack rejects bare
    capsules in recent jax.)"""
    return jnp.asarray(x)


def from_dlpack(capsule_or_array):
    """Import any __dlpack__-bearing tensor (e.g. a torch.Tensor) —
    or a legacy raw capsule — as a jax array, zero-copy where the
    backend allows."""
    if hasattr(capsule_or_array, "__dlpack__"):
        return jnp.from_dlpack(capsule_or_array) if hasattr(
            jnp, "from_dlpack") else jax.dlpack.from_dlpack(
                capsule_or_array)
    # legacy PyCapsule path
    return jax.dlpack.from_dlpack(capsule_or_array)


def to_numpy(x):
    """Fetch to host as numpy (the reference's TensorToPyArray path)."""
    return np.asarray(x)


def to_tensor(x, dtype=None):
    """Host data -> device array (the reference's PyArrayToTensor)."""
    return jnp.asarray(x, dtype=dtype)


from . import plot  # noqa: E402,F401

__all__ = __all__ + ["plot"]
