"""Tensor interop utilities.

Parity: /root/reference/paddle/fluid/framework/dlpack_tensor.cc (DLPack
import/export on the Tensor stack) — jax arrays speak DLPack natively,
so these are thin, documented entry points for zero-copy exchange with
torch/numpy/cupy, plus the convenience converters user code expects.
"""

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["to_dlpack", "from_dlpack", "to_numpy", "to_tensor"]


def to_dlpack(x):
    """Export a device array for DLPack exchange (dlpack_tensor.cc
    parity).  Modern protocol: returns the array itself, which carries
    __dlpack__/__dlpack_device__ — exactly what torch.from_dlpack,
    cupy.from_dlpack, np.from_dlpack, and our from_dlpack consume.
    (A raw capsule would NOT round-trip: jnp.from_dlpack rejects bare
    capsules in recent jax.)"""
    return jnp.asarray(x)


def from_dlpack(tensor):
    """Import any __dlpack__-bearing tensor (e.g. a torch.Tensor) as a
    jax array, zero-copy where the backend allows.

    Raw PyCapsules (the pre-2021 protocol) are rejected with a clear
    error: the installed jax consumes only the modern
    __dlpack__/__dlpack_device__ protocol, so pass the tensor object
    itself (e.g. the torch.Tensor, NOT torch.utils.dlpack.to_dlpack(t))."""
    if hasattr(tensor, "__dlpack__"):
        return jnp.from_dlpack(tensor) if hasattr(
            jnp, "from_dlpack") else jax.dlpack.from_dlpack(tensor)
    if type(tensor).__name__ == "PyCapsule":
        raise TypeError(
            "from_dlpack no longer accepts raw DLPack capsules; pass the "
            "source tensor itself (it must implement __dlpack__), e.g. "
            "from_dlpack(torch_tensor) instead of "
            "from_dlpack(torch.utils.dlpack.to_dlpack(torch_tensor))")
    return jax.dlpack.from_dlpack(tensor)


def to_numpy(x):
    """Fetch to host as numpy (the reference's TensorToPyArray path)."""
    return np.asarray(x)


def to_tensor(x, dtype=None):
    """Host data -> device array (the reference's PyArrayToTensor)."""
    return jnp.asarray(x, dtype=dtype)


from . import plot  # noqa: E402,F401

__all__ = __all__ + ["plot"]
