"""`paddle.utils.plot` parity — the Ploter the book tutorials use.

Reference: python/paddle/utils/plot.py (PlotData, Ploter): collects
(step, value) series per title and renders them with matplotlib.
Display policy: headless sessions (no DISPLAY) fall back to the Agg
backend and `plot()` draws without showing; with a display attached,
`plot()` shows NON-blocking (the reference's IPython display-update
analogue — a blocking show would freeze the training loop calling
plot() each epoch).  Pass `path` to always write a file.
"""

import os

__all__ = ["Ploter"]


class PlotData:
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    def __init__(self, *args):
        self.__args__ = args
        self.__plot_data__ = {title: PlotData() for title in args}
        self.__disable_plot__ = False
        self._interactive = bool(os.environ.get("DISPLAY"))
        try:
            import matplotlib

            if not self._interactive:
                # headless: only force Agg when no display is attached,
                # never clobber an interactive backend the session set up
                matplotlib.use("Agg")
            import matplotlib.pyplot as plt

            self.plt = plt
        except Exception:
            self.plt = None
            self.__disable_plot__ = True

    def __plot_is_disabled__(self):
        return self.__disable_plot__

    def append(self, title, step, value):
        assert title in self.__plot_data__, (
            "title %s not found in %s" % (title, list(self.__plot_data__)))
        self.__plot_data__[title].append(step, value)

    def plot(self, path=None):
        if self.__plot_is_disabled__():
            return
        self.plt.clf()
        titles = []
        for title in self.__args__:
            data = self.__plot_data__[title]
            if len(data.step) > 0:
                self.plt.plot(data.step, data.value)
                titles.append(title)
        self.plt.legend(titles, loc="upper left")
        if path is not None:
            self.plt.savefig(path)
        elif self._interactive:
            # non-blocking: a tutorial loop calls plot() every epoch
            self.plt.show(block=False)
            self.plt.pause(0.001)
        else:
            # headless with no path: draw so the figure is inspectable
            # via plt.gcf() (tutorials sometimes call plot() bare); a
            # silent no-op here would discard the render entirely
            self.plt.draw()

    def reset(self):
        for data in self.__plot_data__.values():
            data.reset()
