"""LoD tensor user helpers — fluid.lod_tensor parity.

Parity: /root/reference/python/paddle/fluid/lod_tensor.py:24
(create_lod_tensor), :114 (create_random_int_lodtensor). The reference
packs ragged sequences into one flat tensor + offset table (LoD); this
framework's static-shape contract is padded [B, T, ...] data + a lengths
vector (SURVEY §7 hard part (c): bucketing + masking design). These
helpers accept the same ragged inputs the reference does (list of
lists / flat data + recursive_seq_lens) and produce the padded+lengths
pair every sequence op here consumes, with a LoDTensor facade exposing
the reference's accessors.
"""

import numpy as np

__all__ = ["LoDTensor", "create_lod_tensor",
           "create_random_int_lodtensor"]


class LoDTensor:
    """Padded batch + per-row lengths, with the reference's accessors
    (framework/lod_tensor.h:104 analogue at the Python surface).

    Multi-level (nested) LoD — lod_tensor.h:52 `LoD =
    vector<Vector<size_t>>` — keeps the ORIGINAL recursive_seq_lens and
    flattens the hierarchy to bottom-level sequences for the padded
    data: data is [num_bottom_seqs, T_max, ...] with `lengths` the
    bottom-level lengths, and the upper levels describe how those
    bottom sequences group (exactly the information the reference's
    upper offset vectors carry)."""

    def __init__(self, padded, lengths, recursive_seq_lens=None):
        self.data = np.asarray(padded)
        self.lengths = np.asarray(lengths, np.int64).reshape(-1)
        self._recursive = (
            [[int(v) for v in level] for level in recursive_seq_lens]
            if recursive_seq_lens is not None
            else [list(map(int, self.lengths))])

    @property
    def lod_level(self):
        return len(self._recursive)

    def recursive_sequence_lengths(self):
        return [list(level) for level in self._recursive]

    def lod(self):
        # offset form per level: [0, l0, l0+l1, ...]
        return [list(map(int, np.concatenate(
            [[0], np.cumsum(level)])))
            for level in self._recursive]

    def shape(self):
        return tuple(self.data.shape)

    def __array__(self, dtype=None):
        a = self.data
        return a.astype(dtype) if dtype is not None else a

    def rows(self):
        """Iterate the unpadded bottom-level sequences."""
        for i, n in enumerate(self.lengths):
            yield self.data[i, :int(n)]

    def top_level_groups(self):
        """Iterate lists of bottom-sequence indices per top-level
        sequence (the grouping the upper LoD levels encode)."""
        counts = self._recursive[0]
        if self.lod_level == 1:
            yield from ([i] for i in range(len(counts)))
            return
        # fold intermediate levels: how many bottom seqs per top seq
        per = list(self._recursive[0])
        for level in self._recursive[1:-1]:
            folded = []
            off = 0
            for c in per:
                folded.append(int(sum(level[off:off + c])))
                off += c
            per = folded
        off = 0
        for c in per:
            yield list(range(off, off + c))
            off += c


def create_lod_tensor(data, recursive_seq_lens=None, place=None):
    """Build a LoDTensor from a list of per-sequence arrays, or from
    flat data + recursive_seq_lens (the reference's two accepted forms,
    lod_tensor.py:24). `place` is accepted for API parity; device
    placement belongs to jit in this framework."""
    if recursive_seq_lens is None:
        seqs = [np.asarray(s) for s in data]
        recursive = None
    else:
        _validate_nested_lod(recursive_seq_lens)
        lens = list(recursive_seq_lens[-1])     # bottom level: data rows
        flat = np.asarray(data)
        if flat.ndim == 1:
            flat = flat.reshape(-1, 1)
        seqs = []
        off = 0
        for n in lens:
            seqs.append(flat[off:off + n])
            off += n
        if off != flat.shape[0]:
            raise ValueError(
                f"recursive_seq_lens sums to {off}, data has "
                f"{flat.shape[0]} rows")
        recursive = recursive_seq_lens
    if not seqs:
        raise ValueError("need at least one sequence")
    from .layers.sequence_ops import pad_sequences

    dtype = np.result_type(*[s.dtype for s in seqs])
    padded, lengths = pad_sequences(seqs, dtype=dtype)
    return LoDTensor(padded, lengths, recursive_seq_lens=recursive)


def _validate_nested_lod(recursive_seq_lens):
    """Each level's entry count must equal the sum of the level above
    (lod_tensor.h CheckLoD semantics on the lengths form)."""
    for upper, lower in zip(recursive_seq_lens, recursive_seq_lens[1:]):
        if sum(upper) != len(lower):
            raise ValueError(
                f"invalid nested LoD: level with sum {sum(upper)} must "
                f"partition the {len(lower)} entries below it")


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1):
    """lod_tensor.py:114 — random int sequences with the given ragged
    lengths; each element has shape `base_shape`."""
    lens = list(recursive_seq_lens[-1])
    total = int(sum(lens))
    flat = np.random.randint(low, high + 1,
                             size=(total,) + tuple(base_shape))
    return create_lod_tensor(flat, recursive_seq_lens, place)
