"""LoD tensor user helpers — fluid.lod_tensor parity.

Parity: /root/reference/python/paddle/fluid/lod_tensor.py:24
(create_lod_tensor), :114 (create_random_int_lodtensor). The reference
packs ragged sequences into one flat tensor + offset table (LoD); this
framework's static-shape contract is padded [B, T, ...] data + a lengths
vector (SURVEY §7 hard part (c): bucketing + masking design). These
helpers accept the same ragged inputs the reference does (list of
lists / flat data + recursive_seq_lens) and produce the padded+lengths
pair every sequence op here consumes, with a LoDTensor facade exposing
the reference's accessors.
"""

import numpy as np

__all__ = ["LoDTensor", "create_lod_tensor",
           "create_random_int_lodtensor"]


class LoDTensor:
    """Padded batch + per-row lengths, with the reference's accessors
    (framework/lod_tensor.h:104 analogue at the Python surface)."""

    def __init__(self, padded, lengths):
        self.data = np.asarray(padded)
        self.lengths = np.asarray(lengths, np.int64).reshape(-1)

    def recursive_sequence_lengths(self):
        return [list(map(int, self.lengths))]

    def lod(self):
        # offset form: [0, l0, l0+l1, ...]
        return [list(map(int, np.concatenate(
            [[0], np.cumsum(self.lengths)])))]

    def shape(self):
        return tuple(self.data.shape)

    def __array__(self, dtype=None):
        a = self.data
        return a.astype(dtype) if dtype is not None else a

    def rows(self):
        """Iterate the unpadded sequences."""
        for i, n in enumerate(self.lengths):
            yield self.data[i, :int(n)]


def create_lod_tensor(data, recursive_seq_lens=None, place=None):
    """Build a LoDTensor from a list of per-sequence arrays, or from
    flat data + recursive_seq_lens (the reference's two accepted forms,
    lod_tensor.py:24). `place` is accepted for API parity; device
    placement belongs to jit in this framework."""
    if recursive_seq_lens is None:
        seqs = [np.asarray(s) for s in data]
    else:
        if len(recursive_seq_lens) != 1:
            raise NotImplementedError(
                "multi-level LoD is not supported by the padded+lengths "
                "design; flatten the hierarchy to one level (got "
                f"{len(recursive_seq_lens)} levels)")
        lens = list(recursive_seq_lens[-1])
        flat = np.asarray(data)
        if flat.ndim == 1:
            flat = flat.reshape(-1, 1)
        seqs = []
        off = 0
        for n in lens:
            seqs.append(flat[off:off + n])
            off += n
        if off != flat.shape[0]:
            raise ValueError(
                f"recursive_seq_lens sums to {off}, data has "
                f"{flat.shape[0]} rows")
    if not seqs:
        raise ValueError("need at least one sequence")
    from .layers.sequence_ops import pad_sequences

    dtype = np.result_type(*[s.dtype for s in seqs])
    padded, lengths = pad_sequences(seqs, dtype=dtype)
    return LoDTensor(padded, lengths)


def create_random_int_lodtensor(recursive_seq_lens, base_shape, place=None,
                                low=0, high=1):
    """lod_tensor.py:114 — random int sequences with the given ragged
    lengths; each element has shape `base_shape`."""
    lens = list(recursive_seq_lens[-1])
    total = int(sum(lens))
    flat = np.random.randint(low, high + 1,
                             size=(total,) + tuple(base_shape))
    return create_lod_tensor(flat, recursive_seq_lens, place)
