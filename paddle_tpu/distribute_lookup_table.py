"""`fluid.distribute_lookup_table` import-path compatibility.

Parity: python/paddle/fluid/distribute_lookup_table.py: helpers the
transpiler era used to locate the (single) distributed embedding
table in a program.  Works over the JSON-IR Program: a distributed
table is a lookup_table/embedding op with is_distributed=True.
"""

LOOKUP_TABLE_TYPE = "lookup_table"
_LOOKUP_OPS = ("lookup_table", "lookup_table_v2", "embedding")

__all__ = [
    "find_distributed_lookup_table",
    "find_distributed_lookup_table_inputs",
    "find_distributed_lookup_table_outputs",
]


def _distributed_lookup_ops(program, table_name=None):
    for op in program.global_block().ops:
        if op.type in _LOOKUP_OPS and op.attrs.get("is_distributed"):
            w = op.inputs.get("W")
            name = w[0] if isinstance(w, (list, tuple)) else w
            if table_name is None or name == table_name:
                yield op, name


def find_distributed_lookup_table(program):
    """Name of the distributed table, or None.  Reference constraint
    kept: at most ONE distributed table per program."""
    names = {name for _, name in _distributed_lookup_ops(program)}
    if len(names) > 1:
        raise ValueError(
            "only one distributed lookup table is supported, found %s"
            % sorted(names))
    return names.pop() if names else None


def find_distributed_lookup_table_inputs(program, table_name):
    inputs = []
    for op, _ in _distributed_lookup_ops(program, table_name):
        ids = op.inputs.get("Ids")
        inputs.extend(ids if isinstance(ids, (list, tuple)) else [ids])
    return inputs


def find_distributed_lookup_table_outputs(program, table_name):
    outputs = []
    for op, _ in _distributed_lookup_ops(program, table_name):
        out = op.outputs.get("Out")
        outputs.extend(out if isinstance(out, (list, tuple)) else [out])
    return outputs
