"""Gradient clipping.

Parity: /root/reference/python/paddle/fluid/clip.py — GradientClipByValue,
GradientClipByNorm, GradientClipByGlobalNorm, set_gradient_clip.
"""


class GradientClipBase:
    def apply(self, params_grads):
        raise NotImplementedError


class GradientClipByValue(GradientClipBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def apply(self, params_grads):
        from .layers import tensor as T
        from .layers import nn as N

        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, N.clip(g, self.min, self.max)))
        return out


class GradientClipByNorm(GradientClipBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply(self, params_grads):
        from .layers import nn as N

        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, N.clip_by_norm(g, self.clip_norm)))
        return out


class GradientClipByGlobalNorm(GradientClipBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def apply(self, params_grads):
        from .layers import tensor as T

        sq_norms = []
        for p, g in params_grads:
            if g is None:
                continue
            helper_out = T._single_out("squared_l2_norm", {"X": g})
            sq_norms.append(helper_out)
        if not sq_norms:
            return params_grads
        total = T.sums(sq_norms) if len(sq_norms) > 1 else sq_norms[0]
        global_norm = T.sqrt(total)
        max_norm = T.fill_constant([1], "float32", self.clip_norm)
        denom = T.elementwise_max(global_norm, max_norm)
        scale_var = T.elementwise_div(max_norm, denom)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, T.elementwise_mul(g, scale_var)))
        return out


_gradient_clip = None


def set_gradient_clip(clip):
    global _gradient_clip
    _gradient_clip = clip


def get_gradient_clip():
    return _gradient_clip


# reference-era aliases
ErrorClipByValue = GradientClipByValue
