"""`fluid.layer_helper` import-path compatibility.

Parity: python/paddle/fluid/layer_helper.py — implementation in
framework/layer_helper.py.  Custom-layer authors import LayerHelper
from this path in 1.x scripts.
"""

from .framework.layer_helper import LayerHelper  # noqa: F401

__all__ = ["LayerHelper"]
