"""`fluid.executor` import-path compatibility.

Parity: python/paddle/fluid/executor.py — the implementation lives in
framework/executor.py; this module preserves the reference import path
(`from paddle.fluid.executor import Executor, global_scope`).
"""

from .framework.executor import (Executor, Scope, global_scope,  # noqa: F401
                                 scope_guard)

__all__ = ["Executor", "Scope", "global_scope", "scope_guard"]
