"""Functional optimizers — pure (params, grads, state) -> (params, state).

The eager/sharded counterpart to the static-graph optimizer zoo in
optimizer/__init__.py.  Both call the SAME update kernels
(paddle_tpu/ops/optimizer_ops.py, the rebuild of the reference's
operators/optimizers/*), so static and functional training produce
bit-identical updates.  The pure-transform shape is what lets a train step
be jitted/pjit-sharded whole: optimizer state is an explicit pytree that
rides through jax transformations (the reference instead mutates
accumulator Variables in the scope — SURVEY.md §2.2 Optimizers).

Usage:
    opt = functional.Adam(1e-3)
    state = opt.init(params)                       # params: dict name->array
    params, state = opt.update(params, grads, state)
"""

import jax.numpy as jnp

from ..ops import optimizer_ops as K

__all__ = [
    "FunctionalOptimizer", "SGD", "Momentum", "LarsMomentum", "Adam",
    "AdamW", "Adagrad", "DecayedAdagrad", "Adadelta", "RMSProp", "Adamax",
    "Ftrl", "Lamb",
]


class FunctionalOptimizer:
    """Wraps one optimizer_ops kernel into an init/update transform.

    Subclasses define:
      op: the kernel function
      slots: dict input-name -> fill value, per-param accumulators
      scalar_slots: dict input-name -> init value, per-param scalar
        accumulators (beta powers)
      out_map: kernel output name -> input name rebind
    """

    op = None
    slots = {}
    scalar_slots = {}
    out_map = {}  # kernel output name -> state slot, when != name minus "Out"

    def __init__(self, learning_rate=0.001, grad_clip=None,
                 weight_decay=None, **attrs):
        self._lr = learning_rate
        self._attrs = attrs
        self._grad_clip = grad_clip
        self._weight_decay = weight_decay

    def init(self, params):
        state = {}
        for name, p in params.items():
            # accumulators always fp32 (bf16 moments destroy Adam
            # stability); full_like keeps the param's sharding so moments
            # of tp/dp-sharded params stay sharded
            s = {k: jnp.full_like(p, v, dtype=jnp.float32)
                 for k, v in self.slots.items()}
            s.update({k: jnp.asarray(v, dtype=jnp.float32)
                      for k, v in self.scalar_slots.items()})
            state[name] = s
        state["__step__"] = jnp.zeros((), jnp.int32)
        return state

    def learning_rate(self, step):
        lr = self._lr
        if callable(lr):
            lr = lr(step)
        return jnp.asarray(lr, dtype=jnp.float32).reshape(1)

    def update(self, params, grads, state):
        if self._grad_clip is not None:
            grads = self._grad_clip(grads)
        step = state["__step__"]
        lr = self.learning_rate(step)
        new_params, new_state = {}, {"__step__": step + 1}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                new_state[name] = state[name]
                continue
            # update math in fp32 regardless of param dtype (bf16 training);
            # the new param is cast back to the stored dtype
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self._weight_decay:
                g = g + self._weight_decay * p32
            ins = {"Param": p32, "Grad": g, "LearningRate": lr}
            ins.update(state[name])
            out = type(self).op(ins, dict(self._attrs))
            new_params[name] = out.pop("ParamOut").astype(p.dtype)
            new_state[name] = {
                self.out_map.get(k, k[: -len("Out")]): v
                for k, v in out.items() if k.endswith("Out")
            }
        return new_params, new_state


class SGD(FunctionalOptimizer):
    op = staticmethod(K.sgd)


class Momentum(FunctionalOptimizer):
    op = staticmethod(K.momentum)
    slots = {"Velocity": 0.0}

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 use_nesterov=False, **kw):
        super().__init__(learning_rate, mu=momentum,
                         use_nesterov=use_nesterov, **kw)


class LarsMomentum(FunctionalOptimizer):
    op = staticmethod(K.lars_momentum)
    slots = {"Velocity": 0.0}

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kw):
        super().__init__(learning_rate, mu=momentum, lars_coeff=lars_coeff,
                         lars_weight_decay=lars_weight_decay, **kw)


class Adam(FunctionalOptimizer):
    op = staticmethod(K.adam)
    slots = {"Moment1": 0.0, "Moment2": 0.0}

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, **kw)
        self.scalar_slots = {"Beta1Pow": beta1, "Beta2Pow": beta2}


class AdamW(Adam):
    op = staticmethod(K.adamw)

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, coeff=0.01, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, **kw)
        self._attrs["coeff"] = coeff


class Adagrad(FunctionalOptimizer):
    op = staticmethod(K.adagrad)
    slots = {"Moment": 0.0}

    def __init__(self, learning_rate=0.001, epsilon=1e-6, **kw):
        super().__init__(learning_rate, epsilon=epsilon, **kw)


class DecayedAdagrad(FunctionalOptimizer):
    op = staticmethod(K.decayed_adagrad)
    slots = {"Moment": 0.0}

    def __init__(self, learning_rate=0.001, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, decay=decay, epsilon=epsilon, **kw)


class Adadelta(FunctionalOptimizer):
    op = staticmethod(K.adadelta)
    slots = {"AvgSquaredGrad": 0.0, "AvgSquaredUpdate": 0.0}

    def __init__(self, learning_rate=1.0, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, rho=rho, epsilon=epsilon, **kw)


class RMSProp(FunctionalOptimizer):
    op = staticmethod(K.rmsprop)

    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, **kw):
        super().__init__(learning_rate, decay=rho, epsilon=epsilon,
                         momentum=momentum, centered=centered, **kw)
        self.slots = {"MeanSquare": 0.0, "Moment": 0.0}
        if centered:
            self.slots["MeanGrad"] = 0.0


class Adamax(FunctionalOptimizer):
    op = staticmethod(K.adamax)
    slots = {"Moment": 0.0, "InfNorm": 0.0}

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, **kw)
        self.scalar_slots = {"Beta1Pow": beta1}


class Ftrl(FunctionalOptimizer):
    op = staticmethod(K.ftrl)
    slots = {"SquaredAccumulator": 0.0, "LinearAccumulator": 0.0}
    out_map = {"SquaredAccumOut": "SquaredAccumulator",
               "LinearAccumOut": "LinearAccumulator"}

    def __init__(self, learning_rate=0.05, l1=0.0, l2=0.0, lr_power=-0.5,
                 **kw):
        super().__init__(learning_rate, l1=l1, l2=l2, lr_power=lr_power,
                         **kw)


class Lamb(FunctionalOptimizer):
    op = staticmethod(K.lamb)
    slots = {"Moment1": 0.0, "Moment2": 0.0}

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, **kw):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, **kw)
        self._attrs["weight_decay"] = lamb_weight_decay
        self.scalar_slots = {"Beta1Pow": beta1, "Beta2Pow": beta2}


def global_norm_clip(clip_norm):
    """Gradient clip-by-global-norm as a grads->grads transform (parity:
    fluid.clip.GradientClipByGlobalNorm)."""

    def clip(grads):
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                 for g in grads.values() if g is not None)
        gnorm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gnorm, 1e-12))
        return {k: (None if g is None else g * scale.astype(g.dtype))
                for k, g in grads.items()}

    return clip
