"""Optimizer zoo (static graph).

Parity: /root/reference/python/paddle/fluid/optimizer.py — Optimizer base
(:54, backward :607, apply_gradients :671, minimize :779) and the zoo: SGD
:828, Momentum :918, LarsMomentum :1441, Adagrad :1546, Adam :1653, Adamax
:1899, Dpsgd :2062, DecayedAdagrad :2157, Adadelta :2258, RMSProp :2369,
Ftrl :2548, Lamb :2698, plus RecomputeOptimizer :3713, ExponentialMovingAverage
:3165, ModelAverage :2861, LookaheadOptimizer :4009.

Each optimizer emits its update op(s) into the program after the backward
marker; update kernels live in paddle_tpu/ops/optimizer_ops.py.  The LR is
a graph variable (schedulable via layers.learning_rate_scheduler) exactly
like the reference.
"""

from ..framework import unique_name
from ..framework.backward import append_backward
from ..framework.initializer import ConstantInitializer
from ..framework.program import Variable, default_main_program, default_startup_program
from ..layers import tensor as T
from ..regularizer import append_regularization_ops
from .. import clip as clip_mod

__all__ = [
    "Optimizer", "SGD", "SGDOptimizer", "Momentum", "MomentumOptimizer",
    "DGCMomentumOptimizer",
    "LarsMomentum", "LarsMomentumOptimizer", "Adagrad", "AdagradOptimizer",
    "Adam", "AdamOptimizer", "AdamW", "Adamax", "AdamaxOptimizer", "Dpsgd",
    "DpsgdOptimizer", "DecayedAdagrad", "DecayedAdagradOptimizer",
    "Adadelta", "AdadeltaOptimizer", "RMSProp", "RMSPropOptimizer", "Ftrl",
    "FtrlOptimizer", "Lamb", "LambOptimizer", "RecomputeOptimizer",
    "ExponentialMovingAverage", "LookaheadOptimizer", "ModelAverage",
    "PipelineOptimizer",
]


class Optimizer:
    def __init__(self, learning_rate, regularization=None, grad_clip=None,
                 name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name or unique_name.generate(type(self).__name__.lower())
        self._lr_var = None
        self._accumulators = {}

    # -- LR -------------------------------------------------------------

    def _create_global_learning_rate(self):
        if self._lr_var is not None:
            return self._lr_var
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
        else:
            self._lr_var = T.create_global_var(
                [1], float(self._learning_rate), "float32", persistable=True,
                name=unique_name.generate(self._name + "_lr"))
        return self._lr_var

    def _param_lr(self, param):
        base = self._create_global_learning_rate()
        mult = getattr(param, "optimize_attr", {}).get("learning_rate", 1.0)
        if mult == 1.0:
            return base
        return T.scale(base, scale=mult)

    # -- accumulators ----------------------------------------------------

    def _add_accumulator(self, name, param, fill_value=0.0, shape=None,
                         dtype=None):
        key = (name, param.name)
        if key in self._accumulators:
            return self._accumulators[key]
        vname = f"{param.name}_{self._name}_{name}"
        shape = shape if shape is not None else list(param.shape)
        dtype = dtype or param.dtype
        block = default_main_program().global_block()
        var = block.create_var(name=vname, shape=shape, dtype=dtype,
                               persistable=True, stop_gradient=True)
        sb = default_startup_program().global_block()
        if vname not in sb.vars:
            sv = sb.create_var(name=vname, shape=shape, dtype=dtype,
                               persistable=True, stop_gradient=True)
            ConstantInitializer(fill_value)(sv, sb)
        self._accumulators[key] = var
        return var

    # -- main API --------------------------------------------------------

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, checkpoints=None):
        return append_backward(loss, parameter_list, no_grad_set,
                               checkpoints=checkpoints)

    def apply_gradients(self, params_grads):
        grad_clip = self._grad_clip or clip_mod.get_gradient_clip()
        if grad_clip is not None:
            params_grads = grad_clip.apply(params_grads)
        params_grads = append_regularization_ops(params_grads,
                                                 self.regularization)
        self._create_global_learning_rate()
        ops = []
        for p, g in params_grads:
            if g is None:
                continue
            ops.append(self._append_optimize_op(
                default_main_program().global_block(), (p, g)))
        return ops

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        opt_ops = self.apply_gradients(params_grads)
        return opt_ops, params_grads

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError


class SGD(Optimizer):
    """optimizer.py:828 / operators/optimizers/sgd_op.cc"""

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "sgd",
            inputs={"Param": p, "Grad": g,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p})


class Momentum(Optimizer):
    """optimizer.py:918"""

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._add_accumulator("velocity", p)
        return block.append_op(
            "momentum",
            inputs={"Param": p, "Grad": g, "Velocity": v,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p, "VelocityOut": v},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov})


class DGCMomentumOptimizer(Optimizer):
    """optimizer.py:1041 — DGC momentum with the reference constructor
    (momentum, rampup_begin_step, rampup_step, sparsity warmup list).

    Emits per-param: [optional dgc_clip_by_norm] -> dgc (U/V momentum
    correction + error feedback + top-k sparsify, ops/misc_ops.py) ->
    dgc_momentum (momentum before the rampup boundary, direct sparse
    update after), plus one shared step counter incremented per
    apply_gradients.  Under the DP CompiledProgram path the masked dense
    GradOut is the allreduce operand — the SPMD form of the reference's
    sparse NCCL allreduce (operators/dgc_op.h encode path)."""

    _u_velocity_acc_str = "_dgc_u_"
    _v_velocity_acc_str = "_dgc_v_"

    def __init__(self, learning_rate, momentum, rampup_begin_step,
                 rampup_step=1, sparsity=None, parameter_list=None,
                 use_nesterov=False, local_grad_clip_norm=None,
                 num_trainers=None, regularization=None, grad_clip=None,
                 name=None):
        assert rampup_begin_step >= 0, "rampup_begin_step must >= 0"
        super().__init__(learning_rate, regularization=regularization,
                         grad_clip=grad_clip, name=name)
        self.type = "dgc_momentum"
        self._momentum = momentum
        self._use_nesterov = bool(use_nesterov)
        self._rampup_begin_step = rampup_begin_step
        self._rampup_step = rampup_step
        self._sparsity = list(sparsity) if sparsity is not None else [0.999]
        self._parameter_list = parameter_list
        if local_grad_clip_norm is not None:
            # reference optimizer.py:1153-1156: clip norm is scaled by
            # num_trainers**-0.5 and num_trainers must be a positive int
            assert isinstance(num_trainers, int) and num_trainers > 0, \
                "local_grad_clip_norm needs a positive int num_trainers"
            self._clip_norm = local_grad_clip_norm * (num_trainers ** -0.5)
        else:
            self._clip_norm = None
        self._num_trainers = num_trainers
        self._global_step_var = None

    def _get_global_step_var(self):
        if self._global_step_var is None:
            self._global_step_var = T.create_global_var(
                [1], 0.0, "float32", persistable=True,
                name=unique_name.generate(self._name + "_global_step"))
        return self._global_step_var

    def apply_gradients(self, params_grads):
        ops = super().apply_gradients(params_grads)
        T.increment(self._get_global_step_var(), 1.0, in_place=True)
        return ops

    def _is_use_dgc(self, param):
        """optimizer.py:1169 — small (<16384 elements) or non-fp32
        params skip sparsification and stay on dense momentum."""
        numel = 1
        for s in param.shape:
            numel *= int(s)
        return numel >= 16384 and str(param.dtype) in ("float32",
                                                       "FP32")

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        vel = self._add_accumulator("velocity", p)
        if not self._is_use_dgc(p):
            return block.append_op(
                "momentum",
                inputs={"Param": p, "Grad": g, "Velocity": vel,
                        "LearningRate": self._param_lr(p)},
                outputs={"ParamOut": p, "VelocityOut": vel},
                attrs={"mu": self._momentum,
                       "use_nesterov": self._use_nesterov})
        u = self._add_accumulator(self._u_velocity_acc_str, p)
        v = self._add_accumulator(self._v_velocity_acc_str, p)
        step = self._get_global_step_var()
        if self._clip_norm is not None:
            clipped = block.create_var(
                name=unique_name.generate(p.name + "_dgc_clip"),
                shape=list(p.shape), dtype=p.dtype)
            block.append_op(
                "dgc_clip_by_norm",
                inputs={"X": g, "current_step": step},
                outputs={"Out": clipped},
                attrs={"max_norm": float(self._clip_norm),
                       "rampup_begin_step": float(self._rampup_begin_step)})
            g = clipped
        sparse_g = block.create_var(
            name=unique_name.generate(p.name + "_dgc_grad"),
            shape=list(p.shape), dtype=p.dtype)
        block.append_op(
            "dgc",
            inputs={"U": u, "V": v, "Grad": g, "current_step": step},
            outputs={"UOut": u, "VOut": v, "GradOut": sparse_g},
            attrs={"m": self._momentum,
                   "rampup_begin_step": float(self._rampup_begin_step),
                   "rampup_step": float(self._rampup_step),
                   "sparsity": self._sparsity})
        return block.append_op(
            "dgc_momentum",
            inputs={"Param": p, "Grad": sparse_g, "Velocity": vel,
                    "LearningRate": self._param_lr(p),
                    "current_step": step},
            outputs={"ParamOut": p, "VelocityOut": vel},
            attrs={"mu": self._momentum,
                   "use_nesterov": self._use_nesterov,
                   "rampup_begin_step": float(self._rampup_begin_step)})


class LarsMomentum(Optimizer):
    """optimizer.py:1441 — LARS for large-batch training."""

    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._add_accumulator("velocity", p)
        return block.append_op(
            "lars_momentum",
            inputs={"Param": p, "Grad": g, "Velocity": v,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p, "VelocityOut": v},
            attrs={"mu": self._momentum, "lars_coeff": self._lars_coeff,
                   "lars_weight_decay": self._lars_weight_decay})


class Adagrad(Optimizer):
    """optimizer.py:1546"""

    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._add_accumulator("moment", p, fill_value=self._init_acc)
        return block.append_op(
            "adagrad",
            inputs={"Param": p, "Grad": g, "Moment": m,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p, "MomentOut": m},
            attrs={"epsilon": self._epsilon})


class Adam(Optimizer):
    """optimizer.py:1653"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    _op_type = "adam"
    _extra_attrs = {}

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._add_accumulator("moment1", p)
        m2 = self._add_accumulator("moment2", p)
        b1p = self._add_accumulator("beta1_pow", p, fill_value=self._beta1,
                                    shape=[1])
        b2p = self._add_accumulator("beta2_pow", p, fill_value=self._beta2,
                                    shape=[1])
        attrs = {"beta1": self._beta1, "beta2": self._beta2,
                 "epsilon": self._epsilon}
        attrs.update(self._extra_attrs)
        return block.append_op(
            self._op_type,
            inputs={"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
                    "Beta1Pow": b1p, "Beta2Pow": b2p,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p, "Moment1Out": m1, "Moment2Out": m2,
                     "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            attrs=attrs)


class AdamW(Adam):
    """Decoupled weight decay variant (modern addition; reference gets the
    same effect via L2 regularization)."""

    _op_type = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self._extra_attrs = {"coeff": weight_decay}


class Adamax(Optimizer):
    """optimizer.py:1899"""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._add_accumulator("moment", p)
        inf = self._add_accumulator("inf_norm", p)
        b1p = self._add_accumulator("beta1_pow", p, fill_value=self._beta1,
                                    shape=[1])
        return block.append_op(
            "adamax",
            inputs={"Param": p, "Grad": g, "Moment": m, "InfNorm": inf,
                    "Beta1Pow": b1p, "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p, "MomentOut": m, "InfNormOut": inf,
                     "Beta1PowOut": b1p},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class Dpsgd(Optimizer):
    """optimizer.py:2062 — differentially-private SGD."""

    def __init__(self, learning_rate=0.001, clip=10.0, batch_size=16.0,
                 sigma=1.0, **kw):
        super().__init__(learning_rate, **kw)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        return block.append_op(
            "dpsgd",
            inputs={"Param": p, "Grad": g, "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p},
            attrs={"clip": self._clip, "batch_size": self._batch_size,
                   "sigma": self._sigma})


class DecayedAdagrad(Optimizer):
    """optimizer.py:2157"""

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._add_accumulator("moment", p)
        return block.append_op(
            "decayed_adagrad",
            inputs={"Param": p, "Grad": g, "Moment": m,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p, "MomentOut": m},
            attrs={"decay": self._decay, "epsilon": self._epsilon})


class Adadelta(Optimizer):
    """optimizer.py:2258"""

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        g2 = self._add_accumulator("avg_squared_grad", p)
        u2 = self._add_accumulator("avg_squared_update", p)
        return block.append_op(
            "adadelta",
            inputs={"Param": p, "Grad": g, "AvgSquaredGrad": g2,
                    "AvgSquaredUpdate": u2,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p, "AvgSquaredGradOut": g2,
                     "AvgSquaredUpdateOut": u2},
            attrs={"rho": self._rho, "epsilon": self._epsilon})


class RMSProp(Optimizer):
    """optimizer.py:2369"""

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        ms = self._add_accumulator("mean_square", p)
        mom = self._add_accumulator("momentum", p)
        inputs = {"Param": p, "Grad": g, "MeanSquare": ms, "Moment": mom,
                  "LearningRate": self._param_lr(p)}
        outputs = {"ParamOut": p, "MeanSquareOut": ms, "MomentOut": mom}
        if self._centered:
            mg = self._add_accumulator("mean_grad", p)
            inputs["MeanGrad"] = mg
            outputs["MeanGradOut"] = mg
        return block.append_op(
            "rmsprop", inputs=inputs, outputs=outputs,
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered})


class Ftrl(Optimizer):
    """optimizer.py:2548"""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._add_accumulator("squared", p)
        lin = self._add_accumulator("linear", p)
        return block.append_op(
            "ftrl",
            inputs={"Param": p, "Grad": g, "SquaredAccumulator": sq,
                    "LinearAccumulator": lin,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p, "SquaredAccumOut": sq,
                     "LinearAccumOut": lin},
            attrs={"l1": self._l1, "l2": self._l2,
                   "lr_power": self._lr_power})


class Lamb(Optimizer):
    """optimizer.py:2698 — LAMB large-batch optimizer."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m1 = self._add_accumulator("moment1", p)
        m2 = self._add_accumulator("moment2", p)
        b1p = self._add_accumulator("beta1_pow", p, fill_value=self._beta1,
                                    shape=[1])
        b2p = self._add_accumulator("beta2_pow", p, fill_value=self._beta2,
                                    shape=[1])
        return block.append_op(
            "lamb",
            inputs={"Param": p, "Grad": g, "Moment1": m1, "Moment2": m2,
                    "Beta1Pow": b1p, "Beta2Pow": b2p,
                    "LearningRate": self._param_lr(p)},
            outputs={"ParamOut": p, "Moment1Out": m1, "Moment2Out": m2,
                     "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "weight_decay": self._wd})


class RecomputeOptimizer(Optimizer):
    """optimizer.py:3713 — activation checkpointing wrapper.

    The reference rebuilds forward subgraphs between user checkpoints in the
    backward pass (backward.py:623); here the checkpoint names flow into the
    BackwardSection and the executor applies jax.checkpoint — same memory/
    compute trade, compiler-native mechanism."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = None

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = checkpoints

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, checkpoints=None):
        return self._optimizer.backward(
            loss, startup_program, parameter_list, no_grad_set,
            checkpoints=checkpoints or self._checkpoints)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        opt_ops = self._optimizer.apply_gradients(params_grads)
        return opt_ops, params_grads


class ExponentialMovingAverage:
    """optimizer.py:3165 — EMA of parameters maintained by extra ops."""

    def __init__(self, decay=0.999, name=None):
        self._decay = decay
        self._name = name or unique_name.generate("ema")
        self._ema_vars = {}
        self._params = []
        self._counter_name = self._name + "_step_counter"

    def update(self):
        program = default_main_program()
        counter = T.create_global_var([1], 0.0, "float32", persistable=True,
                                      name=self._counter_name)
        T.increment(counter, 1.0, in_place=True)
        for p in program.all_parameters():
            if not getattr(p, "trainable", True):
                continue
            vname = f"{p.name}_{self._name}"
            block = program.global_block()
            if vname not in block.vars:
                ema = block.create_var(name=vname, shape=p.shape,
                                       dtype=p.dtype, persistable=True,
                                       stop_gradient=True)
                sb = default_startup_program().global_block()
                sv = sb.create_var(name=vname, shape=p.shape, dtype=p.dtype,
                                   persistable=True, stop_gradient=True)
                ConstantInitializer(0.0)(sv, sb)
                self._ema_vars[p.name] = ema
                self._params.append(p)
            ema = block.vars[vname]
            new_ema = T.elementwise_add(
                T.scale(ema, scale=self._decay),
                T.scale(p, scale=1.0 - self._decay))
            block.append_op("assign", inputs={"X": new_ema},
                            outputs={"Out": ema})

    def apply(self, executor, need_restore=True):
        """Swap EMA values into params (for eval)."""
        import contextlib

        import numpy as np

        from ..framework.executor import global_scope

        scope = global_scope()

        # bias correction: ema_t / (1 - decay^t), parity with
        # optimizer.py:3293-3302
        t = scope.find_var(self._counter_name)
        t = float(np.asarray(t).reshape(())) if t is not None else 0.0
        correction = 1.0 - self._decay ** t if t > 0 else 1.0

        @contextlib.contextmanager
        def guard():
            backup = {}
            for p in self._params:
                vname = f"{p.name}_{self._name}"
                backup[p.name] = scope.find_var(p.name)
                ema_val = scope.find_var(vname)
                if ema_val is not None:
                    scope.set_var(p.name, ema_val / correction)
            try:
                yield
            finally:
                if need_restore:
                    for n, v in backup.items():
                        scope.set_var(n, v)

        return guard()


class ModelAverage:
    """optimizer.py:2861 — windowed parameter averaging for eval.

    Appends an average_accumulates op per trainable param (the reference's
    _append_average_accumulate_op, optimizer.py:3003): sum_1/sum_2/sum_3
    window accumulators cascade as windows roll over
    (operators/average_accumulates_op.cc). apply() swaps the averaged
    params in; restore() puts the trained ones back.
    """

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, name=None):
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._name = name or unique_name.generate("model_average")
        self._params = []
        program = default_main_program()
        block = program.global_block()
        sb = default_startup_program().global_block()
        for p in program.all_parameters():
            if not getattr(p, "trainable", True):
                continue
            self._params.append(p)
            slots = {}
            for s in ("sum_1", "sum_2", "sum_3"):
                vname = f"{p.name}_{self._name}_{s}"
                block.create_var(name=vname, shape=p.shape, dtype=p.dtype,
                                 persistable=True, stop_gradient=True)
                sv = sb.create_var(name=vname, shape=p.shape, dtype=p.dtype,
                                   persistable=True, stop_gradient=True)
                ConstantInitializer(0.0)(sv, sb)
                slots[s] = vname
            for s, dt in (("num_accumulates", "int32"),
                          ("old_num_accumulates", "int32"),
                          ("num_updates", "int32")):
                vname = f"{p.name}_{self._name}_{s}"
                block.create_var(name=vname, shape=[1], dtype=dt,
                                 persistable=True, stop_gradient=True)
                sv = sb.create_var(name=vname, shape=[1], dtype=dt,
                                   persistable=True, stop_gradient=True)
                ConstantInitializer(0.0)(sv, sb)
                slots[s] = vname
            block.append_op(
                "average_accumulates",
                inputs={"param": p.name,
                        "in_sum_1": slots["sum_1"],
                        "in_sum_2": slots["sum_2"],
                        "in_sum_3": slots["sum_3"],
                        "in_num_accumulates": slots["num_accumulates"],
                        "in_old_num_accumulates":
                            slots["old_num_accumulates"],
                        "in_num_updates": slots["num_updates"]},
                outputs={"out_sum_1": slots["sum_1"],
                         "out_sum_2": slots["sum_2"],
                         "out_sum_3": slots["sum_3"],
                         "out_num_accumulates": slots["num_accumulates"],
                         "out_old_num_accumulates":
                             slots["old_num_accumulates"],
                         "out_num_updates": slots["num_updates"]},
                attrs={"average_window": float(average_window_rate),
                       "min_average_window": int(min_average_window),
                       "max_average_window": int(max_average_window)})

    def _averaged(self, scope, p):
        import numpy as np

        pre = f"{p.name}_{self._name}_"
        s1 = np.asarray(scope.find_var(pre + "sum_1"))
        s2 = np.asarray(scope.find_var(pre + "sum_2"))
        s3 = np.asarray(scope.find_var(pre + "sum_3"))
        na = float(np.asarray(scope.find_var(pre + "num_accumulates")))
        ona = float(np.asarray(
            scope.find_var(pre + "old_num_accumulates")))
        denom = max(na + ona, 1.0)
        return (s1 + s2 + s3) / denom

    def apply(self, executor=None, need_restore=True):
        import contextlib

        from ..framework.executor import global_scope

        scope = global_scope()

        @contextlib.contextmanager
        def guard():
            backup = {}
            for p in self._params:
                backup[p.name] = scope.find_var(p.name)
                scope.set_var(p.name, self._averaged(scope, p))
            try:
                yield
            finally:
                if need_restore:
                    for n, v in backup.items():
                        scope.set_var(n, v)

        return guard()

    def restore(self, executor=None):
        """No-op when apply() was used as a context manager (parity)."""


class LookaheadOptimizer:
    """optimizer.py:4009 — k-step lookahead with slow/fast weights."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def minimize(self, loss, startup_program=None):
        opt_ops, params_grads = self.inner_optimizer.minimize(
            loss, startup_program)
        program = default_main_program()
        block = program.global_block()
        step = T.create_global_var([1], 0.0, "float32", persistable=True,
                                   name=unique_name.generate("lookahead_step"))
        T.increment(step, 1.0, in_place=True)
        # every k steps: slow = slow + alpha*(fast - slow); fast = slow
        k_var = T.fill_constant([1], "float32", float(self.k))
        rem = T.elementwise_mod(step, k_var)
        is_sync = T.cast(T.equal(rem, T.zeros([1], "float32")), "float32")
        for p, g in params_grads:
            if g is None:
                continue
            vname = f"{p.name}_lookahead_slow"
            slow = block.create_var(name=vname, shape=p.shape, dtype=p.dtype,
                                    persistable=True, stop_gradient=True)
            sb = default_startup_program().global_block()
            if vname not in sb.vars:
                sv = sb.create_var(name=vname, shape=p.shape, dtype=p.dtype,
                                   persistable=True, stop_gradient=True)
                # slow weights start as a COPY of the fast params
                # (optimizer.py:4112 assigns fast->slow in startup)
                sb.append_op("assign", inputs={"X": p.name},
                             outputs={"Out": vname})
            new_slow = T.elementwise_add(
                slow, T.scale(T.elementwise_sub(p, slow), scale=self.alpha))
            synced_slow = T.elementwise_add(
                T.elementwise_mul(new_slow, is_sync),
                T.elementwise_mul(slow, T.scale(is_sync, scale=-1.0, bias=1.0)))
            synced_fast = T.elementwise_add(
                T.elementwise_mul(synced_slow, is_sync),
                T.elementwise_mul(p, T.scale(is_sync, scale=-1.0, bias=1.0)))
            block.append_op("assign", inputs={"X": synced_slow},
                            outputs={"Out": slow})
            block.append_op("assign", inputs={"X": synced_fast},
                            outputs={"Out": p})
        return opt_ops, params_grads


class PipelineOptimizer:
    """Static-graph pipeline wrapper (optimizer.py:3413 parity).

    The reference's v1 pipeline is ASYNC: microbatches flow through
    program sections bound to places, and the optimizer updates per
    microbatch (SectionWorker scope-queues, device_worker.h:325). On TPU
    the section scheduling belongs to XLA (one compiled program) or the
    eager gpipe engine (distributed/pipeline.py) for true multi-stage
    model parallelism; this wrapper keeps the reference API — cut_list /
    place_list / concurrency_list are accepted and recorded — and
    provides the reference's execution semantics through
    `run_pipeline`: the feed batch splits into microbatches, each
    running the full (forward, backward, update) program, so parameter
    updates happen per microbatch exactly like the async reference.
    """

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30,
                 start_cpu_core_id=0, sync_steps=1):
        self._inner = optimizer
        self.cut_list = cut_list or []
        self.place_list = place_list or []
        self.concurrency_list = concurrency_list or []
        self.queue_size = queue_size
        self.sync_steps = sync_steps

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        out = self._inner.minimize(loss, startup_program=startup_program,
                                   parameter_list=parameter_list,
                                   no_grad_set=no_grad_set)
        loss.block.program._pipeline_cfg = {
            "cut_list": self.cut_list,
            "concurrency_list": self.concurrency_list,
            "sync_steps": self.sync_steps,
        }
        return out

    def run_pipeline(self, exe, program, feed, fetch_list,
                     micro_batch_num=None):
        """Run one macro-batch as `micro_batch_num` microbatches with a
        parameter update per microbatch (the reference's async pipeline
        semantics); returns the per-microbatch fetch lists."""
        import numpy as np

        m = micro_batch_num or max(
            1, max(self.concurrency_list) if self.concurrency_list else 2)
        names = list(feed)
        batch = np.asarray(feed[names[0]]).shape[0]
        if batch % m != 0:
            raise ValueError(
                f"macro batch {batch} not divisible into {m} microbatches")
        step = batch // m
        outs = []
        for i in range(m):
            micro = {n: np.asarray(feed[n])[i * step:(i + 1) * step]
                     for n in names}
            outs.append(exe.run(program, feed=micro,
                                fetch_list=fetch_list))
        return outs


# Reference-compatible aliases
SGDOptimizer = SGD
MomentumOptimizer = Momentum
LarsMomentumOptimizer = LarsMomentum
AdagradOptimizer = Adagrad
AdamOptimizer = Adam
AdamaxOptimizer = Adamax
DpsgdOptimizer = Dpsgd
DecayedAdagradOptimizer = DecayedAdagrad
AdadeltaOptimizer = Adadelta
RMSPropOptimizer = RMSProp
FtrlOptimizer = Ftrl
LambOptimizer = Lamb
