"""AST-based dygraph→static conversion.

Parity: /root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:229 (ProgramTranslator.get_func — source rewrite
+ recompile) with ifelse_transformer.py / loop_transformer.py /
break_continue_transformer.py.  The reference rewrites Python control
flow into fluid cond/while ops so a ProgramDesc can capture it; here the
rewrite targets `lax.cond` / `lax.while_loop` so data-dependent Python
control flow survives `jax.jit` tracing with BOTH branches staged —
plain jit tracing (paddle_tpu.jit.to_static) silently bakes in one
branch, which is exactly the gap this module closes.

    from paddle_tpu.jit import declarative

    @declarative
    def f(x):
        if x.sum() > 0:       # tensor condition
            y = x + 1
        else:
            y = x - 1
        while (y < 40).all(): # tensor loop
            y = y * 2
        return y

Both branches execute correctly for either sign of x.sum(), under jit.

Unconverted (left as plain Python, documented contract): constructs
containing `return`; `while`/`for` with `else`; break/continue other
than direct `if c: break`; `for` over non-range iterables.  With Python
values those behave exactly as written; with tensor conditions jax's
tracer error surfaces as before.

Autodiff contract: converted `if` (lax.cond) is reverse-differentiable;
converted tensor-bound loops (lax.while_loop) are not (JAX cannot
reverse an unbounded trip count) — jax's own error surfaces.  Loops
with Python bounds unroll at trace time and differentiate normally.
"""

import ast
import functools
import inspect
import linecache
import textwrap

from . import convert_ops
from .convert_ops import ConversionError
from .transformer import transform_function_def

__all__ = ["convert_to_static", "ast_transform_source", "ConversionError"]

_HELPERS = {
    "__jst_ifelse__": convert_ops.convert_ifelse,
    "__jst_while__": convert_ops.convert_while,
    "__jst_and__": convert_ops.convert_logical_and,
    "__jst_or__": convert_ops.convert_logical_or,
    "__jst_not__": convert_ops.convert_logical_not,
    "__jst_undef__": convert_ops._Undefined,
    "__jst_range__": convert_ops.convert_range,
    "__jst_range_cond__": convert_ops.convert_range_cond,
}

_CACHE_ATTR = "__jst_converted__"


def ast_transform_source(fn):
    """Return the transformed source text for `fn` (debugging aid,
    parity with ProgramTranslator.get_code)."""
    tree = _parse(fn)
    tree = transform_function_def(tree)
    return ast.unparse(tree)


def _parse(fn):
    src = textwrap.dedent(inspect.getsource(fn))
    tree = ast.parse(src)
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise ConversionError(f"cannot convert {fn!r}: not a plain def")
    fdef.decorator_list = []  # avoid re-triggering @declarative
    return tree


def convert_to_static(fn):
    """Rewrite `fn`'s control flow for staging and return the recompiled
    function.  Falls back to `fn` unchanged when the source is
    unavailable (builtins, lambdas, exec'd code)."""
    cached = getattr(fn, _CACHE_ATTR, None)
    if cached is not None:
        return cached
    try:
        tree = _parse(fn)
    except (OSError, TypeError, SyntaxError, ConversionError):
        return fn  # no source (lambda, builtin, exec'd): silently eager
    try:
        tree = transform_function_def(tree)
        new_fn = _recompile(fn, tree)
    except Exception as e:
        # conversion must never break previously-working code: any
        # transform/recompile failure falls back to the original
        # function — but audibly, like the reference ProgramTranslator's
        # log-and-fallback
        import warnings

        warnings.warn(
            f"dygraph_to_static conversion of "
            f"{getattr(fn, '__qualname__', fn)!r} failed "
            f"({type(e).__name__}: {e}); running unconverted",
            stacklevel=3)
        return fn
    try:
        fn.__jst_converted__ = new_fn
    except (AttributeError, TypeError):
        pass
    return new_fn


def _recompile(fn, tree):
    fdef = tree.body[0]
    fname = fdef.name
    freevars = fn.__code__.co_freevars
    filename = (f"<dygraph_to_static "
                f"{fn.__code__.co_filename}:{fn.__code__.co_firstlineno}>")

    if freevars:
        # rebuild the closure: wrap the def in a factory taking the free
        # variables, call it with the live cell contents
        factory = ast.FunctionDef(
            name="__jst_factory__",
            args=ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=v, annotation=None) for v in freevars],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[]),
            body=[fdef, ast.Return(value=ast.Name(id=fname,
                                                  ctx=ast.Load()))],
            decorator_list=[], returns=None)
        module = ast.Module(body=[factory], type_ignores=[])
    else:
        module = ast.Module(body=[fdef], type_ignores=[])
    ast.fix_missing_locations(module)

    # register the generated source so tracebacks show real lines
    source = ast.unparse(module)
    linecache.cache[filename] = (len(source), None,
                                 [l + "\n" for l in source.splitlines()],
                                 filename)

    # Execute the def against the function's REAL module globals so late
    # bindings and `global` writes keep working; the def itself lands in
    # a scratch locals dict so the module's own name is not rebound.
    # Only the mangled __jst_* helpers are injected into the module.
    fn.__globals__.update(_HELPERS)
    local_ns = {}
    code = compile(ast.parse(source), filename, "exec")
    exec(code, fn.__globals__, local_ns)
    if freevars:
        cells = [c.cell_contents for c in fn.__closure__]
        new_fn = local_ns["__jst_factory__"](*cells)
    else:
        new_fn = local_ns[fname]
    functools.update_wrapper(new_fn, fn)
    return new_fn
