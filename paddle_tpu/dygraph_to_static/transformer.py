"""AST transformers: Python control flow on tensors -> staged lax ops.

Parity: /root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
ifelse_transformer.py, loop_transformer.py and
break_continue_transformer.py.  Same rewrite shape as the reference —
branch bodies hoisted into closures returning the assigned names, loops
rewritten around a (cond_fn, body_fn, loop_vars) triple — but targeting
the jax runtime helpers in convert_ops.py instead of fluid ops.

Rewrites applied to a function body:

    if T:  A            ->  def _t(): A;  return (x, ...)
    else:  B                def _f(): B;  return (x, ...)
                            (x, ...) = __jst_ifelse__(T, _t, _f, names)

    while T: B          ->  def _c(x, ...): return T
                            def _b(x, ...): B; return (x, ...)
                            (x, ...) = __jst_while__(_c, _b, inits, names)

    for i in range(e):  ->  counter `while` with the same body

`break`/`continue` inside a `while` are eliminated first with flag
variables (the reference's BreakContinueTransformer scheme), so the
remaining tree is straight-line + if/while only.  Constructs containing
`return` are left as plain Python: early return cannot be staged, and
leaving them untouched keeps Python-value conditions working exactly as
before (a tensor condition then surfaces jax's own tracer error).
"""

import ast


def _assigned_names(stmts):
    """Names bound by a statement list, excluding nested function/class
    scopes (their locals do not escape)."""
    names = []

    def collect_target(t):
        if isinstance(t, ast.Name):
            names.append(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect_target(e)
        elif isinstance(t, ast.Starred):
            collect_target(t.value)

    def walk(node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            # generated __jst_* closures are code, not loop-carried data
            if hasattr(node, "name") and not node.name.startswith("__jst"):
                names.append(node.name)
            return
        if isinstance(node, ast.Assign):
            for t in node.targets:
                collect_target(t)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            collect_target(node.target)
        elif isinstance(node, ast.NamedExpr):
            collect_target(node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            collect_target(node.target)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if item.optional_vars is not None:
                    collect_target(item.optional_vars)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                names.append(alias.asname
                             or alias.name.split(".")[0])
        for child in ast.iter_child_nodes(node):
            walk(child)

    for s in stmts:
        walk(s)
    seen, out = set(), []
    for n in names:
        # __jst_a_/__jst_i_ capture temps are written then immediately
        # read within one statement block — never live across a branch
        # or iteration, so they must not become out/loop vars
        if n not in seen and not n.startswith(
                ("__jst_a_", "__jst_i_", "__jst_t_")):
            seen.add(n)
            out.append(n)
    return out


def _contains(stmts, kinds, stop_at_loops=False):
    """Does any statement (excluding nested function scopes, and
    optionally nested loops) contain a node of the given kinds?"""
    found = False

    def walk(node):
        nonlocal found
        if found:
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if stop_at_loops and isinstance(node, (ast.While, ast.For)):
            return
        if isinstance(node, kinds):
            found = True
            return
        for child in ast.iter_child_nodes(node):
            walk(child)

    for s in stmts:
        walk(s)
    return found


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _const(v):
    return ast.Constant(value=v)


def _call(fn_name, *args):
    return ast.Call(func=_name(fn_name), args=list(args), keywords=[])


def _capture_or_undef(tmp, var):
    """try: tmp = var\nexcept NameError: tmp = __jst_undef__(var_name)"""
    return ast.Try(
        body=[ast.Assign(targets=[_name(tmp, ast.Store())],
                         value=_name(var))],
        handlers=[ast.ExceptHandler(
            type=_name("NameError"), name=None,
            body=[ast.Assign(
                targets=[_name(tmp, ast.Store())],
                value=_call("__jst_undef__", _const(var)))])],
        orelse=[], finalbody=[])


def _tuple_of(names, ctx=None):
    return ast.Tuple(elts=[_name(n, ctx or ast.Load()) for n in names],
                     ctx=ctx or ast.Load())


class BreakContinueTransformer(ast.NodeTransformer):
    """Eliminate `break`/`continue` from `while` bodies via flag
    variables so the loop can be staged.  Only the directly-nested
    `if X: break` / `if X: continue` pattern (arbitrary position, no
    else) is rewritten; loops with other uses are marked to stay
    Python (`_jst_skip`)."""

    def __init__(self):
        self._n = 0

    def visit_FunctionDef(self, node):
        self.generic_visit(node)
        return node

    def visit_While(self, node):
        self.generic_visit(node)
        if not _contains(node.body, (ast.Break, ast.Continue),
                         stop_at_loops=True):
            return node
        if node.orelse or not self._supported(node.body):
            # while/else: `else` must be skipped when the loop breaks —
            # flag elimination would always run it. Stay Python.
            node._jst_skip = True
            return node
        self._n += 1
        brk = f"__jst_brk_{self._n}"
        cont = f"__jst_cont_{self._n}"
        used_brk, used_cont, new_body = self._rewrite(node.body, brk, cont)
        out = []
        if used_cont:
            new_body.insert(0, ast.Assign(
                targets=[_name(cont, ast.Store())], value=_const(False)))
        if used_brk:
            out.append(ast.Assign(targets=[_name(brk, ast.Store())],
                                  value=_const(False)))
            # `not brk` first: after a break Python never re-evaluates
            # the loop test, so ours must short-circuit before it too
            node.test = _call(
                "__jst_and__",
                ast.Lambda(args=_no_args(),
                           body=_call("__jst_not__", _name(brk))),
                ast.Lambda(args=_no_args(), body=node.test))
        node.body = new_body
        out.append(node)
        return out

    def visit_For(self, node):
        # `for` has no test to splice a break flag into; loops using
        # break/continue stay Python (the range conversion skips them)
        self.generic_visit(node)
        if _contains(node.body, (ast.Break, ast.Continue),
                     stop_at_loops=True):
            node._jst_skip = True
        return node

    def _supported(self, body):
        """break/continue must be the direct `if X: break` pattern at
        the top level of the loop body, with no else."""
        for s in body:
            if (isinstance(s, ast.If) and len(s.body) == 1
                    and not s.orelse
                    and isinstance(s.body[0], (ast.Break, ast.Continue))):
                continue
            if isinstance(s, (ast.While, ast.For,
                              ast.FunctionDef, ast.ClassDef)):
                continue  # inner loops/scopes own their breaks
            for sub in ast.walk(s):
                if isinstance(sub, (ast.Break, ast.Continue)):
                    return False
        return True

    def _rewrite(self, body, brk, cont):
        used_brk = used_cont = False
        new = []
        guard = None  # accumulated active flags
        for s in body:
            if (isinstance(s, ast.If) and len(s.body) == 1
                    and not s.orelse
                    and isinstance(s.body[0], (ast.Break, ast.Continue))):
                is_break = isinstance(s.body[0], ast.Break)
                flag = brk if is_break else cont
                used_brk |= is_break
                used_cont |= not is_break
                setter = ast.If(
                    test=s.test,
                    body=[ast.Assign(targets=[_name(flag, ast.Store())],
                                     value=_const(True))],
                    orelse=[])
                new.append(self._guarded(setter, guard))
                guard = (_call("__jst_and__",
                               ast.Lambda(args=_no_args(), body=guard),
                               ast.Lambda(args=_no_args(),
                                          body=_skip_test(flag)))
                         if guard is not None else _skip_test(flag))
            else:
                new.append(self._guarded(s, guard))
        # collapse consecutive same-guard statements into one if
        return used_brk, used_cont, _merge_guards(new)

    def _guarded(self, stmt, guard):
        if guard is None:
            return stmt
        import copy

        g = ast.If(test=copy.deepcopy(guard), body=[stmt], orelse=[])
        g._jst_guard = ast.dump(guard)
        return g


def _skip_test(flag):
    return _call("__jst_not__", _name(flag))


def _merge_guards(stmts):
    out = []
    for s in stmts:
        tag = getattr(s, "_jst_guard", None)
        if (tag is not None and out
                and getattr(out[-1], "_jst_guard", None) == tag):
            out[-1].body.extend(s.body)
        else:
            out.append(s)
    return out


def _no_args():
    return ast.arguments(posonlyargs=[], args=[], vararg=None,
                         kwonlyargs=[], kw_defaults=[], kwarg=None,
                         defaults=[])


class ControlFlowTransformer(ast.NodeTransformer):
    """if/while/for(range) -> __jst_ifelse__/__jst_while__ call sites."""

    def __init__(self):
        self._n = 0

    def _next(self):
        self._n += 1
        return self._n

    # -- tests: rewrite `and`/`or`/`not` so tensor operands never hit
    # Python bool()
    def _rewrite_test(self, node):
        if isinstance(node, ast.BoolOp):
            fn = ("__jst_and__" if isinstance(node.op, ast.And)
                  else "__jst_or__")
            expr = self._rewrite_test(node.values[-1])
            for v in reversed(node.values[:-1]):
                expr = _call(fn,
                             ast.Lambda(args=_no_args(),
                                        body=self._rewrite_test(v)),
                             ast.Lambda(args=_no_args(), body=expr))
            return expr
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            return _call("__jst_not__",
                         self._rewrite_test(node.operand))
        return node

    def visit_If(self, node):
        self.generic_visit(node)
        if _contains(node.body + node.orelse,
                     (ast.Return, ast.Global, ast.Nonlocal)):
            return node  # early return / scope decls: keep Python
        if _contains(node.body + node.orelse,
                     (ast.Break, ast.Continue), stop_at_loops=True):
            # break/continue belonging to an unconverted enclosing loop
            # must stay syntactically inside that loop
            return node
        n = self._next()
        out_vars = _assigned_names(node.body + node.orelse)
        true_name, false_name = f"__jst_true_{n}", f"__jst_false_{n}"

        # out_vars enter the branch closures as PARAMETERS: a branch
        # assigning `y = y + 1` then reads its own bound local, and a
        # branch not assigning `y` returns the incoming value unchanged
        def branch(name, body):
            args = ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=v, annotation=None) for v in out_vars],
                vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
                defaults=[])
            return ast.FunctionDef(
                name=name, args=args,
                body=(body or [ast.Pass()])
                + [ast.Return(value=_tuple_of(out_vars))],
                decorator_list=[], returns=None)

        # hoist the test ahead of the captures: a walrus in the test
        # (`if (y := f()) > 0:`) must bind y before y's value is
        # captured for the branches
        test_tmp = f"__jst_t_{n}"
        hoist = ast.Assign(targets=[_name(test_tmp, ast.Store())],
                           value=self._rewrite_test(node.test))
        inits = []
        init_tmps = []
        for i, v in enumerate(out_vars):
            tmp = f"__jst_a_{n}_{i}"
            init_tmps.append(tmp)
            inits.append(_capture_or_undef(tmp, v))
        call = _call("__jst_ifelse__", _name(test_tmp),
                     _name(true_name), _name(false_name),
                     _tuple_of(init_tmps),
                     ast.Tuple(elts=[_const(v) for v in out_vars],
                               ctx=ast.Load()))
        if out_vars:
            site = ast.Assign(
                targets=[_tuple_of(out_vars, ast.Store())], value=call)
        else:
            site = ast.Expr(value=call)
        return ([branch(true_name, node.body),
                 branch(false_name, node.orelse), hoist]
                + inits + [site])

    def visit_While(self, node):
        # always visit children first: even when this loop itself stays
        # Python, convertible tensor control flow nested inside it must
        # still be rewritten (visit_If keeps break/continue-bearing ifs
        # intact, so an unconverted loop keeps its breaks)
        self.generic_visit(node)
        if getattr(node, "_jst_skip", False):
            return node  # unsupported break/continue: stay Python
        if node.orelse or _contains([node.test], (ast.NamedExpr,)):
            # while/else stays Python; a walrus in the test binds a name
            # the body reads — hoisting it into cond_fn would localize it
            return node
        if _contains(node.body,
                     (ast.Return, ast.Global, ast.Nonlocal)):
            return node
        n = self._next()
        loop_vars = _assigned_names(node.body)
        if not loop_vars:
            return node  # nothing carried; cannot terminate on tensors
        cond_name, body_name = f"__jst_cond_{n}", f"__jst_body_{n}"
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=v, annotation=None) for v in loop_vars],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[])
        cond_def = ast.FunctionDef(
            name=cond_name, args=args,
            body=[ast.Return(value=self._rewrite_test(node.test))],
            decorator_list=[], returns=None)
        body_def = ast.FunctionDef(
            name=body_name, args=args,
            body=node.body + [ast.Return(value=_tuple_of(loop_vars))],
            decorator_list=[], returns=None)
        inits = []
        init_tmps = []
        for i, v in enumerate(loop_vars):
            tmp = f"__jst_i_{n}_{i}"
            init_tmps.append(tmp)
            inits.append(_capture_or_undef(tmp, v))
        site = ast.Assign(
            targets=[_tuple_of(loop_vars, ast.Store())],
            value=_call("__jst_while__", _name(cond_name),
                        _name(body_name), _tuple_of(init_tmps),
                        ast.Tuple(elts=[_const(v) for v in loop_vars],
                                  ctx=ast.Load())))
        return [cond_def, body_def] + inits + [site]

    def visit_For(self, node):
        if (getattr(node, "_jst_skip", False) or node.orelse
                or not (isinstance(node.iter, ast.Call)
                        and isinstance(node.iter.func, ast.Name)
                        and node.iter.func.id == "range"
                        and not node.iter.keywords
                        and isinstance(node.target, ast.Name))
                or _contains(node.body,
                             (ast.Return, ast.Global, ast.Nonlocal))
                or _contains(node.body, (ast.Break, ast.Continue),
                             stop_at_loops=True)):
            # loop stays Python, but nested constructs still convert
            self.generic_visit(node)
            return node
        n = self._next()
        it, start, stop, step = (f"__jst_it_{n}", f"__jst_start_{n}",
                                 f"__jst_stop_{n}", f"__jst_step_{n}")
        header = ast.Assign(
            targets=[ast.Tuple(elts=[_name(start, ast.Store()),
                                     _name(stop, ast.Store()),
                                     _name(step, ast.Store())],
                               ctx=ast.Store())],
            value=_call("__jst_range__", *node.iter.args))
        init = ast.Assign(targets=[_name(it, ast.Store())],
                          value=_name(start))
        # i = _it; body; _it = _it + step   (target reassignment inside
        # the body does not perturb the iteration, matching `for`)
        body = ([ast.Assign(targets=[ast.Name(id=node.target.id,
                                              ctx=ast.Store())],
                            value=_name(it))]
                + node.body
                + [ast.Assign(
                    targets=[_name(it, ast.Store())],
                    value=ast.BinOp(left=_name(it), op=ast.Add(),
                                    right=_name(step)))])
        loop = ast.While(
            test=_call("__jst_range_cond__", _name(it), _name(stop),
                       _name(step)),
            body=body, orelse=[])
        converted = self.visit_While(loop)
        converted = (converted if isinstance(converted, list)
                     else [converted])
        return [header, init] + converted


def transform_function_def(tree):
    """Apply the full pipeline to a Module containing one FunctionDef."""
    tree = BreakContinueTransformer().visit(tree)
    tree = ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(tree)
    return tree
