"""Runtime conversion helpers targeted by the AST transformer.

Parity: /root/reference/python/paddle/fluid/dygraph/dygraph_to_static/
convert_operators.py (convert_ifelse / convert_while_loop /
convert_logical_*).  The reference dispatches on Variable vs Python
value and builds fluid control-flow ops; the TPU-native dispatch is on
jax.Array / tracer vs Python value and builds `lax.cond` /
`lax.while_loop`, so the converted function stays fully jittable while
plain-Python conditions keep exact Python semantics (including short
circuit and one-branch execution).
"""

import jax
import jax.numpy as jnp


class ConversionError(RuntimeError):
    """A converted construct cannot be staged on a tensor condition."""


class _Undefined:
    """Placeholder for a name not bound on some path (the reference's
    RETURN_NO_VALUE sentinel).  Any real use raises, so silently-wrong
    values can never flow out of a converted branch."""

    __slots__ = ("name",)

    def __init__(self, name="<var>"):
        self.name = name

    def __repr__(self):
        return f"<undefined variable {self.name!r}>"

    def _raise(self, *a, **k):
        raise ConversionError(
            f"variable {self.name!r} is undefined on this control-flow "
            f"path (define it before the if/while so both paths bind it)")

    __bool__ = __call__ = __getattr__ = __getitem__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _raise
    __truediv__ = __rtruediv__ = __lt__ = __le__ = __gt__ = __ge__ = _raise
    __iter__ = __len__ = __neg__ = __matmul__ = __rmatmul__ = _raise


def _is_tensor(x):
    return isinstance(x, (jax.Array, jax.core.Tracer))


def _as_pred(x, what):
    x = jnp.asarray(x)
    if x.size != 1:
        raise ConversionError(
            f"{what} must be a scalar, got shape {x.shape}; reduce it "
            f"(e.g. .any()/.all()) first")
    return x.reshape(()).astype(bool)


def convert_ifelse(pred, true_fn, false_fn, init, names):
    """`if pred:` with tensor pred -> lax.cond (both branches staged);
    Python pred -> run exactly one branch.  `init` holds the incoming
    values of the branch-assigned variables (branch closures take them
    as parameters and return their final values)."""
    if not _is_tensor(pred):
        return true_fn(*init) if pred else false_fn(*init)
    try:
        # init rides the closures, not cond operands: an _Undefined
        # placeholder only raises if the staged branch actually uses it
        return jax.lax.cond(_as_pred(pred, "if condition"),
                            lambda: true_fn(*init),
                            lambda: false_fn(*init))
    except ConversionError:
        raise
    except (TypeError, ValueError) as e:
        missing = _diagnose_undef(names, init, true_fn, false_fn)
        if missing:
            raise ConversionError(
                f"if-condition is a tensor, so both branches are staged "
                f"with lax.cond and must bind the same variables with "
                f"matching shape/dtype; {missing}") from e
        raise ConversionError(
            f"branches of a tensor `if` must return matching "
            f"shapes/dtypes for {list(names)}: {e}") from e


def _diagnose_undef(names, init, *fns):
    # failure path only: run each branch once to find which names come
    # back undefined (fn returns plain tuples, so no staging needed)
    notes = []
    for which, fn in zip(("true", "false"), fns):
        try:
            out = fn(*init)
        except ConversionError as e:
            notes.append(f"{which}-branch: {e}")
            continue
        except Exception:
            continue
        for name, v in zip(names, out):
            if isinstance(v, _Undefined):
                notes.append(f"{name!r} is not bound on the "
                             f"{which}-branch")
    return "; ".join(notes)


def convert_while(cond_fn, body_fn, init, names):
    """`while cond:` with tensor cond -> lax.while_loop over the
    assigned-in-body variables as loop carry; Python cond -> plain
    Python loop (body still runs through body_fn, semantics identical)."""
    c = cond_fn(*init)
    if not _is_tensor(c):
        vals = tuple(init)
        while c:
            vals = tuple(body_fn(*vals))
            c = cond_fn(*vals)
        return vals

    init = _concretize_undef_init(body_fn, init, names)
    try:
        return jax.lax.while_loop(
            lambda vs: _as_pred(cond_fn(*vs), "while condition"),
            lambda vs: tuple(body_fn(*vs)),
            tuple(init))
    except ConversionError:
        raise
    except (TypeError, ValueError) as e:
        raise ConversionError(
            f"while-condition is a tensor, so the loop is staged with "
            f"lax.while_loop and the loop variables {list(names)} must "
            f"keep fixed shape/dtype across iterations: {e}") from e


def _concretize_undef_init(body_fn, init, names):
    """Loop variables first *written* inside the body may be undefined at
    loop entry.  One abstract trace of the body proves they are never
    read before written (reading an _Undefined raises), and yields their
    steady-state avals so they can enter the carry as zeros."""
    if not any(isinstance(v, _Undefined) for v in init):
        return init
    try:
        out = jax.eval_shape(lambda _: tuple(body_fn(*init)), 0)
    except ConversionError as e:
        raise ConversionError(
            f"while-condition is a tensor but a loop variable is read "
            f"before it is written and not defined before the loop: {e}"
        ) from e
    return tuple(
        jnp.zeros(o.shape, o.dtype) if isinstance(v, _Undefined) else v
        for v, o in zip(init, out))


def convert_logical_and(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if _is_tensor(lhs):
        return jnp.logical_and(lhs, rhs_fn())
    return lhs and rhs_fn()       # Python short-circuit preserved


def convert_logical_or(lhs_fn, rhs_fn):
    lhs = lhs_fn()
    if _is_tensor(lhs):
        return jnp.logical_or(lhs, rhs_fn())
    return lhs or rhs_fn()


def convert_logical_not(x):
    if _is_tensor(x):
        return jnp.logical_not(x)
    return not x


def convert_range(*args):
    """start/stop/step triple for a converted `for i in range(...)`;
    any argument may be a tensor."""
    if len(args) == 1:
        return 0, args[0], 1
    if len(args) == 2:
        return args[0], args[1], 1
    return args


def convert_range_cond(i, stop, step):
    """Python range termination: i < stop for step > 0, i > stop for
    step < 0 — on tensors this stays a tensor predicate."""
    if _is_tensor(i) or _is_tensor(stop) or _is_tensor(step):
        return jnp.where(jnp.asarray(step) > 0, jnp.asarray(i) < stop,
                         jnp.asarray(i) > stop)
    return i < stop if step > 0 else i > stop
