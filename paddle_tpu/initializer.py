"""`fluid.initializer` import-path compatibility.

Parity: python/paddle/fluid/initializer.py (Constant :86, Uniform
:161, Normal :268, TruncatedNormal :351, Xavier :432, MSRA :564,
NumpyArray :822) — implementation in framework/initializer.py.

`init_on_cpu`/`force_init_on_cpu` are placement hints in the
reference; under XLA, initializer placement is the compiler's
decision, so the context is an honest no-op kept for script parity.
"""

import contextlib

from .framework.initializer import (  # noqa: F401
    Bilinear, BilinearInitializer, Constant, ConstantInitializer,
    Initializer, MSRA, MSRAInitializer, Normal, NormalInitializer,
    NumpyArrayInitializer, TruncatedNormal, TruncatedNormalInitializer,
    Uniform, UniformInitializer, Xavier, XavierInitializer)

__all__ = [
    "Constant", "Uniform", "Normal", "TruncatedNormal", "Xavier",
    "Bilinear", "MSRA", "NumpyArrayInitializer", "force_init_on_cpu",
    "init_on_cpu",
]

_force_init_on_cpu = False


def force_init_on_cpu():
    """initializer.py parity — reads the flag set by init_on_cpu()."""
    return _force_init_on_cpu


@contextlib.contextmanager
def init_on_cpu():
    """initializer.py parity — placement hint; XLA decides placement,
    so only the flag round-trip is kept."""
    global _force_init_on_cpu
    prev = _force_init_on_cpu
    _force_init_on_cpu = True
    try:
        yield
    finally:
        _force_init_on_cpu = prev
