"""`fluid.data_feed_desc` import-path compatibility.

Parity: python/paddle/fluid/data_feed_desc.py (DataFeedDesc :21):
describes the MultiSlot input format.  The reference parses a
data_feed.proto text message; this implementation parses the same
prototxt surface with a small recursive reader (no protobuf
runtime), exposing the documented mutators and a `desc()` that
re-serializes, and feeds the same slot schema the native MultiSlot
reader (csrc/data_feed.cpp) consumes.
"""

__all__ = ["DataFeedDesc"]


def _parse_prototxt(text):
    """Minimal prototxt reader for the data_feed.proto shape:
    scalar fields (`name: "x"`, `batch_size: 2`) and repeated/nested
    messages (`multi_slot_desc { slots { ... } }`)."""
    import re
    tokens = re.findall(r'[{}]|[A-Za-z_]\w*\s*:\s*(?:"[^"]*"|[^\s}]+)|'
                        r'[A-Za-z_]\w*(?=\s*\{)', text)
    pos = 0

    def parse_block():
        nonlocal pos
        msg = {}
        while pos < len(tokens):
            tok = tokens[pos]
            if tok == "}":
                pos += 1
                return msg
            pos += 1
            if ":" in tok:
                key, _, raw = tok.partition(":")
                key, raw = key.strip(), raw.strip()
                if raw.startswith('"'):
                    val = raw[1:-1]
                elif raw in ("true", "false"):
                    val = raw == "true"
                else:
                    try:
                        val = int(raw)
                    except ValueError:
                        val = float(raw)
                msg[key] = val
            else:
                assert tokens[pos] == "{", "expected { after %s" % tok
                pos += 1
                sub = parse_block()
                if tok == "slots":
                    msg.setdefault(tok, []).append(sub)
                else:
                    msg[tok] = sub
        return msg

    return parse_block()


def _emit(msg, indent=0):
    pad = "  " * indent
    out = []
    for key, val in msg.items():
        if isinstance(val, dict):
            out.append("%s%s {" % (pad, key))
            out.append(_emit(val, indent + 1))
            out.append("%s}" % pad)
        elif isinstance(val, list):
            for item in val:
                out.append("%s%s {" % (pad, key))
                out.append(_emit(item, indent + 1))
                out.append("%s}" % pad)
        elif isinstance(val, bool):
            out.append("%s%s: %s" % (pad, key, "true" if val else "false"))
        elif isinstance(val, str):
            out.append('%s%s: "%s"' % (pad, key, val))
        else:
            out.append("%s%s: %s" % (pad, key, val))
    return "\n".join(out)


class DataFeedDesc:
    def __init__(self, proto_file):
        with open(proto_file) as f:
            self.proto_desc = _parse_prototxt(f.read())
        self._name_to_idx = {}
        if self.proto_desc.get("name") == "MultiSlotDataFeed":
            slots = self.proto_desc.get("multi_slot_desc", {}) \
                .get("slots", [])
            self._name_to_idx = {s["name"]: i for i, s in enumerate(slots)}

    def _slots(self):
        if not self._name_to_idx:
            raise ValueError("only MultiSlotDataFeed descs have slots")
        return self.proto_desc["multi_slot_desc"]["slots"]

    def set_batch_size(self, batch_size):
        self.proto_desc["batch_size"] = batch_size

    def set_dense_slots(self, dense_slots_name):
        slots = self._slots()
        for name in dense_slots_name:
            slots[self._name_to_idx[name]]["is_dense"] = True

    def set_use_slots(self, use_slots_name):
        slots = self._slots()
        for name in use_slots_name:
            slots[self._name_to_idx[name]]["is_used"] = True

    def desc(self):
        return _emit(self.proto_desc) + "\n"
