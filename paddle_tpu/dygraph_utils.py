"""`fluid.dygraph_utils` import-path compatibility.

Parity: python/paddle/fluid/dygraph_utils.py
(_append_activation_in_dygraph :20, _append_bias_in_dygraph :48):
helpers the reference's generated `core.ops.*` fast path uses to
tack an activation / bias onto an eager op result.  The cudnn/mkldnn
toggles have no TPU meaning and are accepted and ignored.
"""

from . import nn

__all__ = []

_ACTS = {
    "relu": nn.functional.relu,
    "relu6": nn.functional.relu6,
    "sigmoid": nn.functional.sigmoid,
    "tanh": nn.functional.tanh,
    "softmax": nn.functional.softmax,
    "leaky_relu": nn.functional.leaky_relu,
    "elu": nn.functional.elu,
    "gelu": nn.functional.gelu,
    "softplus": nn.functional.softplus,
    "swish": nn.functional.swish,
    "hard_sigmoid": nn.functional.hard_sigmoid,
    "hard_swish": nn.functional.hard_swish,
}


def _append_activation_in_dygraph(input, act=None, use_cudnn=None,
                                  use_mkldnn=None):
    if act is None:
        return input
    if act not in _ACTS:
        raise ValueError("unsupported activation %r" % act)
    return _ACTS[act](input)


def _append_bias_in_dygraph(input, bias=None, axis=1):
    if bias is None:
        return input
    # elementwise_add(axis) semantics: align bias dims starting at
    # `axis`; axis=-1 means trailing alignment (rank(x) - rank(bias))
    ndim = len(input.shape)
    bshape = list(bias.shape)
    if axis == -1:
        axis = ndim - len(bshape)
    if not 0 <= axis <= ndim - len(bshape):
        raise ValueError("bias of rank %d cannot align at axis %d of a "
                         "rank-%d input" % (len(bshape), axis, ndim))
    new_shape = [1] * axis + bshape + [1] * (ndim - axis - len(bshape))
    return input + bias.reshape(*new_shape)
